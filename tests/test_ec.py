"""Erasure coding: RS bit-matrix kernels vs the GF(2^8) oracle, cell-striping
layout, and cluster end-to-end (write striped, degraded read, NN-scheduled
reconstruction) — the capability surface of the reference's EC stack
(DFSStripedOutputStream.java:81, StripedBlockUtil, ErasureCodingWorker.java:46)."""

import time

import numpy as np
import pytest

from hdrf_tpu.client.striped import assemble, layout_shards
from hdrf_tpu.ops import rs


class TestRsKernels:
    def test_encode_matches_gf_oracle(self):
        rng = np.random.default_rng(0)
        for k, m in [(3, 2), (6, 3), (10, 4)]:
            data = rng.integers(0, 256, size=(k, 2048), dtype=np.uint8)
            np.testing.assert_array_equal(rs.rs_encode(data, k, m),
                                          rs.encode_ref(data, m))

    def test_decode_all_erasure_patterns(self):
        rng = np.random.default_rng(1)
        k, m = 4, 2
        data = rng.integers(0, 256, size=(k, 512), dtype=np.uint8)
        parity = rs.rs_encode(data, k, m)
        full = {i: data[i] for i in range(k)} | {k + i: parity[i]
                                                 for i in range(m)}
        import itertools
        for lost in itertools.combinations(range(k + m), m):
            shards = {i: v for i, v in full.items() if i not in lost}
            rec = rs.rs_decode(shards, k, m, want=list(lost))
            for idx in lost:
                np.testing.assert_array_equal(rec[idx], full[idx])

    def test_too_many_erasures_raises(self):
        rng = np.random.default_rng(2)
        k, m = 3, 2
        data = rng.integers(0, 256, size=(k, 64), dtype=np.uint8)
        parity = rs.rs_encode(data, k, m)
        shards = {0: data[0], 3: parity[0]}  # only 2 of 3 needed
        with pytest.raises(ValueError):
            rs.rs_decode(shards, k, m, want=[1])

    def test_policy_parse(self):
        assert rs.parse_policy("rs-6-3-64k") == (6, 3, 65536)
        with pytest.raises(ValueError):
            rs.parse_policy("xor-2-1-64k")


class TestStriping:
    def test_layout_roundtrip(self):
        rng = np.random.default_rng(3)
        for n in [0, 1, 100, 1024, 5000, 65536 * 3 + 17]:
            data = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
            shards = layout_shards(data, k=3, cell=1024)
            got = assemble({i: shards[i] for i in range(3)}, 3, 1024, n)
            assert got == data


@pytest.fixture
def ec_cluster():
    from hdrf_tpu.testing.minicluster import MiniCluster

    with MiniCluster(n_datanodes=5, block_size=64 * 1024) as mc:
        yield mc


class TestEcCluster:
    POLICY = "rs-3-2-4k"

    def test_striped_write_read(self, ec_cluster):
        rng = np.random.default_rng(4)
        data = rng.integers(0, 256, size=300_000, dtype=np.uint8).tobytes()
        with ec_cluster.client("ec1") as c:
            c.write("/ec/f", data, ec=self.POLICY)
            st = c.stat("/ec/f")
            assert st["ec"] == self.POLICY and st["length"] == len(data)
            assert c.read("/ec/f") == data
            # ranged read crossing cells
            assert c.read("/ec/f", offset=4000, length=9000) == data[4000:13000]

    def test_degraded_read_after_dn_loss(self, ec_cluster):
        rng = np.random.default_rng(5)
        data = rng.integers(0, 256, size=150_000, dtype=np.uint8).tobytes()
        with ec_cluster.client("ec2") as c:
            c.write("/ec/g", data, ec=self.POLICY)
            # kill two DNs (m=2 tolerance)
            ec_cluster.stop_datanode(0)
            ec_cluster.stop_datanode(1)
            assert c.read("/ec/g") == data

    def test_nn_schedules_reconstruction(self, ec_cluster):
        rng = np.random.default_rng(6)
        data = rng.integers(0, 256, size=100_000, dtype=np.uint8).tobytes()
        with ec_cluster.client("ec3") as c:
            c.write("/ec/h", data, ec=self.POLICY)
            loc = c._nn.call("get_block_locations", path="/ec/h")
            # find a DN hosting a shard of the first group and kill it
            victim = loc["groups"][0]["blocks"][0]["locations"][0]["dn_id"]
            idx = int(victim.split("-")[1])
            ec_cluster.kill_datanode(idx)
            # wait for dead-node detection + reconstruction + IBR
            deadline = time.monotonic() + 20
            bid = loc["groups"][0]["blocks"][0]["block_id"]
            while time.monotonic() < deadline:
                loc2 = c._nn.call("get_block_locations", path="/ec/h")
                b0 = loc2["groups"][0]["blocks"][0]
                if b0["block_id"] == bid and b0["locations"]:
                    break
                time.sleep(0.2)
            else:
                pytest.fail("shard not reconstructed within deadline")
            assert c.read("/ec/h") == data
