"""Pallas bitonic sort kernel: bit-identity against ``jax.lax.sort``.

The kernel (ops/sort_pallas.py) replaces every ``lax.sort`` site of the LZ4
match scan, so its contract is exact: on rows whose keys are unique (all the
live call sites salt keys with position) the network must produce the SAME
permutation as ``jax.lax.sort`` — not merely a sorted one.  The CPU test
mesh cannot run Mosaic kernels, so the network itself executes through the
Pallas interpreter (``interpret=True``), which exercises the identical
kernel program the TPU compiles.  The interpreter pays about a minute per
full-width network, so tier-1 runs the smallest kernel width (1024 = the
_MIN_E floor) and the production widths ride the ``slow`` marker.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hdrf_tpu.ops import sort_pallas

RNG = np.random.default_rng(7)


def _lax_rows(key, *vals):
    return jax.lax.sort((key, *vals), dimension=1, num_keys=1)


def _unique_keys(t, e, dtype):
    key = np.stack([RNG.permutation(e).astype(np.int64) for _ in range(t)])
    if dtype == np.uint32:
        return (key + 0xFFFF0000 - e // 2).astype(np.uint32)  # wraps sign bit
    return (key - e // 2).astype(np.int32)                    # negatives


def _assert_bit_identical(e, dtype):
    t = 3
    key = _unique_keys(t, e, dtype)
    v1 = RNG.integers(0, 2**32, size=(t, e), dtype=np.uint32)
    v2 = RNG.integers(-2**31, 2**31, size=(t, e)).astype(np.int32)
    got = sort_pallas.sort_rows(jnp.asarray(key), jnp.asarray(v1),
                                jnp.asarray(v2), impl="pallas",
                                interpret=True)
    want = _lax_rows(jnp.asarray(key), jnp.asarray(v1), jnp.asarray(v2))
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


class TestSortRows:
    @pytest.mark.parametrize("dtype", [np.int32, np.uint32])
    def test_unique_keys_bit_identical(self, dtype):
        _assert_bit_identical(1024, dtype)

    @pytest.mark.slow
    @pytest.mark.parametrize("e", [2048, 8192])
    @pytest.mark.parametrize("dtype", [np.int32, np.uint32])
    def test_unique_keys_bit_identical_full_width(self, e, dtype):
        _assert_bit_identical(e, dtype)

    def test_ties_stay_sorted_and_values_are_a_permutation(self):
        # Duplicate keys: the network is unstable, so only assert key order
        # plus KV-pair multiset preservation.
        t, e = 2, 1024
        key = RNG.integers(0, 16, size=(t, e), dtype=np.int32)
        val = np.arange(t * e, dtype=np.int32).reshape(t, e)
        sk, sv = sort_pallas.sort_rows(jnp.asarray(key), jnp.asarray(val),
                                       impl="pallas", interpret=True)
        sk, sv = np.asarray(sk), np.asarray(sv)
        assert (np.diff(sk, axis=1) >= 0).all()
        for r in range(t):
            assert sorted(zip(sk[r], sv[r])) == sorted(zip(key[r], val[r]))

    def test_non_pow2_rows_pad_to_sentinel(self):
        t, e = 2, 1500  # pads to 2048 — the L2/L3 pack-sort shape
        key = np.stack([RNG.permutation(e).astype(np.int32)
                        for _ in range(t)])
        val = RNG.integers(0, 2**31, size=(t, e), dtype=np.int32)
        inv = np.int32(2**31 - 1)
        sk, sv = sort_pallas.sort_rows(
            jnp.asarray(key), jnp.asarray(val), impl="pallas",
            interpret=True, pad_key=inv, pad_vals=(np.int32(0),))
        sk, sv = np.asarray(sk), np.asarray(sv)
        assert sk.shape == (t, 2048)
        wk, wv = _lax_rows(jnp.asarray(key), jnp.asarray(val))
        np.testing.assert_array_equal(sk[:, :e], np.asarray(wk))
        np.testing.assert_array_equal(sv[:, :e], np.asarray(wv))
        assert (sk[:, e:] == inv).all()

    def test_xla_fallback_below_min_e(self):
        # e < _MIN_E silently takes lax.sort even when pallas is requested.
        key = RNG.permutation(256).astype(np.int32)[None]
        val = np.arange(256, dtype=np.int32)[None]
        got = sort_pallas.sort_rows(jnp.asarray(key), jnp.asarray(val),
                                    impl="pallas", interpret=True)
        want = _lax_rows(jnp.asarray(key), jnp.asarray(val))
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))

    def test_env_override_disables_pallas(self, monkeypatch):
        monkeypatch.setenv("HDRF_SORT_PALLAS", "0")
        assert not sort_pallas.use_pallas()

    def test_cpu_backend_defaults_to_xla(self):
        # The test mesh is XLA:CPU, so the default dispatch must not try a
        # compiled Mosaic kernel (which CPU rejects outright).
        assert jax.default_backend() != "tpu"
        assert not sort_pallas.use_pallas()
        key = np.stack([RNG.permutation(4096).astype(np.int32)])
        val = np.zeros((1, 4096), np.int32)
        sk, _ = sort_pallas.sort_rows(jnp.asarray(key), jnp.asarray(val))
        assert (np.diff(np.asarray(sk), axis=1) > 0).all()


def _gram_image(data, stride, e):
    """4-gram little-endian words at stride-aligned positions — the same
    image _match_scan_impl feeds the delta pipeline."""
    rows = []
    for r in range(data.shape[0]):
        b = np.concatenate([data[r], np.zeros(4, np.uint8)])
        w = (b[:-4].astype(np.uint32) | (b[1:-3].astype(np.uint32) << 8)
             | (b[2:-2].astype(np.uint32) << 16)
             | (b[3:-1].astype(np.uint32) << 24))
        rows.append(w[::stride][:e])
    return jnp.asarray(np.stack(rows))


def _posn(t, e, stride):
    if stride == 2:
        idx = np.arange(e)
        p = np.where(idx < e // 2, 2 * idx, 2 * (idx - e // 2) + 1)
    else:
        p = np.arange(e)
    return jnp.asarray(p.astype(np.uint32))[None].repeat(t, axis=0)


def _corpus(name, t, n):
    if name == "text":
        data = RNG.integers(97, 123, size=(t, n), dtype=np.uint8)
        data[:, ::3] = 32
        return data
    if name == "zeros":
        return np.zeros((t, n), np.uint8)
    return RNG.integers(0, 256, size=(t, n), dtype=np.uint8)


def _assert_deltas_match(stride, corpus, e):
    t = 2
    data = _corpus(corpus, t, e * stride)
    vals = _gram_image(data, stride, e)
    pos_bits = int(e - 1).bit_length()
    want = sort_pallas.match_deltas_xla(vals, _posn(t, e, stride), stride,
                                        pos_bits)
    got = sort_pallas.match_deltas(vals, _posn(t, e, stride), stride,
                                   pos_bits, impl="pallas", interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestMatchDeltas:
    @pytest.mark.parametrize("stride,corpus", [(2, "text"), (4, "random")])
    def test_fused_kernel_matches_xla_reference(self, stride, corpus):
        _assert_deltas_match(stride, corpus, 1024)

    @pytest.mark.slow
    @pytest.mark.parametrize("stride", [2, 4])
    @pytest.mark.parametrize("corpus", ["text", "zeros", "random"])
    def test_fused_kernel_matches_xla_reference_full_width(self, stride,
                                                           corpus):
        _assert_deltas_match(stride, corpus, 4096)

    def test_dispatcher_falls_back_off_tpu(self):
        e = 2048
        vals = jnp.asarray(RNG.integers(0, 2**32, size=(1, e),
                                        dtype=np.uint32))
        posn = jnp.asarray(np.arange(e, dtype=np.uint32))[None]
        got = sort_pallas.match_deltas(vals, posn, 4, 11)
        want = sort_pallas.match_deltas_xla(vals, posn, 4, 11)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
