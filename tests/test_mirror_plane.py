"""Coded mirror plane: k-of-n reduced mirroring with hedged legs
(server/mirror_plane.py), so a dead or straggling mirror never stalls
a write.

Covers the fan-out scheduling and reconciliation semantics the serial
relay chain of the reference lacks (DataStreamer.java:765 forwards hop
by hop; BlockReceiver.java:635-641 fate-shares the ack with the
slowest mirror) re-expressed as RS-coded segments (ops/rs.py:181
Cauchy bit-matmul) with tied-request hedging (utils/retry.py:194
hedged_quorum, per-peer p95 windows of utils/rollwin.py:58):

- segment codec bit-identity vs the GF log/antilog host oracle
  (ops/rs.py:134 encode_ref), any-k-survivors reassembly, padding
  edges;
- the acceptance matrix: one mirror killed mid-write (fault point
  "mirror_plane.leg") — the ack lands without eating the leg timeout,
  the hedged parity leg covers the dead peer, and the NN
  reconciliation monitor (_check_partial_replicas) upgrades the
  partial replica to a full one afterwards;
- segment-ingest failure on the mirror side ("mirror_plane.segment")
  hedging across to parity;
- ``mirror_parity = 0`` staying on the serial relay verbatim (no coded
  counters move);
- the serial relay's own crash windows: a mirror dying mid-chunk-delta
  ("block_receiver.mirror_push"), a torn need-frame negotiation
  ("block_receiver.need_frame"), and a stale-generation re-push
  refused at ingest entry ("block_receiver.ingest_reduced",
  FSNamesystem updatePipeline analog) — each attributed to the ACTUAL
  broken peer for the NN outlier feed, never ``targets[0]``.
"""

from __future__ import annotations

import itertools
import time

import numpy as np
import pytest

from hdrf_tpu.ops import rs
from hdrf_tpu.server import mirror_plane
from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.utils import fault_injection, metrics, retry

RNG = np.random.default_rng(41)

_MIR = metrics.registry("mirror")
_BR = metrics.registry("block_receiver")
_NN = metrics.registry("namenode")


def _bytes(n):
    return RNG.integers(0, 256, size=n, dtype=np.uint8).tobytes()


class Boom(Exception):
    pass


@pytest.fixture(autouse=True)
def _fresh_state():
    retry.reset_breakers()
    fault_injection.clear()
    yield
    retry.reset_breakers()
    fault_injection.clear()


def _wait(pred, timeout=25.0, interval=0.1, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# ----------------------------------------------------------- segment codec


class TestSegmentCodec:
    K, M = 3, 2

    def test_parity_matches_reference_encoder(self):
        payload = _bytes(self.K * 1024 + 17)
        segs, seg_len = mirror_plane.encode_segments(payload, self.K, self.M)
        assert len(segs) == self.K + self.M
        assert all(len(s) == seg_len for s in segs)
        padded = payload.ljust(self.K * seg_len, b"\0")
        data = np.frombuffer(padded, dtype=np.uint8).reshape(self.K, seg_len)
        ref = rs.encode_ref(data, self.M)
        for i in range(self.M):
            assert segs[self.K + i] == ref[i].tobytes()

    def test_any_k_survivors_reassemble(self):
        payload = _bytes(100_000 + 13)
        segs, _ = mirror_plane.encode_segments(payload, self.K, self.M)
        for live in itertools.combinations(range(self.K + self.M), self.K):
            got = mirror_plane.assemble_payload(
                {i: segs[i] for i in live}, self.K, self.M, len(payload))
            assert got == payload, f"survivor set {live} failed"

    def test_padding_edges(self):
        for n in (0, 1, self.K - 1, self.K, self.K + 1, 4096):
            payload = _bytes(n)
            segs, seg_len = mirror_plane.encode_segments(
                payload, self.K, self.M)
            assert seg_len >= 1  # zero-length frames never hit the wire
            # drop all-but-one data segment: decode through parity
            live = {0: segs[0]}
            live.update({self.K + i: segs[self.K + i]
                         for i in range(self.M)})
            got = mirror_plane.assemble_payload(
                dict(itertools.islice(live.items(), self.K)),
                self.K, self.M, n)
            assert got == payload

    def test_fewer_than_k_segments_raises(self):
        segs, _ = mirror_plane.encode_segments(_bytes(999), self.K, self.M)
        with pytest.raises(ValueError):
            mirror_plane.assemble_payload({0: segs[0]}, self.K, self.M, 999)

    def test_bad_geometry_raises(self):
        with pytest.raises(ValueError):
            mirror_plane.encode_segments(b"x", 0, 1)


# ------------------------------------------------------------- cluster e2e


class TestCodedMirrorCluster:
    def test_parity_zero_stays_on_serial_relay(self):
        """mirror_parity=0 (the default) must be byte-identical to the
        serial push_reduced path: no coded counters move, the replica
        chain fills to the full replication factor."""
        before_coded = _MIR.counter("coded_pushes")
        before_segs = _MIR.counter("segments_sent")
        with MiniCluster(n_datanodes=3, replication=3,
                         block_size=1 << 20) as mc:
            data = _bytes(300_000)
            with mc.client("mp0") as c:
                c.write("/mp0/f", data, scheme="dedup_lz4")
                assert c.read("/mp0/f") == data
            mc.wait_for_replication("/mp0/f", 3)
        assert _MIR.counter("coded_pushes") == before_coded
        assert _MIR.counter("segments_sent") == before_segs

    def test_coded_push_registers_partials_then_reconciles(self):
        """Happy path, mirror_parity=1 over a 2-target fan-out (k=1,
        m=1): the ack needs ONE leg; the landed segment registers a
        partial replica with the NN, and the reconciliation monitor
        upgrades every partial to a full replica in the background."""
        before_coded = _MIR.counter("coded_pushes")
        before_partial = _NN.counter("partial_replicas_reported")
        before_up = _NN.counter("partial_upgrades")
        with MiniCluster(n_datanodes=3, replication=3, block_size=1 << 20,
                         reduction_overrides={"mirror_parity": 1}) as mc:
            data = _bytes(300_000)
            with mc.client("mp1") as c:
                c.write("/mp1/f", data, scheme="dedup_lz4")
                assert c.read("/mp1/f") == data
            assert _MIR.counter("coded_pushes") > before_coded
            _wait(lambda: _NN.counter("partial_replicas_reported")
                  > before_partial, msg="partial replica IBR")
            mc.wait_for_replication("/mp1/f", 3)
            _wait(lambda: _NN.counter("partial_upgrades") > before_up,
                  msg="partial upgrade accounting")
            # census drains once every partial went full
            with mc.client("mp1c") as c:
                _wait(lambda: c._call("cluster_status")
                      ["partial_replicas"] == 0,
                      msg="partial census drain")

    def test_kill_one_mirror_mid_write_ack_lands_and_heals(self):
        """The acceptance matrix: kill one mirror AS the coded fan-out
        reaches it.  The dead data leg fails fast, the hedged parity leg
        covers it, and the write acks without eating any leg timeout;
        the NN reconciliation monitor then re-pushes until the block is
        fully replicated on the survivors."""
        before_hedges = _MIR.counter("hedges_fired")
        before_coded = _MIR.counter("coded_pushes")
        killed: list[str] = []
        with MiniCluster(n_datanodes=3, replication=3, block_size=1 << 20,
                         reduction_overrides={"mirror_parity": 1}) as mc:

            def _kill_data_leg(peer=None, seg_index=None, **kw):
                # first data leg (seg_index < k == 1): abrupt peer death
                if seg_index == 0 and not killed and peer is not None:
                    killed.append(peer)
                    mc.kill_datanode(int(peer.split("-")[1]))

            data = _bytes(300_000)
            with fault_injection.inject("mirror_plane.leg", _kill_data_leg):
                with mc.client("mpk") as c:
                    t0 = time.monotonic()
                    c.write("/mpk/f", data, scheme="dedup_lz4")
                    elapsed = time.monotonic() - t0
            assert killed, "fault point never saw the data leg"
            # the whole point: a dead mirror must not stall the ack until
            # the 60 s leg budget burns down
            assert elapsed < 15.0, f"ack stalled {elapsed:.1f}s on dead leg"
            assert _MIR.counter("hedges_fired") > before_hedges
            assert _MIR.counter("coded_pushes") > before_coded
            with mc.client("mpk2") as c:
                assert c.read("/mpk/f") == data
                # 2 live DNs left: the block must reach BOTH (head +
                # the hedged survivor upgraded from its parity segment)
                _wait(lambda: len(c._nn.call(
                    "get_block_locations",
                    path="/mpk/f")["blocks"][0]["locations"]) >= 2,
                      msg="post-kill re-replication to the survivor")
                assert c.read("/mpk/f") == data

    def test_segment_ingest_failure_hedges_to_parity(self):
        """A mirror that dies INSIDE segment ingest ("mirror_plane.segment"
        window) answers with an error frame: the leg fails fast and the
        parity hedge still lands the quorum."""
        before_fail = _MIR.counter("segment_ingest_failures")
        before_hedges = _MIR.counter("hedges_fired")
        with MiniCluster(n_datanodes=3, replication=3, block_size=1 << 20,
                         reduction_overrides={"mirror_parity": 1}) as mc:

            def _boom_data_segment(seg_index=None, **kw):
                if seg_index == 0:
                    raise ValueError("injected segment ingest death")

            data = _bytes(200_000)
            with fault_injection.inject("mirror_plane.segment",
                                        _boom_data_segment):
                with mc.client("mps") as c:
                    c.write("/mps/f", data, scheme="dedup_lz4")
                    assert c.read("/mps/f") == data
            assert _MIR.counter("segment_ingest_failures") > before_fail
            assert _MIR.counter("hedges_fired") > before_hedges
            mc.wait_for_replication("/mps/f", 3)


# ------------------------------------------- serial relay crash windows


class TestSerialRelayFaultMatrix:
    def test_mirror_killed_mid_chunk_delta(self):
        """"block_receiver.mirror_push": the mirror dies between packets
        of the chunk-delta stream.  The primary's replica survives, the
        write acks, and the ACTUAL peer is attributed."""
        with MiniCluster(n_datanodes=2, replication=2,
                         block_size=1 << 20) as mc:
            data = _bytes(300_000)

            def _die_mid_delta(seqno=None, **kw):
                if seqno is not None and seqno >= 1:
                    raise ConnectionError("injected mid-delta mirror death")

            with fault_injection.inject("block_receiver.mirror_push",
                                        _die_mid_delta):
                with mc.client("md") as c:
                    c.write("/md/f", data, scheme="dedup_lz4")
                    assert c.read("/md/f") == data
            flagged = {peer for dn in mc.datanodes if dn is not None
                       for peer in dn._mirror_fail}
            assert flagged, "mid-delta death never attributed"
            live = {dn.dn_id for dn in mc.datanodes if dn is not None}
            assert flagged <= live

    def test_torn_need_frame(self):
        """"block_receiver.need_frame": the mirror dies mid-negotiation,
        before the need list goes back upstream — the primary sees a
        reset socket, acks the client anyway, and attributes the peer."""
        with MiniCluster(n_datanodes=2, replication=2,
                         block_size=1 << 20) as mc:
            data = _bytes(250_000)
            with fault_injection.inject(
                    "block_receiver.need_frame",
                    lambda **kw: (_ for _ in ()).throw(
                        ConnectionError("injected torn need frame"))):
                with mc.client("tn") as c:
                    c.write("/tn/f", data, scheme="dedup_lz4")
                    assert c.read("/tn/f") == data
            flagged = {peer for dn in mc.datanodes if dn is not None
                       for peer in dn._mirror_fail}
            assert flagged, "torn need frame never attributed"

    def test_stale_gen_repush_rejected_at_ingest(self):
        """A re-push carrying a STALE generation stamp must be refused at
        ingest entry (the "block_receiver.ingest_reduced" window fires
        first; accepting would roll the replica behind its recovered
        generation) and accounted via ``stale_gen_rejected``."""
        with MiniCluster(n_datanodes=2, replication=2,
                         block_size=1 << 20) as mc:
            data = _bytes(200_000)
            with mc.client("sg") as c:
                c.write("/sg/f", data, scheme="dedup_lz4")
                loc = c._nn.call("get_block_locations",
                                 path="/sg/f")["blocks"][0]
            bid, gen = loc["block_id"], loc["gen_stamp"]
            mc.wait_for_replication("/sg/f", 2)
            pusher = next(dn for dn in mc.datanodes
                          if dn is not None
                          and dn.index.get_block(bid) is not None)
            victim = next(dn for dn in mc.datanodes
                          if dn is not None and dn is not pusher)
            meta = victim.replicas.get_meta(bid)
            assert meta is not None and meta.gen_stamp == gen
            seen: list[tuple] = []
            before = _BR.counter("stale_gen_rejected")
            with fault_injection.inject(
                    "block_receiver.ingest_reduced",
                    lambda block_id=None, gen_stamp=None, **kw:
                    seen.append((block_id, gen_stamp))):
                with pytest.raises((IOError, ConnectionError)):
                    pusher._receiver.push_reduced(
                        bid, gen - 1, meta.scheme, meta.logical_len, b"",
                        list(meta.checksums),
                        [{"dn_id": victim.dn_id,
                          "addr": list(victim.addr)}])
            assert _BR.counter("stale_gen_rejected") == before + 1
            assert (bid, gen - 1) in seen
            # the stale push must not have rolled the replica back
            assert victim.replicas.get_meta(bid).gen_stamp == gen
            with mc.client("sg2") as c:
                assert c.read("/sg/f") == data
