"""NameNode HA: standby journal tailing, failover, fencing, DN dual-reports,
client failover proxy (the reference's namenode/ha + qjournal capability:
EditLogTailer.java:74, StandbyCheckpointer.java:62, epoch-fenced journal,
ConfiguredFailoverProxyProvider)."""

import time

import numpy as np
import pytest

from hdrf_tpu.proto.rpc import RpcClient
from hdrf_tpu.testing.minicluster import MiniCluster


@pytest.fixture
def ha_cluster():
    with MiniCluster(n_datanodes=3, replication=2, ha=True) as mc:
        yield mc


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise TimeoutError(msg)


class TestHa:
    def test_standby_tails_namespace(self, ha_cluster):
        with ha_cluster.client("ha1") as c:
            c.write("/ha/f", b"x" * 50_000)
        sb = ha_cluster.standby
        _wait(lambda: sb.rpc_ha_state()["seq"] >=
              ha_cluster.namenode.rpc_ha_state()["seq"], msg="tail catchup")
        assert sb.rpc_stat("/ha/f")["length"] == 50_000

    def test_standby_rejects_mutations(self, ha_cluster):
        with RpcClient(ha_cluster.standby.addr) as sc:
            from hdrf_tpu.proto.rpc import RpcError

            with pytest.raises(RpcError, match="Standby"):
                sc.call("mkdir", path="/nope")

    def test_failover_preserves_namespace_and_serves_writes(self, ha_cluster):
        payload = np.random.default_rng(0).integers(
            0, 256, size=120_000, dtype=np.uint8).tobytes()
        with ha_cluster.client("ha2") as c:
            c.write("/ha/g", payload)
            sb = ha_cluster.standby
            _wait(lambda: sb.rpc_ha_state()["seq"] >=
                  ha_cluster.namenode.rpc_ha_state()["seq"])
            ha_cluster.failover()
            assert ha_cluster.namenode.role == "active"
            # same client object keeps working via the failover proxy
            assert c.read("/ha/g") == payload
            c.write("/ha/h", b"after failover")
            assert c.read("/ha/h") == b"after failover"

    def test_old_active_is_fenced(self, ha_cluster):
        nn, sb = ha_cluster.namenode, ha_cluster.standby
        with ha_cluster.client("ha3") as c:
            c.write("/ha/i", b"z" * 1000)
        _wait(lambda: sb.rpc_ha_state()["seq"] >= nn.rpc_ha_state()["seq"])
        # promote the standby WITHOUT stopping the old active (split brain)
        sb.rpc_transition_to_active()
        # the old active's next mutation must be fenced and demote it
        from hdrf_tpu.server.namenode import StandbyError

        with pytest.raises(StandbyError):
            nn.rpc_mkdir("/ha/old-active-write")
        assert nn.role == "standby"
        # and the op never reached the shared journal
        assert sb.rpc_transition_to_active()  # idempotent
        try:
            sb.rpc_stat("/ha/old-active-write")
            raise AssertionError("fenced write leaked into the journal")
        except FileNotFoundError:
            pass

    def test_dn_reports_reach_standby(self, ha_cluster):
        with ha_cluster.client("ha4") as c:
            c.write("/ha/j", b"q" * 80_000)
            sb = ha_cluster.standby
            _wait(lambda: sb.rpc_ha_state()["seq"] >=
                  ha_cluster.namenode.rpc_ha_state()["seq"])
            # standby knows the block locations (warm map at failover)
            def located():
                try:
                    loc = sb.rpc_get_block_locations("/ha/j")
                    return all(b["locations"] for b in loc["blocks"])
                except FileNotFoundError:
                    return False
            _wait(located, msg="standby block map")


class TestFailoverController:
    def test_choose_candidate_highest_txid_never_observer(self):
        from hdrf_tpu.server.failover import FailoverController

        a1, a2, a3 = ("h", 1), ("h", 2), ("h", 3)
        states = [(a1, "standby", 5), (a2, "standby", 9),
                  (a3, "observer", 50)]
        assert FailoverController._choose_candidate(states) == a2
        # only observers reachable: nobody to promote
        assert FailoverController._choose_candidate(
            [(a3, "observer", 50)]) is None

    def test_auto_failover_on_active_death(self, ha_cluster):
        from hdrf_tpu.server.failover import FailoverController

        fc = FailoverController(ha_cluster.nn_addrs(),
                                probe_interval_s=0.2, grace=2).start()
        try:
            with ha_cluster.client("zkfc") as c:
                c.write("/ha/k", b"m" * 10_000)
                sb = ha_cluster.standby
                _wait(lambda: sb.rpc_ha_state()["seq"] >=
                      ha_cluster.namenode.rpc_ha_state()["seq"])
                ha_cluster.namenode.stop()  # active dies; controller promotes
                _wait(lambda: sb.role == "active", timeout=15,
                      msg="auto failover")
                ha_cluster.namenode, ha_cluster.standby = sb, None
                assert c.read("/ha/k") == b"m" * 10_000
        finally:
            fc.stop()


@pytest.fixture
def obs_cluster():
    with MiniCluster(n_datanodes=3, replication=2, ha=True,
                     observers=1) as mc:
        yield mc


def _ha_counter(key: str) -> int:
    from hdrf_tpu.utils import metrics

    return metrics.registry("client.ha").snapshot()["counters"].get(key, 0)


class TestObserver:
    """Observer read plane (ISSUE 20): staleness-bounded read replicas,
    msync read-your-writes, breaker demotion, storm-proof failover — the
    ObserverReadProxyProvider / GlobalStateIdContext contract."""

    def test_observer_serves_reads_refuses_mutations(self, obs_cluster):
        ob = obs_cluster.observers[0]
        with obs_cluster.client("obs0") as c:
            c.mkdir("/obs/d")
            c.msync(wait_s=5.0)
        assert ob.rpc_ha_state()["role"] == "observer"
        with RpcClient(ob.addr) as oc:
            from hdrf_tpu.proto.rpc import RpcError

            assert oc.call("stat", path="/obs/d")["type"] == "dir"
            with pytest.raises(RpcError, match="Standby"):
                oc.call("mkdir", path="/obs/nope")
            # and an observer can never be promoted (satellite 1)
            with pytest.raises(RpcError, match="observer"):
                oc.call("transition_to_active")

    def test_read_your_writes_after_every_mutation_type(self, obs_cluster):
        """The msync matrix: after each mutating RPC type the very next
        observer-routed read must see the write — zero-tolerance on
        silent staleness."""
        reads0 = _ha_counter("observer_reads")
        with obs_cluster.client("obs1") as c:
            c.mkdir("/obs/m")
            c.msync(wait_s=5.0)
            assert c.stat("/obs/m")["type"] == "dir"

            c.write("/obs/m/f", b"v1" * 4096)          # create+addBlock+complete
            c.msync(wait_s=5.0)
            assert c.stat("/obs/m/f")["length"] == 8192
            assert c.read("/obs/m/f") == b"v1" * 4096

            c.rename("/obs/m/f", "/obs/m/g")           # rename
            c.msync(wait_s=5.0)
            assert c.exists("/obs/m/g") and not c.exists("/obs/m/f")

            c.setfattr("/obs/m/g", "user.tag", b"t1")  # set_xattr
            c.msync(wait_s=5.0)
            assert c.getfattr("/obs/m/g")["user.tag"] == b"t1"

            c.set_replication("/obs/m/g", 3)           # setrep
            c.msync(wait_s=5.0)
            assert c.stat("/obs/m/g")["replication"] == 3

            c.delete("/obs/m/g")                       # delete
            c.msync(wait_s=5.0)
            assert not c.exists("/obs/m/g")
        # the matrix's reads were actually observer-served, not active
        assert _ha_counter("observer_reads") > reads0

    def test_stale_observer_bounces_not_lies(self, obs_cluster):
        """Park the observer's tailer (tail fault point), mutate, read:
        the observer cannot reach the client's txid inside the wait
        window, refuses with ObserverStaleError, and the proxy bounces
        the read to the active — correct answer, bounce counted."""
        from hdrf_tpu.utils import fault_injection, metrics

        def park(role=None, **kw):
            if role == "observer":
                raise RuntimeError("tailer parked by test")

        bounces0 = _ha_counter("observer_bounces")
        nn_stale0 = metrics.registry("namenode").snapshot()[
            "counters"].get("observer_stale_bounced", 0)
        with fault_injection.inject("namenode.tail", park):
            with obs_cluster.client("obs2") as c:
                c.mkdir("/obs/stale")
                assert c.stat("/obs/stale")["type"] == "dir"  # bounced, not stale
        assert _ha_counter("observer_bounces") > bounces0
        assert metrics.registry("namenode").snapshot()["counters"].get(
            "observer_stale_bounced", 0) > nn_stale0

    def test_breaker_demotes_dead_observer(self, obs_cluster):
        from hdrf_tpu.utils import retry

        ob = obs_cluster.observers[0]
        host, port = ob.addr
        with obs_cluster.client("obs3") as c:
            c.write("/obs/b", b"alive" * 1000)
            c.msync(wait_s=5.0)
            assert c.read("/obs/b") == b"alive" * 1000
            ob.stop()  # observer dies; reads must keep succeeding
            for _ in range(5):
                assert c.stat("/obs/b")["length"] == 5000
        b = retry.all_breakers().get(f"nn:{host}:{port}")
        assert b is not None and b.state == "open"

    def test_kill_active_mid_storm(self, obs_cluster):
        """Active dies under reader load; the controller promotes the
        standby while observer reads keep flowing — zero responses staler
        than the bound (content mismatches) throughout the window."""
        import threading

        from hdrf_tpu.server.failover import FailoverController

        payload = b"storm" * 2000
        with obs_cluster.client("seed") as c:
            c.write("/obs/storm", payload)
            c.msync(wait_s=5.0)
        fc = FailoverController(obs_cluster.nn_addrs(),
                                probe_interval_s=0.2, grace=2).start()
        stop = threading.Event()
        reads, errors, stale = [0], [0], [0]

        def reader():
            with obs_cluster.client("storm-reader") as c:
                while not stop.is_set():
                    try:
                        data = c.read("/obs/storm")
                    except Exception:  # noqa: BLE001 — counted, judged below
                        errors[0] += 1
                        time.sleep(0.05)
                        continue
                    reads[0] += 1
                    if data != payload:
                        stale[0] += 1

        t = threading.Thread(target=reader)
        t.start()
        try:
            time.sleep(1.0)
            pre_kill = reads[0]
            obs_cluster.kill_namenode()
            _wait(lambda: obs_cluster.standby is not None
                  and obs_cluster.standby.role == "active",
                  timeout=15, msg="auto promotion")
            obs_cluster.ns[0]["active"] = obs_cluster.standby
            obs_cluster.namenode = obs_cluster.standby
            obs_cluster.ns[0]["standby"] = None
            obs_cluster.standby = None
            time.sleep(1.0)
        finally:
            stop.set()
            t.join()
            fc.stop()
        assert stale[0] == 0, "stale-beyond-bound responses"
        assert reads[0] > pre_kill, "reads stopped at the kill"
        with obs_cluster.client("post") as c:
            c.write("/obs/after", b"promoted")
            c.msync(wait_s=5.0)
            assert c.read("/obs/after") == b"promoted"

    def test_metadata_cache_invalidated_on_txid_bump(self, obs_cluster):
        from hdrf_tpu.client.filesystem import HdrfClient
        from hdrf_tpu.config import ClientConfig
        from hdrf_tpu.utils import metrics

        def hits():
            return metrics.registry("client").snapshot()[
                "counters"].get("meta_cache_hits", 0)

        cfg = ClientConfig(metadata_cache_ttl_s=30.0)
        with HdrfClient(obs_cluster.nn_addrs(), name="cache",
                        config=cfg) as c:
            c.mkdir("/obs/cache")
            c.msync(wait_s=5.0)
            c.stat("/obs/cache")
            h0 = hits()
            c.stat("/obs/cache")            # same generation: served hot
            assert hits() == h0 + 1
            c.mkdir("/obs/cache2")          # txid bump invalidates the gen
            c.msync(wait_s=5.0)
            h1 = hits()
            c.stat("/obs/cache")
            assert hits() == h1             # miss: generation moved


class TestJournalTornTail:
    def test_promotion_truncates_torn_tail(self, tmp_path):
        """Edits appended after a promotion over a torn journal tail (old
        active crashed mid-append) must be reachable by later replays: the
        promoting NN truncates the torn frame before opening for append."""
        import os

        from hdrf_tpu.server.editlog import EditLog

        d = str(tmp_path / "journal")
        a = EditLog(d)
        a.load_image()
        a.replay(lambda rec: None)
        a.open_for_append(lambda: None)
        a.claim_epoch()
        a.append(["mkdir", "/a"])
        a.append(["mkdir", "/b"])
        a.close()
        # crash mid-append: an incomplete frame at the WAL tail
        with open(os.path.join(d, "edits.wal"), "ab") as f:
            f.write(b"\x40\x00\x00\x00\x00\x00\x00\x00torn")
        # promotion: claim the epoch, truncating catch-up, open, append
        b = EditLog(d)
        b.load_image()
        b.claim_epoch()
        seen = []
        b.tail(seen.append, readonly=False)
        assert [r[1] for r in seen] == ["/a", "/b"]
        b.open_for_append(lambda: None)
        b.append(["mkdir", "/c"])
        b.close()
        # every acked edit survives a cold replay
        c = EditLog(d)
        c.load_image()
        replayed = []
        c.replay(replayed.append)
        assert [r[1] for r in replayed] == ["/a", "/b", "/c"]
        c.close()

    def test_standby_tail_never_truncates(self, tmp_path):
        """The readonly tail must leave a torn tail in place — it may be the
        active's append in flight, not a crash artifact."""
        import os

        from hdrf_tpu.server.editlog import EditLog

        d = str(tmp_path / "journal")
        a = EditLog(d)
        a.open_for_append(lambda: None)
        a.append(["mkdir", "/a"])
        a.close()
        wal = os.path.join(d, "edits.wal")
        with open(wal, "ab") as f:
            f.write(b"\x40\x00\x00\x00\x00\x00\x00\x00mid")
        size_before = os.path.getsize(wal)
        sb = EditLog(d)
        sb.load_image()
        sb.tail(lambda rec: None)  # readonly default
        assert os.path.getsize(wal) == size_before
        sb.close()
