"""NameNode HA: standby journal tailing, failover, fencing, DN dual-reports,
client failover proxy (the reference's namenode/ha + qjournal capability:
EditLogTailer.java:74, StandbyCheckpointer.java:62, epoch-fenced journal,
ConfiguredFailoverProxyProvider)."""

import time

import numpy as np
import pytest

from hdrf_tpu.proto.rpc import RpcClient
from hdrf_tpu.testing.minicluster import MiniCluster


@pytest.fixture
def ha_cluster():
    with MiniCluster(n_datanodes=3, replication=2, ha=True) as mc:
        yield mc


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise TimeoutError(msg)


class TestHa:
    def test_standby_tails_namespace(self, ha_cluster):
        with ha_cluster.client("ha1") as c:
            c.write("/ha/f", b"x" * 50_000)
        sb = ha_cluster.standby
        _wait(lambda: sb.rpc_ha_state()["seq"] >=
              ha_cluster.namenode.rpc_ha_state()["seq"], msg="tail catchup")
        assert sb.rpc_stat("/ha/f")["length"] == 50_000

    def test_standby_rejects_mutations(self, ha_cluster):
        with RpcClient(ha_cluster.standby.addr) as sc:
            from hdrf_tpu.proto.rpc import RpcError

            with pytest.raises(RpcError, match="Standby"):
                sc.call("mkdir", path="/nope")

    def test_failover_preserves_namespace_and_serves_writes(self, ha_cluster):
        payload = np.random.default_rng(0).integers(
            0, 256, size=120_000, dtype=np.uint8).tobytes()
        with ha_cluster.client("ha2") as c:
            c.write("/ha/g", payload)
            sb = ha_cluster.standby
            _wait(lambda: sb.rpc_ha_state()["seq"] >=
                  ha_cluster.namenode.rpc_ha_state()["seq"])
            ha_cluster.failover()
            assert ha_cluster.namenode.role == "active"
            # same client object keeps working via the failover proxy
            assert c.read("/ha/g") == payload
            c.write("/ha/h", b"after failover")
            assert c.read("/ha/h") == b"after failover"

    def test_old_active_is_fenced(self, ha_cluster):
        nn, sb = ha_cluster.namenode, ha_cluster.standby
        with ha_cluster.client("ha3") as c:
            c.write("/ha/i", b"z" * 1000)
        _wait(lambda: sb.rpc_ha_state()["seq"] >= nn.rpc_ha_state()["seq"])
        # promote the standby WITHOUT stopping the old active (split brain)
        sb.rpc_transition_to_active()
        # the old active's next mutation must be fenced and demote it
        from hdrf_tpu.server.namenode import StandbyError

        with pytest.raises(StandbyError):
            nn.rpc_mkdir("/ha/old-active-write")
        assert nn.role == "standby"
        # and the op never reached the shared journal
        assert sb.rpc_transition_to_active()  # idempotent
        try:
            sb.rpc_stat("/ha/old-active-write")
            raise AssertionError("fenced write leaked into the journal")
        except FileNotFoundError:
            pass

    def test_dn_reports_reach_standby(self, ha_cluster):
        with ha_cluster.client("ha4") as c:
            c.write("/ha/j", b"q" * 80_000)
            sb = ha_cluster.standby
            _wait(lambda: sb.rpc_ha_state()["seq"] >=
                  ha_cluster.namenode.rpc_ha_state()["seq"])
            # standby knows the block locations (warm map at failover)
            def located():
                try:
                    loc = sb.rpc_get_block_locations("/ha/j")
                    return all(b["locations"] for b in loc["blocks"])
                except FileNotFoundError:
                    return False
            _wait(located, msg="standby block map")


class TestFailoverController:
    def test_auto_failover_on_active_death(self, ha_cluster):
        from hdrf_tpu.server.failover import FailoverController

        fc = FailoverController(ha_cluster.nn_addrs(),
                                probe_interval_s=0.2, grace=2).start()
        try:
            with ha_cluster.client("zkfc") as c:
                c.write("/ha/k", b"m" * 10_000)
                sb = ha_cluster.standby
                _wait(lambda: sb.rpc_ha_state()["seq"] >=
                      ha_cluster.namenode.rpc_ha_state()["seq"])
                ha_cluster.namenode.stop()  # active dies; controller promotes
                _wait(lambda: sb.role == "active", timeout=15,
                      msg="auto failover")
                ha_cluster.namenode, ha_cluster.standby = sb, None
                assert c.read("/ha/k") == b"m" * 10_000
        finally:
            fc.stop()


class TestJournalTornTail:
    def test_promotion_truncates_torn_tail(self, tmp_path):
        """Edits appended after a promotion over a torn journal tail (old
        active crashed mid-append) must be reachable by later replays: the
        promoting NN truncates the torn frame before opening for append."""
        import os

        from hdrf_tpu.server.editlog import EditLog

        d = str(tmp_path / "journal")
        a = EditLog(d)
        a.load_image()
        a.replay(lambda rec: None)
        a.open_for_append(lambda: None)
        a.claim_epoch()
        a.append(["mkdir", "/a"])
        a.append(["mkdir", "/b"])
        a.close()
        # crash mid-append: an incomplete frame at the WAL tail
        with open(os.path.join(d, "edits.wal"), "ab") as f:
            f.write(b"\x40\x00\x00\x00\x00\x00\x00\x00torn")
        # promotion: claim the epoch, truncating catch-up, open, append
        b = EditLog(d)
        b.load_image()
        b.claim_epoch()
        seen = []
        b.tail(seen.append, readonly=False)
        assert [r[1] for r in seen] == ["/a", "/b"]
        b.open_for_append(lambda: None)
        b.append(["mkdir", "/c"])
        b.close()
        # every acked edit survives a cold replay
        c = EditLog(d)
        c.load_image()
        replayed = []
        c.replay(replayed.append)
        assert [r[1] for r in replayed] == ["/a", "/b", "/c"]
        c.close()

    def test_standby_tail_never_truncates(self, tmp_path):
        """The readonly tail must leave a torn tail in place — it may be the
        active's append in flight, not a crash artifact."""
        import os

        from hdrf_tpu.server.editlog import EditLog

        d = str(tmp_path / "journal")
        a = EditLog(d)
        a.open_for_append(lambda: None)
        a.append(["mkdir", "/a"])
        a.close()
        wal = os.path.join(d, "edits.wal")
        with open(wal, "ab") as f:
            f.write(b"\x40\x00\x00\x00\x00\x00\x00\x00mid")
        size_before = os.path.getsize(wal)
        sb = EditLog(d)
        sb.load_image()
        sb.tail(lambda rec: None)  # readonly default
        assert os.path.getsize(wal) == size_before
        sb.close()
