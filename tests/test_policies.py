"""Storage policies + Mover, and the attr namespace ops: setReplication,
setTimes, concat, symlinks (Mover.java:70, FSDirAttrOp, FSDirConcatOp.java:49,
FSDirSymlinkOp.java:34 analogs)."""

from __future__ import annotations

import time

import numpy as np
import pytest

from hdrf_tpu.testing.minicluster import MiniCluster

RNG = np.random.default_rng(31)


def _bytes(n):
    return RNG.integers(0, 256, size=n, dtype=np.uint8).tobytes()


class TestStoragePolicies:
    def test_policy_aware_placement(self):
        """A 'cold' path places its replica on the ARCHIVE node."""
        with MiniCluster(n_datanodes=3, replication=1, block_size=1 << 20,
                         storage_types=["DISK", "DISK", "ARCHIVE"]) as mc:
            nn = mc.namenode
            with mc.client("w") as c:
                c.mkdir("/cold")
                c.set_storage_policy("/cold", "cold")
                assert c.get_storage_policy("/cold")["effective"] == "cold"
                for i in range(3):
                    c.write(f"/cold/f{i}", _bytes(10_000))
                for i in range(3):
                    loc = c._call("get_block_locations", path=f"/cold/f{i}")
                    for b in loc["blocks"]:
                        for ld in b["locations"]:
                            dn = nn._datanodes[ld["dn_id"]]
                            assert dn.storage_type == "ARCHIVE"

    def test_mover_migrates_replicas(self):
        """Policy set AFTER writing: the mover moves the replica from the
        hot (DISK) node to the ARCHIVE node."""
        from hdrf_tpu.tools import cli

        with MiniCluster(n_datanodes=2, replication=1, block_size=1 << 20,
                         storage_types=["DISK", "ARCHIVE"]) as mc:
            nn = mc.namenode
            with mc.client("w") as c:
                c.mkdir("/data")
                c.write("/data/f", _bytes(50_000))  # hot default -> DISK
                loc = c._call("get_block_locations", path="/data/f")
                bid = loc["blocks"][0]["block_id"]
                assert nn._datanodes[
                    loc["blocks"][0]["locations"][0]["dn_id"]
                ].storage_type == "DISK"
                c.set_storage_policy("/data", "cold")
                viol = c._call("policy_violations")
                assert viol and viol[0]["block_id"] == bid
                addr = f"{nn.addr[0]}:{nn.addr[1]}"
                assert cli.main(["mover", "--namenode", addr,
                                 "--iterations", "20",
                                 "--wait-s", "0.3"]) == 0
                deadline = time.time() + 10
                while time.time() < deadline:
                    loc = c._call("get_block_locations", path="/data/f")
                    dns = [ld["dn_id"]
                           for ld in loc["blocks"][0]["locations"]]
                    if dns and all(nn._datanodes[d].storage_type ==
                                   "ARCHIVE" for d in dns):
                        break
                    time.sleep(0.3)
                else:
                    pytest.fail("replica never moved to ARCHIVE")
                assert c.read("/data/f")  # still readable after migration


class TestAttrOps:
    @pytest.fixture(scope="class")
    def cluster(self):
        with MiniCluster(n_datanodes=3, replication=1,
                         block_size=1 << 20) as mc:
            yield mc

    def test_set_replication_converges(self, cluster):
        nn = cluster.namenode
        with cluster.client("r") as c:
            c.write("/sr/f", _bytes(20_000))
            assert c.stat("/sr/f")["replication"] == 1
            c.set_replication("/sr/f", 2)
            assert c.stat("/sr/f")["replication"] == 2
            deadline = time.time() + 10
            while time.time() < deadline:
                loc = c._call("get_block_locations", path="/sr/f")
                if len(loc["blocks"][0]["locations"]) == 2:
                    break
                time.sleep(0.3)
            else:
                pytest.fail("redundancy monitor never added the replica")

    def test_set_times(self, cluster):
        with cluster.client("t") as c:
            c.write("/tm/f", b"x")
            c.set_times("/tm/f", mtime=12345.0)
            assert c.stat("/tm/f")["mtime"] == 12345.0

    def test_concat(self, cluster):
        with cluster.client("cc") as c:
            parts = [_bytes(30_000) for _ in range(3)]
            for i, p in enumerate(parts):
                c.write(f"/cc/p{i}", p)
            c.concat("/cc/p0", ["/cc/p1", "/cc/p2"])
            assert c.read("/cc/p0") == b"".join(parts)
            assert not c.exists("/cc/p1") and not c.exists("/cc/p2")
            st = c.stat("/cc/p0")
            assert st["length"] == 90_000 and st["blocks"] == 3

    def test_concat_validation(self, cluster):
        from hdrf_tpu.proto.rpc import RpcError

        with cluster.client("cv") as c:
            c.write("/cv/a", b"a" * 100)
            c.write("/cv/b", b"b" * 100, scheme="dedup_lz4")
            with pytest.raises(RpcError):
                c.concat("/cv/a", ["/cv/b"])  # scheme mismatch
            with pytest.raises(RpcError):
                c.concat("/cv/a", ["/cv/a"])  # self-concat

    def test_symlink_resolution(self, cluster):
        with cluster.client("sl") as c:
            data = _bytes(12_345)
            c.write("/real/file", data)
            c.create_symlink("/lnk", "/real")
            # read THROUGH the link (client-side redirect retry)
            assert c.read("/lnk/file") == data
            assert c.stat("/lnk/file")["length"] == 12_345
            # listing shows the link itself
            ents = {e["name"]: e for e in c.ls("/")}
            assert ents["lnk"]["type"] == "symlink"
            assert ents["lnk"]["target"] == "/real"
            # deleting the link leaves the target
            assert c.delete("/lnk")
            assert c.read("/real/file") == data

    def test_symlink_to_file_and_dangling(self, cluster):
        from hdrf_tpu.proto.rpc import RpcError

        with cluster.client("sl2") as c:
            c.write("/tgt", b"hello")
            c.create_symlink("/ln2", "/tgt")
            assert c.read("/ln2") == b"hello"
            c.create_symlink("/dang", "/nowhere")
            with pytest.raises((RpcError, IOError)):
                c.read("/dang")


class TestReviewHoles:
    def test_write_through_symlinked_dir(self):
        """create/mkdir UNDER a symlink redirect client-side too."""
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=1 << 20) as mc:
            with mc.client("w") as c:
                c.mkdir("/real")
                c.create_symlink("/ln", "/real")
                c.write("/ln/f", b"through-link")
                assert c.read("/real/f") == b"through-link"
                c.mkdir("/ln/sub")
                assert c.exists("/real/sub")

    def test_relative_symlink_target(self):
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=1 << 20) as mc:
            with mc.client("w") as c:
                c.write("/a/sub/f", b"rel")
                c.create_symlink("/a/ln", "sub")  # relative to /a
                assert c.read("/a/ln/f") == b"rel"

    def test_warm_policy_all_disk_violation_detected(self):
        """warm with every replica on DISK: the membership test missed it;
        the multiset match must propose an ARCHIVE migration."""
        with MiniCluster(n_datanodes=3, replication=2, block_size=1 << 20,
                         storage_types=["DISK", "DISK", "ARCHIVE"]) as mc:
            with mc.client("w") as c:
                c.mkdir("/w")
                c.write("/w/f", _bytes(10_000))  # hot -> both DISK
                import time as _t
                deadline = _t.time() + 10
                while _t.time() < deadline:
                    loc = c._call("get_block_locations", path="/w/f")
                    if len(loc["blocks"][0]["locations"]) == 2:
                        break
                    _t.sleep(0.2)
                c.set_storage_policy("/w", "warm")
                viol = c._call("policy_violations")
                assert viol, "warm violation must be detected"
                assert mc.namenode._datanodes[
                    viol[0]["to_dn"]].storage_type == "ARCHIVE"

    def test_symlink_counts_against_ns_quota(self):
        from hdrf_tpu.proto.rpc import RpcError

        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=1 << 20) as mc:
            with mc.client("q") as c:
                c.mkdir("/qd")
                c.set_quota("/qd", namespace_quota=2)  # dir itself + 1
                c.create_symlink("/qd/l1", "/x")
                with pytest.raises(RpcError):
                    c.create_symlink("/qd/l2", "/y")

    def test_root_storage_policy_roundtrip(self):
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=1 << 20) as mc:
            with mc.client("r") as c:
                c.set_storage_policy("/", "hot")
                assert c.get_storage_policy("/")["effective"] == "hot"
