"""Device-resident reduction pipeline (ops/resident.py) against the native
C++ oracle — including the degenerate inputs the verify skill calls out
(zero runs make every position a Gear candidate; empty blocks are legal)."""

import numpy as np
import pytest

from hdrf_tpu import native
from hdrf_tpu.config import CdcConfig
from hdrf_tpu.ops.dispatch import gear_mask
from hdrf_tpu.ops.resident import ResidentReducer


@pytest.fixture(scope="module")
def reducer():
    return ResidentReducer(CdcConfig())


def _oracle(data: np.ndarray, cdc: CdcConfig):
    cuts = native.cdc_chunk(data, gear_mask(cdc), cdc.min_chunk, cdc.max_chunk)
    starts = np.concatenate([[0], cuts[:-1]]).astype(np.uint64)
    digs = native.sha256_batch(data, starts, (cuts - starts).astype(np.uint64))
    return cuts, digs


def test_matches_oracle(reducer):
    rng = np.random.default_rng(3)
    a = rng.integers(0, 256, size=1 << 20, dtype=np.uint8)
    cuts, digs = reducer.reduce(a)
    wc, wd = _oracle(a, reducer.cdc)
    np.testing.assert_array_equal(cuts, wc)
    np.testing.assert_array_equal(digs, wd)


def test_unaligned_length(reducer):
    rng = np.random.default_rng(4)
    a = rng.integers(0, 256, size=777_777, dtype=np.uint8)
    cuts, digs = reducer.reduce(a)
    wc, wd = _oracle(a, reducer.cdc)
    np.testing.assert_array_equal(cuts, wc)
    np.testing.assert_array_equal(digs, wd)


def test_dense_candidates_zero_run(reducer):
    """A long zero run makes every position a candidate (G[0]==0); the packed
    candidate capacity overflows and the pipeline must retry, not raise."""
    rng = np.random.default_rng(5)
    a = rng.integers(0, 256, size=1 << 20, dtype=np.uint8)
    a[100_000:900_000] = 0
    cuts, digs = reducer.reduce(a)
    wc, wd = _oracle(a, reducer.cdc)
    np.testing.assert_array_equal(cuts, wc)
    np.testing.assert_array_equal(digs, wd)


def test_all_zeros(reducer):
    a = np.zeros(300_000, dtype=np.uint8)
    cuts, digs = reducer.reduce(a)
    wc, wd = _oracle(a, reducer.cdc)
    np.testing.assert_array_equal(cuts, wc)
    np.testing.assert_array_equal(digs, wd)


def test_empty_block(reducer):
    cuts, digs = reducer.reduce(b"")
    assert cuts.size == 0 and digs.shape == (0, 32)


def test_overlapped_jobs(reducer):
    rng = np.random.default_rng(6)
    blocks = [rng.integers(0, 256, size=1 << 19, dtype=np.uint8)
              for _ in range(3)]
    jobs = [reducer.submit(b) for b in blocks]
    for j in jobs:
        reducer.start_sha(j)
    for b, j in zip(blocks, jobs):
        cuts, digs = reducer.finish(j)
        wc, wd = _oracle(b, reducer.cdc)
        np.testing.assert_array_equal(cuts, wc)
        np.testing.assert_array_equal(digs, wd)


def test_reduce_many_batched(reducer):
    """The batched path (one dispatch + one readback per stage for a group
    of equal-length blocks) must be bit-identical to the per-block path and
    the native oracle — including dense-candidate retries and mixed sizes
    that fall back per block."""
    rng = np.random.default_rng(7)
    blocks = [rng.integers(0, 256, size=1 << 19, dtype=np.uint8)
              for _ in range(3)]
    odd = rng.integers(0, 256, size=(1 << 19) + 999, dtype=np.uint8)
    dense = np.zeros(1 << 19, dtype=np.uint8)  # every position a candidate
    dense2 = dense.copy()
    inputs = blocks + [odd, dense, dense2, np.empty(0, np.uint8)]
    results = reducer.reduce_many(inputs)
    assert len(results) == len(inputs)
    for data, (cuts, digs) in zip(inputs, results):
        if data.size == 0:
            assert cuts.size == 0 and digs.shape == (0, 32)
            continue
        wc, wd = _oracle(data, reducer.cdc)
        np.testing.assert_array_equal(cuts, wc)
        np.testing.assert_array_equal(digs, wd)


def test_fused_front_end_matches_oracle():
    """The fused Pallas front end (HDRF_CDC_PALLAS; interpret mode on the
    CPU mesh) drives the SAME reduce_many contract: mixed sizes, a dense
    zero block that fills the cut table to within two entries of the plan
    cap (every position a candidate), and an empty block — all
    oracle-identical.  The overflow fallback proper and the ledger shape
    are pinned in tests/test_cdc_pallas.py."""
    rng = np.random.default_rng(8)
    reducer = ResidentReducer(CdcConfig(), fused_mode="interpret")
    inputs = [rng.integers(0, 256, size=1 << 19, dtype=np.uint8),
              rng.integers(0, 256, size=333_333, dtype=np.uint8),
              np.zeros(1 << 19, dtype=np.uint8),
              np.empty(0, np.uint8)]
    results = reducer.reduce_many(inputs)
    assert len(results) == len(inputs)
    for data, (cuts, digs) in zip(inputs, results):
        if data.size == 0:
            assert cuts.size == 0 and digs.shape == (0, 32)
            continue
        wc, wd = _oracle(data, reducer.cdc)
        np.testing.assert_array_equal(cuts, wc)
        np.testing.assert_array_equal(digs, wd)


def test_batch_lane_count_steps():
    from hdrf_tpu.ops.resident import _lane_count_geo

    assert _lane_count_geo(1) == 128
    assert _lane_count_geo(128) == 128
    assert _lane_count_geo(129) == 256
    assert _lane_count_geo(1025) == 1152  # step 2048/16=128 above 1024
    for n in (5475, 43800, 65537, 70000):
        L = _lane_count_geo(n)
        assert L >= n and L % 128 == 0 and (L - n) / n <= 0.126


def test_sha_kernel_nonmultiple_tile_lane_rows():
    """Regression: lane counts whose 128-row count is NOT a multiple of the
    SHA kernel's row tile (e.g. L=3840 -> 30 rows, tile 8) must still hash
    every lane.  The grid used to FLOOR the tile count, leaving the tail
    rows unprocessed — returning stale device memory that could even equal
    the right digests when a previous dispatch had hashed the same content
    (how the bug hid from identical-block tests while corrupting mixed
    batches)."""
    import hashlib

    import jax

    from hdrf_tpu.ops.sha256 import sha256_words

    for L in (384, 2176, 3840):
        rng = np.random.default_rng(L)
        data = rng.integers(0, 256, size=(L, 32), dtype=np.uint8)
        w = np.zeros((L, 16), dtype=np.uint32)
        be = data.reshape(L, 8, 4).astype(np.uint32)
        w[:, :8] = (be[:, :, 0] << 24) | (be[:, :, 1] << 16) \
            | (be[:, :, 2] << 8) | be[:, :, 3]
        w[:, 8] = 0x80000000
        w[:, 15] = 256
        nb = np.ones(L, np.int32)
        if jax.default_backend() == "cpu":
            out = np.asarray(sha256_words(jax.device_put(w),
                                          jax.device_put(nb)))
        else:
            from hdrf_tpu.ops.sha256_pallas import sha256_words_pallas

            out = np.asarray(sha256_words_pallas(jax.device_put(w),
                                                 jax.device_put(nb)))
        for i in (0, L // 2, L - 1, L - 128, L - 129 if L > 129 else 0):
            assert bytes(out[i]) == hashlib.sha256(
                data[i].tobytes()).digest(), (L, i)


def test_mixed_batch_distinct_blocks_match_oracle(reducer):
    """Regression companion: a batch of DISTINCT blocks (the bench shape
    that exposed the stale-row bug) must be oracle-identical per block."""
    rng = np.random.default_rng(77)
    blocks = []
    for i in range(4):
        a = rng.integers(0, 256, size=1 << 20, dtype=np.uint8)
        a[: 1 << 18] = rng.integers(97, 123, size=1 << 18, dtype=np.uint8)
        blocks.append(a)
    for data, (cuts, digs) in zip(blocks, reducer.reduce_many(blocks)):
        wc, wd = _oracle(data, reducer.cdc)
        np.testing.assert_array_equal(cuts, wc)
        np.testing.assert_array_equal(digs, wd)
