"""Observability spine: dispatch ledger, Prometheus exposition, trace
propagation/assembly, the stall watchdog, and the bench JSON contract.

Covers the reference's metrics2 -> PrometheusMetricsSink text rendering,
the HTrace span resume over op headers (Receiver.java:94-98
``continueTraceSpan``), and HttpServer2's /stacks servlet — in their
re-expressed forms (utils/prom.py, utils/tracing.py, utils/watchdog.py,
server/status_http.py, the gateway's /prom /traces /stacks routes)."""

import json
import os
import random
import re
import subprocess
import sys
import time
import urllib.request

import pytest

from hdrf_tpu.server.http_gateway import HttpGateway
from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.utils import device_ledger, fault_injection, metrics, prom, tracing
from hdrf_tpu.utils.metrics import Histogram
from hdrf_tpu.utils.watchdog import StallWatchdog, thread_stacks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def blob(seed: int, n: int) -> bytes:
    return random.Random(seed).randbytes(n)


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10) as r:
        assert r.status == 200
        return r.read()


# ------------------------------------------------------------- prom parsing

_SAMPLE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)\{([^}]*)\} (-?[0-9.eE+]+|NaN)$')
_TYPE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|histogram|summary|untyped)$")


def parse_prom(text: str):
    """Strict exposition-format parser: every line must be a valid # TYPE
    comment or a ``name{labels} value`` sample; TYPE names must be unique.
    Returns ({family: type}, [(name, labels, value)])."""
    types: dict[str, str] = {}
    samples: list[tuple[str, dict, float]] = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            m = _TYPE.match(line)
            assert m, f"malformed comment line: {line!r}"
            assert m.group(1) not in types, f"duplicate TYPE {m.group(1)}"
            types[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, raw, val = m.groups()
        labels = dict(re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"', raw))
        samples.append((name, labels, float(val)))
    return types, samples


def check_prom(text: str):
    """Cross-checks beyond line syntax: every sample belongs to a typed
    family, counters end in _total, histogram buckets are cumulative and
    their +Inf bucket equals _count."""
    types, samples = parse_prom(text)
    hist_series: dict[tuple, list] = {}
    for name, labels, val in samples:
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) == "histogram":
                fam = base
        assert fam in types, f"sample {name} has no # TYPE"
        if types[fam] == "counter":
            assert name.endswith("_total"), f"counter {name} missing _total"
        if types[fam] == "histogram" and name.endswith("_bucket"):
            # key on the FULL label set minus le — per-op/per-phase series
            # (wait_us{op=...}, phase_us{phase=...}) are distinct histograms
            # sharing one family
            key = (fam, tuple(sorted((k, v) for k, v in labels.items()
                                     if k != "le")))
            hist_series.setdefault(key, []).append(
                (float("inf") if labels["le"] == "+Inf" else float(labels["le"]),
                 val))
    for (fam, lab_key), rows in hist_series.items():
        rows.sort()
        cums = [v for _, v in rows]
        assert cums == sorted(cums), f"{fam}{{{lab_key}}} buckets not cumulative"
        count = next(v for n, lab, v in samples
                     if n == f"{fam}_count"
                     and tuple(sorted(lab.items())) == lab_key)
        assert rows[-1][0] == float("inf") and rows[-1][1] == count, \
            f"{fam}{{{lab_key}}} +Inf bucket != _count"
    return types, samples


# ----------------------------------------------------------------- units


class TestHistogramBuckets:
    def test_cumulative_snapshot(self):
        h = Histogram()
        for v in (1, 3, 3, 100):
            h.update(v)
        snap = h.snapshot()
        assert snap["count"] == 4 and snap["sum"] == 107
        bounds = [b for b, _ in snap["buckets"]]
        cums = [c for _, c in snap["buckets"]]
        assert bounds == sorted(bounds)
        assert cums == sorted(cums), "bucket counts must be cumulative"
        assert cums[-1] == snap["count"], "all samples below 2**32 bound"
        # every emitted bound's cumulative count really is #observations <= it
        assert dict(snap["buckets"])[1.0] == 1
        assert dict(snap["buckets"])[4.0] == 3

    def test_empty(self):
        snap = Histogram().snapshot()
        assert snap["count"] == 0 and snap["buckets"] == []


class TestPromRender:
    def test_render_is_valid_exposition(self):
        reg = metrics.registry("obs_prom_unit")
        reg.incr("widgets")            # gains _total
        reg.incr("frobs_total", 5)     # keeps single _total
        reg.gauge("depth", 3.5)
        for v in (10, 20, 20_000):
            reg.observe("lat_us", v)
        types, samples = check_prom(prom.render(metrics.all_snapshots()))
        names = {n for n, _, _ in samples}
        assert "hdrf_widgets_total" in names
        assert "hdrf_frobs_total" in names and "hdrf_frobs_total_total" not in names
        assert "hdrf_depth" in names and types["hdrf_depth"] == "gauge"
        assert types["hdrf_lat_us"] == "histogram"
        assert any(lab.get("registry") == "obs_prom_unit"
                   for _, lab, _ in samples)

    def test_same_family_across_registries(self):
        a = metrics.registry("obs_prom_a")
        b = metrics.registry("obs_prom_b")
        a.incr("shared_ops")
        b.incr("shared_ops", 2)
        text = prom.render(metrics.all_snapshots())
        assert text.count("# TYPE hdrf_shared_ops_total counter") == 1
        check_prom(text)

    def test_label_suffix_keys_render_as_labels(self):
        """``name|k=v`` keys (per-op wait_us, per-phase phase_us) render as
        extra labels on the BASE family — one # TYPE, distinct series."""
        reg = metrics.registry("obs_prom_lbl")
        for v in (10, 20):
            reg.observe("io_us", v)
            reg.observe("io_us|op=cdc", v)
            reg.observe("io_us|op=sha", 2 * v)
        reg.incr("ops|op=cdc")
        text = prom.render(metrics.all_snapshots())
        types, samples = check_prom(text)
        assert text.count("# TYPE hdrf_io_us histogram") == 1
        ops = {lab.get("op") for n, lab, _ in samples
               if n == "hdrf_io_us_count"
               and lab.get("registry") == "obs_prom_lbl"}
        assert ops == {None, "cdc", "sha"}
        assert any(n == "hdrf_ops_total" and lab.get("op") == "cdc"
                   for n, lab, _ in samples)


class TestLedger:
    def test_dispatch_readback_counts_and_stamp(self):
        before = device_ledger.stamp()
        tok = device_ledger.dispatch("obs.unit", batch=4, h2d_bytes=1024,
                                     key=("obs-shape", 4))
        device_ledger.readback(tok, d2h_bytes=64)
        device_ledger.readback(None)           # None-safe (skipped dispatch)
        device_ledger.transfer("d2h", "obs.copy", 32)
        d = device_ledger.delta(before)
        assert d["dispatch_total"] == 1 and d["readback_total"] == 1
        assert d["h2d_bytes_total"] == 1024 and d["d2h_bytes_total"] == 96
        assert d["compiles_total"] >= 1      # first sighting of the key
        # the same shape key must not count a second compile
        before2 = device_ledger.stamp()
        device_ledger.readback(
            device_ledger.dispatch("obs.unit", key=("obs-shape", 4)))
        assert device_ledger.delta(before2)["compiles_total"] == 0

    def test_events_carry_trace_context(self):
        tr = tracing.tracer("obs_ledger_unit")
        with tr.span("ledger_linkage") as sp:
            device_ledger.readback(device_ledger.dispatch("obs.linked"))
        evs = [e for e in device_ledger.events_snapshot()
               if e["op"] == "obs.linked"]
        assert evs, "dispatch event missing from the ring"
        assert evs[-1]["trace_id"] == f"{sp.trace_id:016x}"
        assert evs[-1]["span_id"] == f"{sp.span_id:016x}"
        # events are msgpack/JSON-plain
        json.dumps(evs[-1])

    def test_chrome_trace_includes_ledger_rows(self):
        tr = tracing.tracer("obs_chrome_unit")
        with tr.span("chrome_root") as sp:
            device_ledger.readback(device_ledger.dispatch("obs.chrome"))
        tid = f"{sp.trace_id:016x}"
        doc = tracing.chrome_trace(tracing.all_span_snapshots(),
                                   device_ledger.events_snapshot(),
                                   trace_id=tid)
        evs = doc["traceEvents"]
        assert any(e.get("cat") == "span" and e["name"] == "chrome_root"
                   for e in evs)
        assert any(e.get("cat") == "device_ledger"
                   and e["args"]["trace_id"] == tid for e in evs)
        assert all(e["ph"] in ("M", "X") for e in evs)


class TestWatchdog:
    def test_scan_flags_once_per_budget(self):
        events = []
        wd = StallWatchdog("obs-unit", budget_s=10.0, tick_s=999)
        base = wd.stall_count()
        with fault_injection.inject("watchdog.stall",
                                    lambda **kw: events.append(kw)):
            with wd.track("slow_op"):
                t0 = time.monotonic()
                assert wd.scan(now=t0 + 1) == 0          # within budget
                assert wd.scan(now=t0 + 11) == 1         # over budget: flag
                assert wd.scan(now=t0 + 12) == 0         # already flagged
                assert wd.scan(now=t0 + 22) == 1         # a further budget
            assert wd.scan(now=t0 + 99) == 0             # op finished
        assert wd.stall_count() - base == 2
        assert [e["op"] for e in events] == ["slow_op", "slow_op"]
        recs = wd.stalls()
        assert recs and recs[-1]["op"] == "slow_op" and recs[-1]["stacks"]

    def test_inflight_and_stacks(self):
        wd = StallWatchdog("obs-unit2", budget_s=5.0, tick_s=999)
        with wd.track("visible"):
            ops = [e["op"] for e in wd.inflight()]
            assert "visible" in ops
        assert wd.inflight() == []
        stacks = thread_stacks()
        assert any("test_inflight_and_stacks" in "".join(frames)
                   for frames in stacks.values())


# ------------------------------------------------------------- cluster e2e


@pytest.fixture(scope="class")
def obs_cluster():
    with MiniCluster(n_datanodes=1, replication=1, block_size=256 * 1024,
                     dn_config_overrides={"status_port": 0}) as mc:
        gw = HttpGateway(mc.namenode.addr).start()
        try:
            yield mc, gw
        finally:
            gw.stop()


class TestEndpoints:
    def test_prom_from_gateway_and_datanode(self, obs_cluster):
        mc, gw = obs_cluster
        with mc.client() as c:
            c.write("/obs/prom", blob(1, 64 * 1024), scheme="dedup_lz4")
        # daemon status endpoint (DN opted in via status_port=0)
        dn = mc.datanodes[0]
        host, port = dn._status.addr
        types, samples = check_prom(
            _get(f"http://{host}:{port}/prom").decode())
        regs = {lab.get("registry") for _, lab, _ in samples}
        assert "datanode" in regs
        # gateway endpoint merges its own + the NameNode's registries
        types, samples = check_prom(
            _get(f"http://{gw.addr[0]}:{gw.addr[1]}/prom").decode())
        regs = {lab.get("registry") for _, lab, _ in samples}
        assert "namenode" in regs

    def test_status_metrics_and_stacks(self, obs_cluster):
        mc, gw = obs_cluster
        host, port = mc.datanodes[0]._status.addr
        snaps = json.loads(_get(f"http://{host}:{port}/metrics"))
        assert "datanode" in snaps and "counters" in snaps["datanode"]
        stacks = json.loads(_get(f"http://{host}:{port}/stacks"))
        assert stacks["threads"] and "inflight" in stacks
        gstacks = json.loads(_get(f"http://{gw.addr[0]}:{gw.addr[1]}/stacks"))
        assert gstacks["threads"]

    def test_rpc_trace_roundtrip(self, obs_cluster):
        mc, _ = obs_cluster
        tr = tracing.tracer("obs_rpc_client")
        with tr.span("client.ls") as sp:
            with mc.client() as c:
                c.ls("/")
        tid, sid = f"{sp.trace_id:016x}", f"{sp.span_id:016x}"
        server = [s for s in tracing.all_span_snapshots()
                  if s["tracer"] == "rpc.namenode" and s["trace_id"] == tid]
        assert server, "NameNode RPC span did not resume the client trace"
        assert any(s["parent_id"] == sid for s in server), \
            "server span's parent is not the client span"

    def test_datatransfer_trace_roundtrip(self, obs_cluster):
        mc, _ = obs_cluster
        tr = tracing.tracer("obs_dt_client")
        data = blob(2, 96 * 1024)
        with tr.span("client.write") as sp:
            with mc.client() as c:
                c.write("/obs/dt", data, scheme="lz4")
        tid = f"{sp.trace_id:016x}"
        spans = [s for s in tracing.all_span_snapshots()
                 if s["trace_id"] == tid]
        xceiver = [s for s in spans if s["name"].startswith("xceiver.")]
        assert xceiver, "DN xceiver span did not resume the wire trace"
        # the receiver's reduce_block span nests under the xceiver span
        reduce = [s for s in spans if s["name"] == "reduce_block"]
        assert reduce
        xc_ids = {s["span_id"] for s in xceiver}
        assert all(s["parent_id"] in xc_ids for s in reduce)

    def test_watchdog_flags_delayed_op(self, obs_cluster):
        """An op that outlives its budget gets flagged WHILE in flight.
        The injected packet handler drives a deterministic watchdog pass
        with a synthetic clock from inside the stalled xceiver op itself
        (the background thread does the same every tick_s; the manual
        scan keeps the test free of real 30 s waits)."""
        mc, _ = obs_cluster
        dn = mc.datanodes[0]
        base = dn.watchdog.stall_count()
        fired = []
        hit = []

        def slow_packet(**kw):
            if not hit:                      # one packet is enough
                hit.append(1)
                assert any(e["op"].startswith("xceiver.")
                           for e in dn.watchdog.inflight())
                dn.watchdog.scan(now=time.monotonic() + 60.0)
        with fault_injection.inject("block_receiver.packet", slow_packet), \
                fault_injection.inject("watchdog.stall",
                                       lambda **kw: fired.append(kw)):
            with mc.client() as c:
                c.write("/obs/slow", blob(3, 64 * 1024), scheme="direct")
        assert dn.watchdog.stall_count() > base, "stall never flagged"
        assert any(e["op"].startswith("xceiver.") for e in fired)
        recs = dn.watchdog.stalls()
        assert recs and recs[-1]["stacks"], "stall record missing stacks"
        # the stall surfaces on the /stacks endpoint too
        host, port = dn._status.addr
        body = json.loads(_get(f"http://{host}:{port}/stacks"))
        assert body.get("stalls")


class TestTraceAssembly:
    def test_e2e_chrome_trace_with_worker(self):
        """The acceptance-criteria trace: one write through a real worker
        subprocess (device backend on the virtual mesh) shows up at the
        gateway's /traces?format=chrome as one trace with the client ->
        NN rpc -> DN xceiver -> worker chain AND >= 1 linked device-ledger
        event (the worker's resident-pipeline dispatches)."""
        base = blob(7, 32 * 1024)
        data = base * 3 + blob(8, 32 * 1024)   # dedup-friendly, 128 KiB
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=256 * 1024, tpu_worker=True,
                         worker_backend="tpu") as mc:
            gw = HttpGateway(mc.namenode.addr).start()
            try:
                tr = tracing.tracer("obs_e2e_client")
                with tr.span("client.write") as root:
                    with mc.client() as c:
                        c.write("/obs/e2e", data, scheme="dedup_lz4")
                with mc.client() as c:
                    assert c.read("/obs/e2e") == data
                tid = f"{root.trace_id:016x}"
                body = _get(f"http://{gw.addr[0]}:{gw.addr[1]}"
                            f"/traces?format=chrome&trace_id={tid}")
                doc = json.loads(body)
            finally:
                gw.stop()
        evs = doc["traceEvents"]
        spans = [e for e in evs if e.get("cat") == "span"]
        names = {e["name"] for e in spans}
        assert "client.write" in names
        assert any(n.startswith("xceiver.") for n in names)
        assert any(n.startswith("worker.") for n in names), \
            f"worker span missing from {sorted(names)}"
        assert any(s["args"]["parent_id"] == f"{root.span_id:016x}"
                   for s in spans), "nothing chained to the client root"
        # every non-root span's ancestry resolves back to the client span
        by_id = {e["args"]["span_id"]: e for e in spans}
        root_sid = f"{root.span_id:016x}"
        worker = next(e for e in spans if e["name"].startswith("worker."))
        sid, hops = worker["args"]["parent_id"], 0
        while sid != root_sid:
            assert sid in by_id, f"broken parent chain at {sid}"
            sid = by_id[sid]["args"]["parent_id"]
            hops += 1
            assert hops < 32
        led = [e for e in evs if e.get("cat") == "device_ledger"]
        assert led, "no device-ledger event linked into the trace"
        assert all(e["args"]["trace_id"] == tid for e in led)
        # at least three daemons contributed rows (client, DN, worker, ...)
        assert len({e["pid"] for e in spans}) >= 3

    def test_gateway_traces_json_merge(self, ):
        with MiniCluster(n_datanodes=1, replication=1) as mc:
            gw = HttpGateway(mc.namenode.addr).start()
            try:
                with mc.client() as c:
                    c.write("/obs/merge", blob(9, 32 * 1024), scheme="lz4")
                doc = json.loads(
                    _get(f"http://{gw.addr[0]}:{gw.addr[1]}/traces"))
            finally:
                gw.stop()
        tracers = {s["tracer"] for s in doc["spans"]}
        assert "rpc.namenode" in tracers, tracers
        assert "datanode" in tracers, tracers
        # merged view dedupes: span ids unique
        sids = [s["span_id"] for s in doc["spans"]]
        assert len(sids) == len(set(sids))


# ------------------------------------------------------- bench contract


class TestBenchContract:
    def test_bench_emits_one_json_line_with_ledger(self):
        """bench.py's stdout contract (CLAUDE.md: exactly ONE JSON line)
        now including the dispatch-ledger delta and stall count."""
        from hdrf_tpu.utils.cleanenv import clean_cpu_env
        env = clean_cpu_env(8, keep_existing_count=True)
        env["HDRF_BENCH_SMOKE"] = "1"
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
        assert out.returncode == 0, out.stderr[-2000:]
        lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, f"stdout must be ONE line, got {lines!r}"
        doc = json.loads(lines[0])
        assert doc["unit"] == "MB/s" and "value" in doc
        assert "stalls" in doc
        for key in ("dispatch_total", "readback_total", "compiles_total",
                    "stall_total", "h2d_bytes_total", "d2h_bytes_total"):
            assert key in doc["ledger"], f"ledger missing {key}"
        # reduction-effectiveness + health-intelligence fields: the dedup
        # ratio recomputed from the pass's chunk index (>= 1.0 by
        # definition) and the outlier detector's slow-peer verdict (0 —
        # the bench runs no cluster)
        assert float(doc["dedup_ratio"]) >= 1.0
        assert int(doc["slow_peer_count"]) == 0
        # degraded-mode health of the run: no breaker tripped, no write
        # fell back mid-bench (either would taint the throughput verdict)
        assert int(doc["resilience"]["breaker_open_total"]) == 0
        assert int(doc["resilience"]["degraded_writes"]) == 0
        # write-path phase profile: the e2e window decomposed into the
        # profiler's exclusive classes (sums to wall within rounding) with
        # the overlap ratios alongside
        pp = doc["phase_profile"]
        assert set(pp["classes"]) == {"host_busy", "device_busy",
                                      "transport_wait", "idle"}
        assert pp["wall_s"] > 0
        assert abs(sum(pp["classes"].values()) - pp["wall_s"]) < 0.005
        assert 0.0 <= pp["overlap_efficiency"] <= 1.0
        assert 0.0 <= pp["attributed_frac"] <= 1.0
        # the smoke e2e pass runs real CDC+SHA + WAL commits: both phases
        # must have been attributed some exclusive time
        assert pp["phases"].get("reduce_compute", 0) > 0
        assert pp["phases"].get("wal_commit", 0) > 0
        # pipeline-depth stamp (same shape in the no-TPU and TPU prints):
        # configured depth, WAL group-commit batches, overlap efficiency
        pl = doc["pipeline"]
        assert int(pl["depth"]) >= 1
        assert int(pl["group_commit_batches"]) >= 0
        assert 0.0 <= float(pl["overlap_efficiency"]) <= 1.0
        # EC cold-tier stamp: the in-bench RS(6,3) exercise encodes one
        # container (9 stripes) and reads it back degraded (all-data
        # erasures -> decode through parity), so both counters are live;
        # the tier's expansion sits at ~(k+m)/k = 1.5
        ec = doc["ec"]
        assert int(ec["stripes_encoded"]) >= 9
        assert int(ec["degraded_reads"]) >= 1
        assert int(ec["repair_bytes"]) >= 0
        assert 1.49 <= float(ec["storage_ratio"]) <= 1.51

    def test_benchmarks_ec_one_json_line(self):
        """python -m hdrf_tpu.benchmarks ec: the paired encode / intact /
        degraded-read slope harness prints exactly ONE JSON line, with the
        parity pinned against the GF log/antilog oracle before timing."""
        from hdrf_tpu.utils.cleanenv import clean_cpu_env
        env = clean_cpu_env(8, keep_existing_count=True)
        out = subprocess.run(
            [sys.executable, "-m", "hdrf_tpu.benchmarks", "ec",
             "--mb", "2", "--inner", "2"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
        assert out.returncode == 0, out.stderr[-2000:]
        lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, f"stdout must be ONE line, got {lines!r}"
        doc = json.loads(lines[0])
        assert doc["parity_oracle_ok"] is True
        assert doc["k"] == 6 and doc["m"] == 3
        for key in ("encode_MBps", "intact_read_MBps",
                    "degraded_read_MBps"):
            assert float(doc[key]) > 0, key
        assert 1.49 <= float(doc["storage_ratio"]) <= 1.51
