"""Quorum journal: JournalNode + QuorumJournal + NN-over-quorum HA.

Re-expresses the reference's qjournal test surface
(TestQuorumJournalManager, TestJournalNode, MiniQJMHACluster): majority-ack
durability, epoch fencing at the nodes, segment recovery on promotion
(longest-log selection + divergent-tail truncation), purge + image
bootstrap for a gapped reader, and the edit-log group commit that batches
concurrent handlers into one journal round (FSEditLog.logSync design)."""

from __future__ import annotations

import os
import threading
import time

import msgpack
import pytest

from hdrf_tpu.server.journal import (FencedError, JournalGapError,
                                     JournalNode, QuorumJournal,
                                     QuorumLostError)


def _payload(seq: int, tag: str = "op") -> bytes:
    return msgpack.packb([seq, tag, f"/p{seq}"])


@pytest.fixture()
def jns(tmp_path):
    nodes = [JournalNode(str(tmp_path / f"jn{i}")).start() for i in range(3)]
    yield nodes
    for n in nodes:
        try:
            n.stop()
        except Exception:  # noqa: BLE001
            pass


class TestQuorumJournal:
    def test_append_read_roundtrip(self, jns):
        q = QuorumJournal([n.addr for n in jns])
        q.claim_epoch()
        q.append_frames([_payload(1), _payload(2)], first_seq=1)
        q.append_frames([_payload(3)], first_seq=3)
        assert q.read(0) == [_payload(1), _payload(2), _payload(3)]
        assert q.read(2) == [_payload(3)]
        q.close()

    def test_majority_survives_one_node_down(self, jns):
        q = QuorumJournal([n.addr for n in jns])
        q.claim_epoch()
        q.append_frames([_payload(1)], first_seq=1)
        jns[2].stop()
        q.append_frames([_payload(2)], first_seq=2)  # 2/3 acks = durable
        assert q.read(0) == [_payload(1), _payload(2)]
        q.close()

    def test_quorum_lost_raises(self, jns):
        q = QuorumJournal([n.addr for n in jns], timeout=1.0)
        q.claim_epoch()
        jns[1].stop()
        jns[2].stop()
        with pytest.raises(QuorumLostError):
            q.append_frames([_payload(1)], first_seq=1)
        q.close()

    def test_epoch_fences_old_writer(self, jns):
        old = QuorumJournal([n.addr for n in jns])
        old.claim_epoch()
        old.append_frames([_payload(1)], first_seq=1)
        new = QuorumJournal([n.addr for n in jns])
        new.claim_epoch()
        with pytest.raises(FencedError):
            old.append_frames([_payload(2)], first_seq=2)
        new.append_frames([_payload(2)], first_seq=2)
        assert new.read(0) == [_payload(1), _payload(2)]
        old.close()
        new.close()

    def test_recovery_copies_longest_log(self, jns):
        """An edit acked by a majority must survive promotion even when the
        new writer can only reach a different majority."""
        q = QuorumJournal([n.addr for n in jns])
        q.claim_epoch()
        q.append_frames([_payload(1)], first_seq=1)
        # jn0 misses an append (down), comes back; jn2 goes down BEFORE
        # recovery, so the new writer must recover seq 2 from jn1 alone.
        jns[0].stop()
        q.append_frames([_payload(2)], first_seq=2)
        q.close()
        jn0 = JournalNode(jns[0]._dir).start()
        jns[0] = jn0
        jns[2].stop()
        new = QuorumJournal([jn0.addr, jns[1].addr, jns[2].addr],
                            timeout=1.0)
        new.claim_epoch()
        # recovery re-journaled seq 2 to jn0; a read via any majority sees it
        assert new.read(0) == [_payload(1), _payload(2)]
        st = jn0.rpc_jn_state()
        assert st["last_seq"] == 2
        new.close()

    def test_unacked_record_resurrected_consistently(self, jns):
        """Accepted-recovery semantics (like QJM): an unacked dead-epoch
        record that recovery adopts (longest log among promisers) becomes
        canon on EVERY node — resurrection is legal, divergence is not."""
        old = QuorumJournal([n.addr for n in jns])
        old.claim_epoch()
        old.append_frames([_payload(1)], first_seq=1)
        # old writer got seq 2 onto ONLY jn0 before dying:
        jns[0].rpc_jn_journal(epoch=old._epoch, first_seq=2,
                              payloads=[_payload(2, "old")])
        new = QuorumJournal([n.addr for n in jns])
        new.claim_epoch()   # adopts jn0's longer log; re-journals seq 2
        recs = [msgpack.unpackb(p, raw=False)
                for p in new.read(0, readonly=False)]
        assert [r[1] for r in recs] == ["op", "old"]
        for jn in jns:      # every node agrees
            r = jn.rpc_jn_read(after_seq=0)
            assert [msgpack.unpackb(p, raw=False)[1]
                    for _, p in r["records"]] == ["op", "old"]
        old.close()
        new.close()

    def test_divergent_tail_truncated_on_rejoin(self, jns):
        """A node that was down through a failover holds a stale dead-epoch
        tail; when it rejoins, the new writer's catch-up must REPLACE that
        tail, not append after it."""
        old = QuorumJournal([n.addr for n in jns])
        old.claim_epoch()
        old.append_frames([_payload(1)], first_seq=1)
        jns[0].rpc_jn_journal(epoch=old._epoch, first_seq=2,
                              payloads=[_payload(2, "old")])
        d0 = jns[0]._dir
        jns[0].stop()       # down during the failover
        new = QuorumJournal([n.addr for n in jns], timeout=1.0)
        new.claim_epoch()   # majority = jn1+jn2 (last=1): "old" not adopted
        new.append_frames([_payload(2, "new")], first_seq=2)
        jns[0] = JournalNode(d0).start()
        new2 = QuorumJournal([jns[0].addr, jns[1].addr, jns[2].addr],
                             timeout=1.0)
        new2._epoch = new._epoch
        new2._cache = list(new._cache)
        new2.append_frames([_payload(3)], first_seq=3)
        r = jns[0].rpc_jn_read(after_seq=0)
        assert [msgpack.unpackb(p, raw=False)[1]
                for _, p in r["records"]] == ["op", "new", "op"]
        old.close()
        new.close()
        new2.close()

    def test_purge_and_gap_detection(self, jns):
        q = QuorumJournal([n.addr for n in jns])
        q.claim_epoch()
        q.append_frames([_payload(i) for i in range(1, 6)], first_seq=1)
        q.purge(3)
        assert q.read(3) == [_payload(4), _payload(5)]
        with pytest.raises(JournalGapError):
            q.read(0)  # records 1..3 purged: reader must bootstrap an image
        q.close()

    def test_committed_floor_bounds_tailer(self, jns):
        """A record on a minority is invisible to a readonly tailer."""
        q = QuorumJournal([n.addr for n in jns])
        q.claim_epoch()
        q.append_frames([_payload(1)], first_seq=1)
        jns[0].rpc_jn_journal(epoch=q._epoch, first_seq=2,
                              payloads=[_payload(2)])
        assert q.read(0, readonly=True) == [_payload(1)]
        q.close()

    def test_journalnode_restart_keeps_records(self, jns, tmp_path):
        q = QuorumJournal([n.addr for n in jns])
        q.claim_epoch()
        q.append_frames([_payload(1), _payload(2)], first_seq=1)
        d = jns[1]._dir
        jns[1].stop()
        jns[1] = JournalNode(d).start()
        st = jns[1].rpc_jn_state()
        assert st["last_seq"] == 2
        q.close()


class TestEditLogGroupCommit:
    def test_concurrent_appends_batch_into_few_journal_rounds(self, tmp_path):
        from hdrf_tpu.server.editlog import EditLog

        log = EditLog(str(tmp_path / "nn"))
        log.claim_epoch()
        log.replay(lambda rec: None)
        log.open_for_append(lambda: None)
        counted = {"n": 0}
        orig = log.journal.append_frames

        def counting(payloads, first_seq):
            counted["n"] += 1
            return orig(payloads, first_seq)
        log.journal.append_frames = counting

        def worker(k):
            for i in range(50):
                log.sync(log.append_async(["mkdir", f"/w{k}/{i}"]))
        ts = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert log.seq == 400
        # group commit: far fewer journal rounds than records
        assert counted["n"] < 400
        log.close()
        # every record durable + replayable
        log2 = EditLog(str(tmp_path / "nn"))
        seen = []
        log2.replay(lambda rec: seen.append(rec[1]), readonly=True)
        assert len(seen) == 400
        log2.close()

    def test_sync_failure_restores_buffer_order(self, tmp_path):
        from hdrf_tpu.server.editlog import EditLog

        log = EditLog(str(tmp_path / "nn"))
        log.claim_epoch()
        log.replay(lambda rec: None)
        log.open_for_append(lambda: None)
        seq1 = log.append_async(["mkdir", "/a"])
        orig = log.journal.append_frames
        calls = {"n": 0}

        def flaky(payloads, first_seq):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("disk hiccup")
            return orig(payloads, first_seq)
        log.journal.append_frames = flaky
        with pytest.raises(OSError):
            log.sync(seq1)
        log.sync(seq1)  # retry succeeds; order preserved
        log.close()
        log2 = EditLog(str(tmp_path / "nn"))
        seen = []
        log2.replay(lambda rec: seen.append(rec), readonly=True)
        assert seen == [["mkdir", "/a"]]
        log2.close()


class TestQuorumHaCluster:
    def test_ha_over_quorum_with_journalnode_down(self):
        from hdrf_tpu.testing.minicluster import MiniCluster

        with MiniCluster(n_datanodes=2, replication=2, ha=True,
                         journal_nodes=3) as mc:
            with mc.client("q") as c:
                c.write("/q/a", b"alpha" * 2000, scheme="direct")
                mc.stop_journalnode(2)          # quorum of 2/3 remains
                c.write("/q/b", b"beta" * 2000, scheme="direct")
                time.sleep(1.0)                 # standby tails the quorum
                mc.failover()
                assert c.read("/q/a") == b"alpha" * 2000
                assert c.read("/q/b") == b"beta" * 2000
                c.write("/q/c", b"gamma" * 2000, scheme="direct")
                assert c.read("/q/c") == b"gamma" * 2000

    def test_partitioned_ex_active_cannot_ack(self):
        """Split brain: the old active keeps running but the standby claims
        the quorum epoch; the old active's next write is fenced at the
        JournalNodes and it demotes itself."""
        from hdrf_tpu.client.filesystem import HdrfClient
        from hdrf_tpu.testing.minicluster import MiniCluster

        with MiniCluster(n_datanodes=1, replication=1, ha=True,
                         journal_nodes=3) as mc:
            old_active = mc.namenode
            with mc.client("s") as c:
                c.write("/s/a", b"x" * 1000, scheme="direct")
            time.sleep(0.8)
            # promote the standby WITHOUT stopping the old active
            mc.standby.rpc_transition_to_active()
            with pytest.raises(Exception):
                with HdrfClient([old_active.addr], name="split") as c2:
                    c2.mkdir("/s/split")
            assert old_active.role == "standby"  # demoted on fencing

    def test_standby_bootstraps_image_past_purge(self):
        """A standby that starts after the journal was purged fetches the
        fsimage from the active peer instead of failing forever."""
        import dataclasses

        from hdrf_tpu.server.namenode import NameNode
        from hdrf_tpu.testing.minicluster import MiniCluster

        with MiniCluster(n_datanodes=1, replication=1, ha=False,
                         journal_nodes=3) as mc:
            with mc.client("b") as c:
                for i in range(30):
                    c.mkdir(f"/boot/d{i}")
            mc.namenode.rpc_save_namespace()    # checkpoint purges the quorum
            sb_cfg = dataclasses.replace(
                mc.nn_config, role="standby", port=0,
                meta_dir=os.path.join(mc.base_dir, "name-late"),
                peers=[list(mc.namenode.addr)], tail_interval_s=0.2)
            sb = NameNode(sb_cfg).start()
            try:
                deadline = time.time() + 10
                while time.time() < deadline:
                    if sb.rpc_ha_state()["seq"] >= \
                            mc.namenode.rpc_ha_state()["seq"]:
                        break
                    time.sleep(0.2)
                st = sb.rpc_listing("/boot")
                assert len(st) == 30
            finally:
                sb.stop()

    def test_journal_web_page_renders_quorum_state(self):
        """webapps/journal analog: the gateway's /journal page shows each
        JournalNode's epoch/sequence state, and marks a downed node."""
        import urllib.request

        from hdrf_tpu.server.http_gateway import HttpGateway
        from hdrf_tpu.testing.minicluster import MiniCluster

        with MiniCluster(n_datanodes=1, replication=1, ha=True,
                         journal_nodes=3) as mc:
            with mc.client("jw") as c:
                c.write("/jw/a", b"j" * 5000, scheme="direct")
            gw = HttpGateway(mc.namenode.addr).start()
            try:
                base = f"http://{gw.addr[0]}:{gw.addr[1]}"
                with urllib.request.urlopen(base + "/journal") as r:
                    page = r.read().decode()
                assert page.count("<td>up</td>") == 3
                assert "promised epoch" in page
                mc.stop_journalnode(2)
                with urllib.request.urlopen(base + "/journal") as r:
                    page = r.read().decode()
                assert page.count("<td>up</td>") == 2 and "down" in page
            finally:
                gw.stop()
