"""Tests for libhdrf_native.so: SHA-256, Gear-CDC, LZ4 block codec, CRC32C.

Cross-implementation oracles: hashlib for SHA-256, a pure-Python LZ4 block
decoder for format conformance, numpy recomputation for gear candidates, and
fused-vs-two-phase CDC equivalence.
"""

import hashlib
import os
import zlib

import numpy as np
import pytest

from hdrf_tpu import native

RNG = np.random.default_rng(7)


def lz4_decompress_pyref(src: bytes) -> bytes:
    """Pure-Python LZ4 block decoder — format conformance oracle."""
    out = bytearray()
    i = 0
    while i < len(src):
        token = src[i]; i += 1
        litlen = token >> 4
        if litlen == 15:
            while True:
                b = src[i]; i += 1
                litlen += b
                if b != 255:
                    break
        out += src[i:i + litlen]; i += litlen
        if i >= len(src):
            break
        offset = src[i] | (src[i + 1] << 8); i += 2
        matchlen = token & 0xF
        if matchlen == 15:
            while True:
                b = src[i]; i += 1
                matchlen += b
                if b != 255:
                    break
        matchlen += 4
        assert 0 < offset <= len(out)
        for _ in range(matchlen):
            out.append(out[-offset])
    return bytes(out)


def gear_hash_pyref(data: np.ndarray, table: np.ndarray) -> np.ndarray:
    """Rolling gear hash value after each byte, h = (h<<1) + G[b] (mod 2^32)."""
    h = np.uint64(0)
    out = np.empty(len(data), dtype=np.uint32)
    g = table.astype(np.uint64)
    for i, b in enumerate(data):
        h = ((h << np.uint64(1)) + g[b]) & np.uint64(0xFFFFFFFF)
        out[i] = h
    return out


# ------------------------------------------------------------------ SHA-256

def test_sha256_vs_hashlib():
    for n in [0, 1, 55, 56, 63, 64, 65, 1000, 1 << 16]:
        data = RNG.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert native.sha256(data) == hashlib.sha256(data).digest(), n


def test_sha256_batch():
    data = RNG.integers(0, 256, 1 << 16, dtype=np.uint8)
    offs = np.array([0, 100, 5000, 65535], dtype=np.uint64)
    lens = np.array([100, 4900, 60000, 1], dtype=np.uint64)
    got = native.sha256_batch(data, offs, lens)
    for i in range(len(offs)):
        want = hashlib.sha256(data[int(offs[i]):int(offs[i]) + int(lens[i])].tobytes()).digest()
        assert got[i].tobytes() == want


# ------------------------------------------------------------------ CDC

def test_gear_candidates_vs_pyref():
    data = RNG.integers(0, 256, 4096, dtype=np.uint8)
    table = native.gear_table()
    mask = 0xFF000000  # 8 bits -> ~16 candidates in 4 KiB
    hashes = gear_hash_pyref(data, table)
    want = [p + 1 for p in range(len(data)) if p + 1 >= 32 and (hashes[p] & mask) == 0]
    got = native.gear_candidates(data, mask).tolist()
    assert got == want


def test_cdc_fused_equals_two_phase():
    mask = 0xFFF00000 >> 8  # 12 effective bits
    for n in [0, 10, 100, 5000, 1 << 18]:
        data = RNG.integers(0, 256, n, dtype=np.uint8)
        cand = native.gear_candidates(data, mask)
        cuts_a = native.cdc_select(cand, n, 512, 8192).tolist()
        cuts_b = native.cdc_chunk(data, mask, 512, 8192).tolist()
        assert cuts_a == cuts_b, (n, cuts_a[:5], cuts_b[:5])


def test_cdc_chunk_invariants():
    data = RNG.integers(0, 256, 1 << 18, dtype=np.uint8)
    min_c, max_c = 512, 8192
    cuts = native.cdc_chunk(data, 0x3FF, min_c, max_c)
    assert cuts[-1] == len(data)
    sizes = np.diff(np.concatenate([[0], cuts]))
    assert (sizes <= max_c).all()
    assert (sizes[:-1] >= min_c).all()  # final chunk may be short


def test_cdc_content_defined_shift_invariance():
    """Inserting bytes at the front only perturbs boundaries near the edit."""
    data = RNG.integers(0, 256, 1 << 17, dtype=np.uint8)
    shifted = np.concatenate([RNG.integers(0, 256, 97, dtype=np.uint8), data])
    cuts_a = set(native.cdc_chunk(data, 0x1FFF, 2048, 65536).tolist())
    cuts_b = {c - 97 for c in native.cdc_chunk(shifted, 0x1FFF, 2048, 65536).tolist()}
    # The tail boundaries must re-align despite the insertion.
    tail_a = {c for c in cuts_a if c > (1 << 16)}
    assert len(tail_a & cuts_b) / max(len(tail_a), 1) > 0.8


def test_cdc_empty_and_tiny():
    assert native.cdc_chunk(b"", 0xFF, 64, 1024).tolist() == []
    assert native.cdc_chunk(b"x" * 10, 0xFF, 64, 1024).tolist() == [10]
    assert native.cdc_chunk(b"x" * 2000, 0xFF, 64, 1024).tolist() == [1024, 2000]


# ------------------------------------------------------------------ LZ4

@pytest.mark.parametrize("kind", ["random", "zeros", "text", "repeats", "tiny", "empty"])
def test_lz4_roundtrip(kind):
    if kind == "random":
        data = RNG.integers(0, 256, 1 << 16, dtype=np.uint8).tobytes()
    elif kind == "zeros":
        data = b"\x00" * (1 << 16)
    elif kind == "text":
        data = (b"the quick brown fox jumps over the lazy dog. " * 2000)
    elif kind == "repeats":
        data = bytes(range(256)) * 300
    elif kind == "tiny":
        data = b"abc"
    else:
        data = b""
    comp = native.lz4_compress(data)
    assert native.lz4_decompress(comp, len(data)) == data
    if data:
        assert lz4_decompress_pyref(comp) == data  # format conformance
    if kind in ("zeros", "text", "repeats"):
        assert len(comp) < len(data) // 3


def test_lz4_compresses_zeros_hard():
    data = b"\x00" * (1 << 20)
    comp = native.lz4_compress(data)
    assert len(comp) < 5000


def test_lz4_rejects_garbage():
    with pytest.raises(RuntimeError):
        native.lz4_decompress(b"\xff\xff\xff\xff\x00", 100)


# ------------------------------------------------------------------ CRC32C

def test_crc32c_known_vectors():
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8A9136AA
    assert native.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert native.crc32c(b"123456789") == 0xE3069283


def test_crc32c_chunks():
    data = RNG.integers(0, 256, 2000, dtype=np.uint8)
    out = native.crc32c_chunks(data, 512)
    assert len(out) == 4
    for i in range(4):
        assert out[i] == native.crc32c(data[i * 512:(i + 1) * 512])


def test_crc32c_incremental():
    data = os.urandom(1000)
    c1 = native.crc32c(data)
    # zlib.crc32 is CRC32 (IEEE), not CRC32C — just ensure ours differs from a
    # wrong-poly implementation and is stable.
    assert c1 == native.crc32c(data)
    assert c1 != zlib.crc32(data)


def test_gear_candidates_dense_mask_no_truncation():
    """mask=0 makes every position>=32 a candidate; wrapper must not truncate."""
    data = RNG.integers(0, 256, 1 << 14, dtype=np.uint8)
    cand = native.gear_candidates(data, 0x0)
    assert len(cand) == (1 << 14) - 31
    assert cand[0] == 32 and cand[-1] == 1 << 14


def test_sha256_batch_bounds_check():
    data = RNG.integers(0, 256, 100, dtype=np.uint8)
    with pytest.raises(ValueError):
        native.sha256_batch(data, np.array([90], dtype=np.uint64),
                            np.array([20], dtype=np.uint64))
