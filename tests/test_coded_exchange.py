"""Coded-exchange shuffle plane (ISSUE 16 / ARCHITECTURE.md design
decision 16): partial-sum stripe repair, on-TPU compressed exchange
intermediates, and background-lane scheduling.

Covers ops/rs.py's partial-sum codecs (repair_rows / partial_sums /
xor_fold) against the GF log/antilog host oracle across EVERY 3-erasure
pattern of RS(6,3) and the tail-padding edges, the smaller-of LZ4
negotiation of server/coded_exchange.py (raw wins ties, mixed versions
stay byte-identical), the QoS control lane (utils/qos.py
BACKGROUND_TENANT: admitted + audited, NEVER shed, never debits a
foreground bucket), the coded repair path end to end on a MiniCluster
(server/ec_tier.py _gather_coded / serve_coded_read — owner ingress
~|missing| stripes instead of k, measured by the repair_wire_ratio
ledger), corrupt-contribution-as-erasure handling (the fold's CRC check
sends the owner to the classic gather, which re-gathers around the
corrupt survivor), and the mirror-plane segment-compression satellite
(server/mirror_plane.py seg_enc negotiation behind the
mirror_compress_segments knob).  Exercises the fault points
"stripe.coded_read", "coded_exchange.send" and "qos.admit".
"""

import itertools
import time

import numpy as np
import pytest

from hdrf_tpu import native
from hdrf_tpu.ops import rs
from hdrf_tpu.server import coded_exchange
from hdrf_tpu.storage import stripe_store
from hdrf_tpu.utils import fault_injection, metrics, qos, retry

_EC = metrics.registry("ec")
_CE = metrics.registry("coded_exchange")
_QOS = metrics.registry("qos")
_MIR = metrics.registry("mirror")


@pytest.fixture(autouse=True)
def _fresh_state():
    retry.reset_breakers()
    fault_injection.clear()
    yield
    retry.reset_breakers()
    fault_injection.clear()


def _wait(pred, timeout=25.0, interval=0.1, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _bytes(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()


def _coded_fold(stripes, manifest, missing, holders=3):
    """Rebuild ``missing`` via per-holder partial sums: survivors are
    round-robined across ``holders`` simulated DNs, each computes ONE
    partial_sums call over its local slice, and the folds XOR together —
    the exact split _gather_coded/serve_coded_read chain performs."""
    k, m = int(manifest["k"]), int(manifest["m"])
    shards = {i: np.frombuffer(s, dtype=np.uint8)
              for i, s in enumerate(stripes) if i not in missing}
    have = sorted(shards)[:k]
    rows = rs.repair_rows(k, m, tuple(have), tuple(missing))
    col = {s: j for j, s in enumerate(have)}
    parts = []
    for h in range(holders):
        mine = have[h::holders]
        if not mine:
            continue
        parts.append(rs.partial_sums(
            np.stack([shards[s] for s in mine]),
            rows[:, [col[s] for s in mine]]))
    return rs.xor_fold(parts)


# ------------------------------------------------ partial-sum repair codec


class TestPartialSumRepair:
    K, M = 6, 3

    def test_partial_sums_matches_gf_oracle(self):
        """The device bit-matmul partial sum is bit-identical to the
        numpy GF exp/log oracle on random stripes and coefficients."""
        rng = np.random.default_rng(5)
        stripes = rng.integers(0, 256, size=(4, 512), dtype=np.uint8)
        coeffs = rng.integers(0, 256, size=(3, 4), dtype=np.uint8)
        got = rs.partial_sums(stripes, coeffs)
        ref = rs.partial_sums_ref(stripes, coeffs)
        assert np.array_equal(got, ref)
        # zero coefficients contribute nothing
        z = rs.partial_sums(stripes, np.zeros((2, 4), dtype=np.uint8))
        assert not z.any()

    def test_fold_bit_identical_on_every_three_erasure_pattern(self):
        """All C(9,3)=84 erasure patterns of RS(6,3): the XOR-fold of
        per-holder contributions equals reconstruct_container's full
        decode, stripe for stripe, bit for bit."""
        payload = _bytes(6 * 96 + 11, seed=7)
        stripes, man = stripe_store.encode_container(payload, self.K,
                                                     self.M)
        for lost in itertools.combinations(range(self.K + self.M), 3):
            missing = list(lost)
            fold = _coded_fold(stripes, man, missing)
            oracle = stripe_store.reconstruct_container(
                {i: s for i, s in enumerate(stripes) if i not in lost},
                man, want=missing)
            for r, w in enumerate(missing):
                assert fold[r].tobytes() == oracle[w], \
                    f"pattern {lost}: stripe {w} diverged"

    def test_single_and_double_erasures_pin_too(self):
        """Sizes 1 and 2 (the common repair shapes) across every
        pattern — repair_rows handles data AND parity wants."""
        payload = _bytes(6 * 64, seed=8)
        stripes, man = stripe_store.encode_container(payload, self.K,
                                                     self.M)
        for width in (1, 2):
            for lost in itertools.combinations(
                    range(self.K + self.M), width):
                fold = _coded_fold(stripes, man, list(lost), holders=2)
                oracle = stripe_store.reconstruct_container(
                    {i: s for i, s in enumerate(stripes)
                     if i not in lost}, man, want=list(lost))
                for r, w in enumerate(lost):
                    assert fold[r].tobytes() == oracle[w]

    def test_tail_padding_edges(self):
        """Payload lengths 0, 1, k-1, k, k+1: stripe_len clamps to >= 1
        and the fold stays bit-identical through the zero pad."""
        k = self.K
        for n in (0, 1, k - 1, k, k + 1):
            payload = _bytes(n, seed=100 + n)
            stripes, man = stripe_store.encode_container(payload, k,
                                                         self.M)
            assert man["stripe_len"] >= 1
            missing = [0, k]  # one data, one parity
            fold = _coded_fold(stripes, man, missing)
            oracle = stripe_store.reconstruct_container(
                {i: s for i, s in enumerate(stripes)
                 if i not in missing}, man, want=missing)
            for r, w in enumerate(missing):
                assert fold[r].tobytes() == oracle[w], f"n={n} w={w}"

    def test_corrupt_contribution_surfaces_at_the_fold_crc(self):
        """A flipped byte in ANY survivor poisons the whole fold (the
        sum hides which) — the manifest CRC catches it, and the classic
        CRC-filtering decode over the remaining survivors recovers."""
        payload = _bytes(6 * 128, seed=9)
        stripes, man = stripe_store.encode_container(payload, self.K,
                                                     self.M)
        missing = [2]
        corrupt = list(stripes)
        bad = bytearray(corrupt[4])
        bad[7] ^= 0x5A
        corrupt[4] = bytes(bad)
        fold = _coded_fold(corrupt, man, missing)
        assert int(native.crc32c(fold[0].tobytes())) \
            != int(man["crcs"][missing[0]]), \
            "corrupt contribution went undetected"
        # erasure fallback: offer every survivor, CRC filter drops the
        # corrupt one, decode still lands bit-identically
        offered = {i: corrupt[i] for i in range(self.K + self.M)
                   if i not in missing}
        oracle = stripe_store.reconstruct_container(offered, man,
                                                    want=missing)
        good = stripe_store.reconstruct_container(
            {i: stripes[i] for i in range(self.K + self.M)
             if i not in missing}, man, want=missing)
        assert oracle[2] == good[2]


# ----------------------------------------------- smaller-of negotiation


class TestPackNegotiation:
    def test_round_trip_compressible(self):
        raw = b"the coded exchange intermediate " * 256
        blob, enc = coded_exchange.pack(raw)
        assert enc == 1 and len(blob) < len(raw)
        assert coded_exchange.unpack(blob, enc, len(raw)) == raw

    def test_incompressible_ships_raw(self):
        raw = _bytes(4096, seed=11)
        before = _CE.counter("incompressible_intermediates")
        blob, enc = coded_exchange.pack(raw)
        assert enc == 0 and blob == raw
        assert coded_exchange.unpack(blob, enc, len(raw)) == raw
        assert _CE.counter("incompressible_intermediates") > before

    def test_tiny_payload_skips_the_codec(self):
        raw = b"x" * (coded_exchange._MIN_PACK - 1)
        blob, enc = coded_exchange.pack(raw)
        assert (blob, enc) == (raw, 0)

    def test_pack_many_alignment_and_ledger(self):
        datas = [b"a" * 1024, _bytes(1024, seed=12), b"", b"b" * 700]
        raw0 = _CE.counter("pack_raw_bytes")
        wire0 = _CE.counter("pack_wire_bytes")
        out = coded_exchange.pack_many(datas)
        assert len(out) == len(datas)
        for d, (p, e) in zip(datas, out):
            assert coded_exchange.unpack(p, e, len(d)) == d
            assert len(p) <= len(d)  # negotiation can only save
        assert _CE.counter("pack_raw_bytes") - raw0 \
            == sum(len(d) for d in datas)
        assert _CE.counter("pack_wire_bytes") - wire0 \
            == sum(len(p) for p, _ in out)

    def test_book_repair_wire_ratio_gauge(self):
        wire0 = _EC.counter("repair_wire_bytes")
        rebuilt0 = _EC.counter("repair_rebuilt_bytes")
        coded_exchange.book_repair_wire(3000, 1000, relay_bytes=2000)
        assert _EC.counter("repair_wire_bytes") == wire0 + 3000
        assert _EC.counter("repair_rebuilt_bytes") == rebuilt0 + 1000
        assert _EC.counter("coded_relay_bytes") >= 2000
        with _EC._lock:
            ratio = _EC._gauges["repair_wire_ratio"]
        assert ratio == pytest.approx(
            (wire0 + 3000) / (rebuilt0 + 1000))


# -------------------------------------------------- background control lane


class TestBackgroundLane:
    def test_background_is_admitted_audited_and_never_shed(self):
        """The permit/shed audit: exhaust a foreground bucket so IT
        sheds, then push 100 background admissions + charges through the
        same controller — zero sheds, zero foreground debits, every
        admission fires the "qos.admit" audit point under the sentinel
        tenant, and the foreground world is untouched afterwards."""
        ctrl = qos.AdmissionController(rate_mb_s=1.0, burst_mb=1.0)
        ctrl.admit("hog", "stripe_write")
        ctrl.charge("hog", "stripe_write", 1 << 40)
        with pytest.raises(qos.ShedError):
            ctrl.admit("hog", "stripe_write")
        sheds0 = ctrl.sheds_total()
        bg0 = _QOS.counter("background_admits")
        admits = []
        with fault_injection.inject("qos.admit",
                                    lambda **kw: admits.append(kw)):
            with qos.background():
                assert qos.current_tenant() == qos.BACKGROUND_TENANT
                assert qos.is_background()
                for _ in range(100):
                    ctrl.admit(qos.current_tenant(), "stripe_write")
                    ctrl.charge(qos.current_tenant(), "stripe_write",
                                1 << 30)
        assert ctrl.sheds_total() == sheds0
        assert qos.BACKGROUND_TENANT not in ctrl.report()["tenant_sheds"]
        assert _QOS.counter("background_admits") >= bg0 + 100
        assert len(admits) == 100
        assert all(a["tenant"] == qos.BACKGROUND_TENANT for a in admits)
        # 100 GiB of background charges debited NO foreground bucket:
        # the anon/default lane and a light tenant still admit
        ctrl.admit(None, "read")
        ctrl.admit("light", "read")
        # the lane unbinds on exit
        assert not qos.is_background()

    def test_background_binding_nests_and_restores(self):
        with qos.bind_tenant("fg"):
            with qos.background():
                assert qos.current_tenant() == qos.BACKGROUND_TENANT
            assert qos.current_tenant() == "fg"


# ------------------------------------------------------------- cluster e2e


@pytest.fixture
def repair_cluster():
    """5 DNs, tiny containers, RS(3,2) armed; demotion flipped on by the
    test (same shape as test_ec_cold_tier's cold_cluster)."""
    from hdrf_tpu.testing.minicluster import MiniCluster

    with MiniCluster(n_datanodes=5, block_size=256 * 1024,
                     container_size=32 * 1024) as mc:
        mc.namenode.config.ec_data_shards = 3
        mc.namenode.config.ec_parity_shards = 2
        mc.namenode.config.ec_demote_after_s = 0.0
        yield mc


def _demote(mc, c, path, data):
    c.write(path, data, scheme="dedup_lz4")
    mc.namenode.config.ec_demote_after_s = 0.3
    time.sleep(0.3)
    _wait(lambda: c._call("ec_status")["demoted_blocks"] >= 1,
          msg="block demotion")
    _wait(lambda: c._call("ec_status")["striped_containers"] >= 1,
          msg="striped-container census")


def _owner_dn(mc):
    for dn in mc.datanodes:
        if dn is not None and dn.index.stats()["striped_containers"] > 0:
            return dn
    return None


class TestCodedRepairCluster:
    def test_coded_repair_cuts_owner_ingress_below_k(self, repair_cluster):
        """The acceptance bar: kill one stripe holder, let the repair
        monitor run, and the rebuilt stripes must arrive via the
        partial-sum chain — coded_repairs moves, both new fault points
        fire on the background tenant, and the wire ledger's delta shows
        owner ingress ~1x the rebuilt bytes, well below k=3.  Foreground
        tenants see zero sheds from any of it."""
        mc = repair_cluster
        data = _bytes(200_000, seed=17)
        sends, serves = [], []
        fault_injection.install(
            "coded_exchange.send", lambda **kw: sends.append(kw))
        fault_injection.install(
            "stripe.coded_read", lambda **kw: serves.append(kw))
        with mc.client("coded") as c:
            _demote(mc, c, "/coded/a", data)
            owner = _owner_dn(mc)
            assert owner is not None
            man = next(iter(owner.index.stripe_manifests().values()))
            victim = next(h[0] for h in man["holders"]
                          if h[0] != owner.dn_id)
            coded0 = _EC.counter("coded_repairs")
            wire0 = _EC.counter("repair_wire_bytes")
            rebuilt0 = _EC.counter("repair_rebuilt_bytes")
            repaired0 = _EC.counter("stripes_repaired")
            sheds0 = _QOS.counter("sheds_total")
            mc.stop_datanode(int(victim.split("-")[1]))
            _wait(lambda: _EC.counter("stripes_repaired") > repaired0,
                  msg="stripe repair")
            assert _EC.counter("coded_repairs") > coded0, \
                "repair took the classic gather, not the coded chain"
            wire = _EC.counter("repair_wire_bytes") - wire0
            rebuilt = _EC.counter("repair_rebuilt_bytes") - rebuilt0
            assert rebuilt > 0
            # owner ingress ~|missing| stripes, not k of them
            assert wire / rebuilt < int(man["k"]) - 0.5, \
                f"wire ratio {wire / rebuilt:.2f} not below k"
            assert sends, "coded_exchange.send never fired"
            assert all(s["tenant"] == qos.BACKGROUND_TENANT
                       for s in sends)
            assert serves, "stripe.coded_read never fired"
            assert _QOS.counter("sheds_total") == sheds0, \
                "background repair shed somebody"
            # the repaired group still reads bit-identically
            assert c.read("/coded/a") == data

    def test_corrupt_contribution_falls_back_and_still_heals(
            self, repair_cluster):
        """Flip a byte in one REMOTE survivor's stripe file, then kill a
        different holder: the coded fold's CRC check refuses the poisoned
        rebuild (coded_contrib_corrupt), the owner falls back to the
        classic gather which treats the corrupt survivor as one more
        erasure (repair_corrupt_survivors), and the repair still lands."""
        mc = repair_cluster
        data = _bytes(150_000, seed=19)
        with mc.client("corrupt") as c:
            _demote(mc, c, "/corrupt/a", data)
            owner = _owner_dn(mc)
            cid, man = next(iter(
                owner.index.stripe_manifests().items()))
            k = int(man["k"])
            # corrupt a remote DATA holder (always in the coded fold's
            # first-k survivor pick); kill a PARITY holder
            corrupt_id = next(man["holders"][i][0] for i in range(k)
                              if man["holders"][i][0] != owner.dn_id)
            corrupt_idx = next(i for i in range(k)
                               if man["holders"][i][0] == corrupt_id)
            victim = next(man["holders"][i][0]
                          for i in range(k, k + int(man["m"]))
                          if man["holders"][i][0]
                          not in (owner.dn_id, corrupt_id))
            holder_dn = mc.datanodes[int(corrupt_id.split("-")[1])]
            path = holder_dn.ec.store._path(owner.dn_id, cid, corrupt_idx)
            with open(path, "r+b") as f:
                f.seek(3)
                b = f.read(1)
                f.seek(3)
                f.write(bytes([b[0] ^ 0xFF]))
            corrupt0 = _EC.counter("coded_contrib_corrupt")
            fb0 = _EC.counter("coded_repair_fallbacks")
            repaired0 = _EC.counter("stripes_repaired")
            mc.stop_datanode(int(victim.split("-")[1]))
            _wait(lambda: _EC.counter("stripes_repaired") > repaired0,
                  msg="repair through the corrupt-survivor fallback")
            assert _EC.counter("coded_contrib_corrupt") > corrupt0, \
                "the poisoned fold was never detected"
            assert _EC.counter("coded_repair_fallbacks") > fb0
            assert c.read("/corrupt/a") == data

    def test_knob_off_pins_the_classic_gather(self):
        """ec_coded_repair=False is the A/B pin: repair completes on the
        full gather, no coded counters move, and the ledger's delta
        ratio sits at ~k (every survivor stripe crosses to the owner)."""
        from hdrf_tpu.testing.minicluster import MiniCluster

        with MiniCluster(n_datanodes=5, block_size=256 * 1024,
                         container_size=32 * 1024,
                         reduction_overrides={
                             "ec_coded_repair": False,
                         }) as mc:
            mc.namenode.config.ec_data_shards = 3
            mc.namenode.config.ec_parity_shards = 2
            mc.namenode.config.ec_demote_after_s = 0.0
            data = _bytes(150_000, seed=23)
            with mc.client("classic") as c:
                _demote(mc, c, "/classic/a", data)
                owner = _owner_dn(mc)
                man = next(iter(owner.index.stripe_manifests().values()))
                victim = next(h[0] for h in man["holders"]
                              if h[0] != owner.dn_id)
                coded0 = _EC.counter("coded_repairs")
                wire0 = _EC.counter("repair_wire_bytes")
                rebuilt0 = _EC.counter("repair_rebuilt_bytes")
                repaired0 = _EC.counter("stripes_repaired")
                mc.stop_datanode(int(victim.split("-")[1]))
                _wait(lambda: _EC.counter("stripes_repaired") > repaired0,
                      msg="classic stripe repair")
                assert _EC.counter("coded_repairs") == coded0
                wire = _EC.counter("repair_wire_bytes") - wire0
                rebuilt = _EC.counter("repair_rebuilt_bytes") - rebuilt0
                assert wire / rebuilt > int(man["k"]) - 0.5
                assert c.read("/classic/a") == data


# ------------------------------------------- mirror segment compression


class TestMirrorSegmentCompression:
    def _run(self, overrides):
        from hdrf_tpu.testing.minicluster import MiniCluster

        # "dedup" (container_codec=none) keeps the reduced chunks raw, so
        # the mirrored payload is still compressible and the smaller-of
        # negotiation has something to win on; unique counters per line
        # keep the chunks from deduping away
        data = b"".join(b"mirror segment compression %08d\n" % i
                        for i in range(4000))
        with MiniCluster(n_datanodes=3, replication=3,
                         block_size=1 << 20,
                         reduction_overrides=overrides) as mc:
            with mc.client("mseg") as c:
                c.write("/mseg/f", data, scheme="dedup")
                assert c.read("/mseg/f") == data

    def test_segments_compress_behind_the_knob(self):
        before = _MIR.counter("segments_compressed")
        raw0 = _MIR.counter("segment_raw_bytes")
        wire0 = _MIR.counter("segment_wire_bytes")
        self._run({"mirror_parity": 1})
        assert _MIR.counter("segments_compressed") > before
        saved = ((_MIR.counter("segment_raw_bytes") - raw0)
                 - (_MIR.counter("segment_wire_bytes") - wire0))
        assert saved > 0, "compressed segments saved no wire bytes"

    def test_knob_off_pins_the_raw_path(self):
        before = _MIR.counter("segments_compressed")
        self._run({"mirror_parity": 1,
                   "mirror_compress_segments": False})
        assert _MIR.counter("segments_compressed") == before
