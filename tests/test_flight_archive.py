"""Crash-safety and series math for the long-horizon flight plane.

Covers utils/flight_archive.py (ISSUE 17 tentpole a/b): JSONL segment
append/rotation/GC with the chunk-index WAL's torn-tail discipline
(index/chunk_index.py:19-27, utils/wal.py:29-60), restart-surviving
recorder rings (utils/flight_recorder.py:41-86), and the cluster-merge
quantile/sum/mean semantics plus step rollups the gateway's
``/timeseries?scope=cluster`` endpoint rides (server/http_gateway.py
timeseries)."""

import json
import os

from hdrf_tpu.utils import flight_archive, metrics
from hdrf_tpu.utils.flight_archive import FlightArchive
from hdrf_tpu.utils.flight_recorder import FlightRecorder


def _mk(tmp_path, **kw) -> FlightArchive:
    return FlightArchive(str(tmp_path / "arch"), **kw)


def _samples(n, start=0):
    return [{"t": float(start + i), "mono": float(start + i), "g": float(i)}
            for i in range(n)]


# ------------------------------------------------------------- segments


class TestArchiveSegments:
    def test_append_replay_bit_identical(self, tmp_path):
        arch = _mk(tmp_path)
        samples = _samples(10)
        for s in samples:
            arch.append(s)
        assert arch.replay() == samples  # bit-identical, oldest first
        arch.close()

    def test_rotation_seals_and_opens_next_segment(self, tmp_path):
        arch = _mk(tmp_path, segment_bytes=128)
        for s in _samples(20):
            arch.append(s)
        segs = flight_archive.list_segments(arch.directory)
        assert len(segs) > 1
        assert segs == sorted(segs)  # zero-padded seq sorts oldest first
        assert arch.replay() == _samples(20)  # rotation loses nothing
        arch.close()

    def test_scan_lines_good_prefix(self):
        good = b'{"a": 1}\n{"b": 2}\n'
        docs, n = flight_archive.scan_lines(good)
        assert docs == [{"a": 1}, {"b": 2}] and n == len(good)
        # torn tail: final line has no newline -> dropped
        docs, n = flight_archive.scan_lines(good + b'{"c": ')
        assert docs == [{"a": 1}, {"b": 2}] and n == len(good)
        # corrupt middle line stops the scan at the good prefix
        docs, n = flight_archive.scan_lines(b'{"a": 1}\nBOOM\n{"c": 3}\n')
        assert docs == [{"a": 1}] and n == len(b'{"a": 1}\n')

    def test_torn_tail_dropped_on_replay(self, tmp_path):
        """Kill mid-append: the half-written final line must vanish from
        replay while every earlier sample survives byte-identical."""
        arch = _mk(tmp_path)
        samples = _samples(5)
        for s in samples:
            arch.append(s)
        arch.close()
        path = os.path.join(arch.directory,
                            flight_archive.list_segments(arch.directory)[-1])
        with open(path, "ab") as f:       # simulated torn append
            f.write(b'{"t": 99.0, "mono":')
        reg = metrics.registry("flight_archive")
        before = reg.counter("torn_tail_drops")
        assert flight_archive.replay_dir(arch.directory) == samples
        assert reg.counter("torn_tail_drops") == before + 1

    def test_reopen_truncates_torn_tail_before_appending(self, tmp_path):
        """wal.recover discipline: post-crash appends must not land
        behind garbage, so opening for append truncates the torn tail."""
        arch = _mk(tmp_path)
        for s in _samples(3):
            arch.append(s)
        arch.close()
        seg = os.path.join(arch.directory,
                           flight_archive.list_segments(arch.directory)[-1])
        with open(seg, "ab") as f:
            f.write(b'{"torn": ')
        arch2 = FlightArchive(arch.directory)
        arch2.append({"t": 3.0, "mono": 3.0, "g": 3.0})
        assert arch2.replay() == _samples(3) + [
            {"t": 3.0, "mono": 3.0, "g": 3.0}]
        with open(seg, "rb") as f:
            assert b"torn" not in f.read()  # physically truncated
        arch2.close()

    def test_gc_respects_byte_budget_never_deletes_active(self, tmp_path):
        arch = _mk(tmp_path, segment_bytes=256, max_bytes=1024)
        for s in _samples(200):
            arch.append(s)
        total = arch.total_bytes()
        segs = flight_archive.list_segments(arch.directory)
        # budget holds (modulo the one segment that crossed the line
        # right before its seal-triggered GC pass)
        assert total <= 1024 + 256
        active = f"flight-{arch._seq:08d}.jsonl"
        assert active in segs              # the active tail always survives
        reg = metrics.registry("flight_archive")
        assert reg.counter("segments_gc") > 0
        # replay returns the SUFFIX of history: newest samples intact
        replayed = arch.replay()
        assert replayed and replayed[-1] == {"t": 199.0, "mono": 199.0,
                                             "g": 199.0}
        arch.close()

    def test_gc_age_bound(self, tmp_path):
        clock = [1000.0]
        arch = FlightArchive(str(tmp_path / "aged"), segment_bytes=64,
                             max_age_s=10.0, wall=lambda: clock[0])
        for s in _samples(8):
            arch.append(s)
        n_before = len(flight_archive.list_segments(arch.directory))
        assert n_before > 1
        clock[0] += 10_000.0
        # mtimes are real wall time; age the files on disk to match
        for name in flight_archive.list_segments(arch.directory):
            p = os.path.join(arch.directory, name)
            os.utime(p, (1.0, 1.0))
        arch.gc()
        left = flight_archive.list_segments(arch.directory)
        assert len(left) == 1              # only the active segment remains
        arch.close()

    def test_replay_since_and_limit(self, tmp_path):
        arch = _mk(tmp_path)
        for s in _samples(10):
            arch.append(s)
        assert [s["t"] for s in arch.replay(since=7.0)] == [7.0, 8.0, 9.0]
        assert [s["t"] for s in arch.replay(limit=2)] == [8.0, 9.0]
        arch.close()


# ----------------------------------------------------- recorder + archive


class TestRecorderArchive:
    def test_samples_survive_restart_bit_identical(self, tmp_path):
        """The restart-survival acceptance bar: a new recorder over the
        same archive dir re-seeds its ring with the pre-crash samples,
        byte-for-byte."""
        d = str(tmp_path / "fr")
        ticks = iter(range(100))
        arch = FlightArchive(d)
        fr = FlightRecorder("t-fa", lambda: {"v": 1.0}, capacity=8,
                            clock=lambda: float(next(ticks)),
                            wall=lambda: 500.0, archive=arch)
        for _ in range(5):
            fr.sample_once()
        pre = fr.snapshot()["samples"]
        arch.close()                       # daemon dies
        arch2 = FlightArchive(d)
        fr2 = FlightRecorder("t-fa", lambda: {"v": 1.0}, capacity=8,
                             clock=lambda: 0.0, wall=lambda: 0.0,
                             archive=arch2)
        assert fr2.snapshot()["samples"] == pre
        arch2.close()

    def test_ring_seed_respects_capacity(self, tmp_path):
        d = str(tmp_path / "cap")
        arch = FlightArchive(d)
        for s in _samples(50):
            arch.append(s)
        arch.close()
        arch2 = FlightArchive(d)
        fr = FlightRecorder("t-fa-cap", lambda: {}, capacity=4,
                            clock=lambda: 0.0, wall=lambda: 0.0,
                            archive=arch2)
        ring = fr.snapshot()["samples"]
        assert len(ring) == 4 and ring[-1]["g"] == 49.0  # newest tail
        arch2.close()

    def test_archive_append_failure_never_kills_sampling(self, tmp_path):
        arch = _mk(tmp_path)
        fr = FlightRecorder("t-fa-err", lambda: {"v": 1.0}, capacity=4,
                            clock=lambda: 0.0, wall=lambda: 0.0,
                            archive=arch)
        arch.close()                       # appends now raise ValueError/OSError
        reg = metrics.registry("flight_recorder")
        before = reg.counter("archive_errors")
        fr.sample_once()                   # must not raise
        assert reg.counter("archive_errors") == before + 1
        assert len(fr.snapshot()["samples"]) == 1  # ring still works


# ------------------------------------------------------- cluster merging


class TestClusterSeriesMath:
    def test_merge_value_semantics(self):
        # quantile-class gauges: MAX across nodes (cannot average p95s)
        assert flight_archive.merge_value("read_p95_ms",
                                          [5.0, 20.0, 10.0]) == 20.0
        # per-node tallies: SUM
        assert flight_archive.merge_value("blocks", [3.0, 4.0]) == 7.0
        assert flight_archive.merge_value("garbage_bytes",
                                          [100.0, 50.0]) == 150.0
        # everything else (ratios): MEAN
        assert flight_archive.merge_value("storage_ratio",
                                          [1.0, 3.0]) == 2.0

    def test_filter_series_metric_and_since(self):
        s = [{"t": 1.0, "mono": 1.0, "a": 1.0, "b": 2.0},
             {"t": 5.0, "mono": 5.0, "a": 3.0, "b": 4.0}]
        out = flight_archive.filter_series(s, metric="a")
        assert out == [{"t": 1.0, "mono": 1.0, "a": 1.0},
                       {"t": 5.0, "mono": 5.0, "a": 3.0}]
        assert flight_archive.filter_series(s, since=2.0) == [s[1]]
        out = flight_archive.filter_series(s, metric="a,b", since=2.0)
        assert out == [s[1]]

    def test_merge_cluster_quantiles_on_injected_clocks(self):
        """The acceptance-criteria math check: two DNs + the NN aligned
        into 1 s buckets; p95 merges as MAX, blocks SUM, ratios MEAN."""
        dn1 = [{"t": 10.2, "read_p95_ms": 5.0, "blocks": 3,
                "storage_ratio": 1.0},
               {"t": 11.1, "read_p95_ms": 6.0, "blocks": 3,
                "storage_ratio": 1.0}]
        dn2 = [{"t": 10.7, "read_p95_ms": 50.0, "blocks": 4,
                "storage_ratio": 3.0}]
        nn = [{"t": 10.4, "datanodes_live": 2}]
        merged = flight_archive.merge_cluster(
            [("dn-1", dn1), ("dn-2", dn2), ("namenode", nn)], step_s=1.0)
        assert [m["t"] for m in merged] == [10.0, 11.0]
        b0 = merged[0]
        assert b0["nodes"] == 3
        assert b0["read_p95_ms"] == 50.0          # slowest node's tail
        assert b0["blocks"] == 7.0                # summed tally
        assert b0["storage_ratio"] == 2.0         # mean ratio
        assert b0["datanodes_live"] == 2.0
        b1 = merged[1]
        assert b1["nodes"] == 1 and b1["read_p95_ms"] == 6.0

    def test_rollup_min_max_mean_last(self):
        s = [{"t": 0.0, "g": 1.0}, {"t": 1.0, "g": 3.0},
             {"t": 2.0, "g": 2.0}, {"t": 10.0, "g": 7.0}]
        rows = flight_archive.rollup(s, step_s=5.0)
        assert len(rows) == 2
        r0 = rows[0]
        assert r0["t"] == 0.0 and r0["n"] == 3
        assert r0["gauges"]["g"] == {"min": 1.0, "max": 3.0,
                                     "mean": 2.0, "last": 2.0}
        assert rows[1]["gauges"]["g"]["last"] == 7.0

    def test_rollup_bounds_response(self):
        """A long archive renders bounded: the rollup row count tracks
        the time span / step, not the sample count."""
        s = [{"t": float(i), "g": float(i)} for i in range(10_000)]
        rows = flight_archive.rollup(s, step_s=1000.0)
        assert len(rows) == 10

    def test_query_merges_ring_and_archive_dedup(self, tmp_path):
        arch = _mk(tmp_path)
        old = {"t": 1.0, "mono": 1.0, "g": 0.0}
        arch.append(old)                   # pre-restart history
        ticks = iter(range(10, 20))
        fr = FlightRecorder("t-q", lambda: {"g": 1.0}, capacity=4,
                            clock=lambda: float(next(ticks)),
                            wall=lambda: 2.0, archive=arch)
        # the archive seeded the ring with `old`; new samples land in both
        fr.sample_once()
        out = flight_archive.query(fr, arch)
        assert out["daemon"] == "t-q" and out["archived"] == 2
        assert out["samples"] == [old, {"t": 2.0, "mono": 10.0, "g": 1.0}]
        # metric/since filters + tail limit apply after the merge
        out = flight_archive.query(fr, arch, metric="g", since=2.0)
        assert out["samples"] == [{"t": 2.0, "mono": 10.0, "g": 1.0}]
        out = flight_archive.query(fr, arch, limit=1)
        assert len(out["samples"]) == 1
        arch.close()

    def test_query_samples_are_json_plain(self, tmp_path):
        arch = _mk(tmp_path)
        fr = FlightRecorder("t-qj", lambda: {"g": 1.0}, capacity=2,
                            clock=lambda: 0.0, wall=lambda: 0.0,
                            archive=arch)
        fr.sample_once()
        json.dumps(flight_archive.query(fr, arch))  # endpoint body
        arch.close()
