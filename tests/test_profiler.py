"""Write-path critical-path profiler (utils/profiler.py) + gap-attribution
report (tools/gap_report.py): the decomposition the reference never had
(DataNodeMetrics.java:553-560 stops at per-op rate counters).

Partition math on injected integer clocks (exact sums — the idle remainder
makes the class partition total the wall clock by construction), timeline
assembly through the ambient contextvar, device-ledger linkage
(dispatch/readback ids landing on the open timeline), the watchdog's
cross-thread phase attribution, the gap_report golden table, and the
MiniCluster end-to-end acceptance bar (>= 95% of write wall attributed)."""

import json
import threading

import pytest

from hdrf_tpu.tools import gap_report
from hdrf_tpu.utils import device_ledger, fault_injection, profiler, tracing

W = profiler.profile_spans


def approx(a, b, tol=1e-9):
    return abs(a - b) < tol


# ------------------------------------------------------- overlap accountant


class TestPartition:
    def test_empty_window_is_all_idle(self):
        p = W([], 0.0, 10.0)
        assert p["wall_s"] == 10.0
        assert p["classes"] == {"host_busy": 0.0, "device_busy": 0.0,
                                "transport_wait": 0.0, "idle": 10.0}
        assert p["attributed_frac"] == 0.0
        assert p["overlap_efficiency"] == 1.0  # nothing to hide

    def test_serial_phases_sum_exactly(self):
        spans = [("recv", 0, 3), ("wal_commit", 3, 5), ("device_wait", 5, 9)]
        p = W(spans, 0, 10)
        assert p["classes"]["transport_wait"] == 3
        assert p["classes"]["host_busy"] == 2
        assert p["classes"]["device_busy"] == 4
        assert p["classes"]["idle"] == 1
        assert sum(p["classes"].values()) == p["wall_s"] == 10
        assert p["phases"] == {"recv": 3, "wal_commit": 2, "device_wait": 4}
        assert approx(p["attributed_frac"], 0.9)

    def test_hidden_wait_and_efficiency(self):
        # recv [0,4), device [2,8), wal [6,10): the canonical overlap case
        spans = [("recv", 0, 4), ("device_wait", 2, 8), ("wal_commit", 6, 10)]
        p = W(spans, 0, 12)
        assert p["classes"] == {"host_busy": 4.0, "device_busy": 4.0,
                                "transport_wait": 2.0, "idle": 2.0}
        # hideable = any device/transport active = [0,8) = 8;
        # hidden = host concurrently busy = [6,8) = 2
        assert p["hideable_wait_s"] == 8 and p["hidden_wait_s"] == 2
        assert approx(p["overlap_efficiency"], 0.25)
        assert p["phases"] == {"recv": 2.0, "device_wait": 4.0,
                               "wal_commit": 4.0}
        assert sum(p["classes"].values()) == 12.0

    def test_class_priority_host_over_device_over_transport(self):
        spans = [("recv", 0, 6), ("device_wait", 0, 4), ("checksum", 0, 2)]
        p = W(spans, 0, 6)
        # [0,2) host wins; [2,4) device wins; [4,6) transport remains
        assert p["classes"]["host_busy"] == 2
        assert p["classes"]["device_busy"] == 2
        assert p["classes"]["transport_wait"] == 2
        assert p["phases"] == {"checksum": 2.0, "device_wait": 2.0,
                               "recv": 2.0}
        # full overlap of waits by time, but only [0,2) of the 6 hideable
        # seconds sat under host work
        assert p["hideable_wait_s"] == 6 and p["hidden_wait_s"] == 2

    def test_unknown_phase_defaults_to_host(self):
        assert profiler.phase_class("weird_new_phase") == profiler.HOST
        p = W([("weird_new_phase", 0, 2)], 0, 2)
        assert p["classes"]["host_busy"] == 2
        assert p["phases"] == {"weird_new_phase": 2.0}

    def test_spans_clamped_to_window(self):
        p = W([("recv", -5, 3), ("wal_commit", 8, 20)], 0, 10)
        assert p["classes"]["transport_wait"] == 3
        assert p["classes"]["host_busy"] == 2
        assert p["phases"] == {"recv": 3.0, "wal_commit": 2.0}
        assert sum(p["classes"].values()) == 10.0

    def test_bytes_rate(self):
        p = W([("recv", 0, 1)], 0, 2, nbytes=4 << 20)
        assert p["bytes"] == 4 << 20 and approx(p["mb_per_s"], 2.0)


# -------------------------------------------------------- timeline assembly


class _Clock:
    """Settable wall clock injected over profiler._now."""

    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


class TestTimelineAssembly:
    def test_phases_land_on_ambient_timeline(self, monkeypatch):
        profiler.reset()
        clk = _Clock()
        monkeypatch.setattr(profiler, "_now", clk)
        assert profiler.current_timeline() is None
        with profiler.block_timeline(7, nbytes=123) as tl:
            assert profiler.current_timeline() is tl
            with profiler.phase("wal_commit"):
                clk.t += 2
            clk.t += 1
            with profiler.phase("recv"):
                clk.t += 3
        assert profiler.current_timeline() is None
        assert tl.t0 == 100.0 and tl.t1 == 106.0
        assert tl.spans == [("wal_commit", 100.0, 102.0, tl.spans[0][3]),
                            ("recv", 103.0, 106.0, tl.spans[1][3])]
        prof = tl.profile()
        assert prof["classes"] == {"host_busy": 2.0, "transport_wait": 3.0,
                                   "device_busy": 0.0, "idle": 1.0}
        assert approx(prof["attributed_frac"], 5.0 / 6.0)
        snap = profiler.timelines_snapshot()[-1]
        assert snap["block_id"] == 7 and snap["nbytes"] == 123
        assert snap["spans"] == [["wal_commit", 100.0, 102.0],
                                 ["recv", 103.0, 106.0]]
        assert snap["profile"]["wall_s"] == 6.0

    def test_finished_timeline_observes_registry(self, monkeypatch):
        profiler.reset()
        clk = _Clock()
        monkeypatch.setattr(profiler, "_now", clk)
        from hdrf_tpu.utils import metrics
        reg = metrics.registry("write_profiler")
        before = reg.counter("blocks_profiled")
        with profiler.block_timeline(1):
            with profiler.phase("container_io"):
                clk.t += 1
        assert reg.counter("blocks_profiled") == before + 1
        snap = reg.snapshot()
        assert snap["gauges"]["attributed_frac"] == 1.0
        assert "phase_us|phase=container_io" in snap["histograms"]

    def test_timed_iter_records_per_item_spans(self, monkeypatch):
        profiler.reset()
        clk = _Clock()
        monkeypatch.setattr(profiler, "_now", clk)

        def slow_src():
            for i in range(3):
                clk.t += 2  # the wait happens inside next()
                yield i

        with profiler.block_timeline(2) as tl:
            items = list(profiler.timed_iter("recv", slow_src()))
        assert items == [0, 1, 2]
        recv = [s for s in tl.spans if s[0] == "recv"]
        assert len(recv) == 3
        assert all(s[2] - s[1] == 2.0 for s in recv)
        assert tl.profile()["classes"]["transport_wait"] == 6.0

    def test_window_profile_sees_other_threads(self, monkeypatch):
        profiler.reset()
        t0 = profiler.mark()

        def worker():
            with profiler.phase("wal_commit"):
                pass

        th = threading.Thread(target=worker)
        th.start()
        th.join()
        prof = profiler.window_profile(t0, profiler.mark())
        assert "wal_commit" in prof["phases"]


# ------------------------------------------------------- device-ledger link


class TestLedgerLinkage:
    def test_dispatch_readback_lands_on_timeline(self):
        profiler.reset()
        with profiler.block_timeline(11) as tl:
            tok = device_ledger.dispatch("prof.unit", batch=2,
                                         h2d_bytes=64, key=("prof-unit", 2))
            device_ledger.readback(tok, d2h_bytes=16)
        assert len(tl.ledger_ids) == 1
        evs = {e["id"]: e for e in device_ledger.events_snapshot()}
        ev = evs[tl.ledger_ids[0]]
        assert ev["op"] == "prof.unit" and ev["kind"] == "dispatch"
        waits = [s for s in tl.spans if s[0] == "device_wait"]
        assert len(waits) == 1
        assert tl.profile()["classes"]["device_busy"] >= 0.0

    def test_outstanding_dispatches_track_balances(self):
        profiler.reset()
        tok = device_ledger.dispatch("prof.track", batch=1)
        names = {(s["name"], s["value"])
                 for s in profiler.counters_snapshot()}
        assert ("outstanding_dispatches", 1.0) in names
        device_ledger.readback(tok)
        last = [s for s in profiler.counters_snapshot()
                if s["name"] == "outstanding_dispatches"][-1]
        assert last["value"] == 0.0
        # aggregate (pending) tokens must NOT decrement below zero
        device_ledger.readback(device_ledger.pending("prof.track"))
        last = [s for s in profiler.counters_snapshot()
                if s["name"] == "outstanding_dispatches"][-1]
        assert last["value"] == 0.0

    def test_counter_samples_render_as_chrome_counter_events(self):
        profiler.reset()
        profiler.counter_set("wal_queue_depth", 3)
        doc = tracing.chrome_trace([], counters=profiler.counters_snapshot())
        cevs = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
        assert any(e["name"] == "wal_queue_depth"
                   and e["args"]["value"] == 3 for e in cevs)


# ------------------------------------------- watchdog phase/trace attribution


class TestWatchdogAttribution:
    def test_stall_record_carries_phase_and_trace(self):
        from hdrf_tpu.utils.watchdog import StallWatchdog
        wd = StallWatchdog("prof_wd", budget_s=5.0, tick_s=999.0)
        seen = {}

        def on_stall(**kw):
            seen.update(kw)

        import time as _time
        tr = tracing.tracer("prof_wd_client")
        with tr.span("client.write") as root:
            with wd.track("xceiver.write"):
                with profiler.phase("container_io"):
                    with fault_injection.inject("watchdog.stall", on_stall):
                        n = wd.scan(now=_time.monotonic() + 100.0)
        assert n == 1
        tid = f"{root.trace_id:016x}"
        rec = wd.stalls()[-1]
        assert rec["phase"] == "container_io"
        assert rec["trace_id"] == tid
        assert seen["phase"] == "container_io" and seen["trace_id"] == tid
        # synthetic stall span joined the watchdog tracer under the same
        # trace id (visible next to the block's spans in a chrome export)
        spans = tracing.tracer("watchdog").snapshot()
        mine = [s for s in spans if s["trace_id"] == tid]
        assert mine and mine[-1]["name"] == "stall:xceiver.write"
        assert mine[-1]["annotations"]["phase"] == "container_io"

    def test_thread_phase_probe(self):
        assert profiler.thread_phase() is None
        with profiler.phase("checksum"):
            with profiler.phase("container_io"):
                assert profiler.thread_phase() == "container_io"
            assert profiler.thread_phase() == "checksum"
        assert profiler.thread_phase() is None


# ------------------------------------------------------- gap_report goldens


def _golden_timelines():
    spans = [["recv", 0.0, 4.0], ["device_wait", 2.0, 8.0],
             ["wal_commit", 6.0, 10.0]]
    tl = {"block_id": 1, "nbytes": 8 << 20, "t0": 0.0, "t1": 12.0,
          "spans": spans, "ledger_ids": [],
          "profile": profiler.profile_spans(
              [tuple(s) for s in spans], 0.0, 12.0, nbytes=8 << 20)}
    return [tl]


class TestGapReport:
    def test_aggregate_golden(self):
        agg = gap_report.aggregate(_golden_timelines())
        assert agg["blocks"] == 1 and agg["bytes"] == 8 << 20
        assert agg["wall_s"] == 12.0
        assert approx(agg["attributed_frac"], 10.0 / 12.0)
        assert approx(agg["overlap_efficiency"], 0.25)
        rows = {r["phase"]: r for r in agg["phases"]}
        assert rows["device_wait"]["exclusive_s"] == 4.0
        # removing wal_commit's 4 exclusive seconds: 8 MiB / 8 s vs /12 s
        assert approx(rows["wal_commit"]["lost_mb_per_s"],
                      8.0 / 8.0 - 8.0 / 12.0)

    def test_format_table_golden(self):
        text = gap_report.format_table(gap_report.aggregate(
            _golden_timelines()))
        assert text == "\n".join([
            "write path: 1 blocks, 8.00 MiB in 12.000 s = 0.7 MB/s",
            "attributed: 83.3% of wall clock in named phase/overlap classes",
            "overlap efficiency: 25.0% (2.000 s of 8.000 s wait hidden "
            "under host work)",
            "",
            "class              seconds   share",
            "host_busy            4.000   33.3%",
            "device_busy          4.000   33.3%",
            "transport_wait       2.000   16.7%",
            "idle                 2.000   16.7%",
            "",
            "phase               excl s   share  lost MB/s",
            "device_wait          4.000   33.3%        0.3",
            "wal_commit           4.000   33.3%        0.3",
            "recv                 2.000   16.7%        0.1",
        ])

    def test_main_json_over_input_file(self, tmp_path):
        f = tmp_path / "tls.json"
        f.write_text(json.dumps(_golden_timelines()))
        import io
        from contextlib import redirect_stdout
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = gap_report.main(["--input", str(f), "--json"])
        assert rc == 0
        agg = json.loads(buf.getvalue())
        assert agg["blocks"] == 1 and approx(agg["overlap_efficiency"], 0.25)

    def test_main_accepts_bench_json_line(self, tmp_path):
        """--input takes bench.py's single JSON line directly: the
        ``phase_profile`` object is lifted out and reported as one
        pseudo-timeline (and a bare profile object works the same)."""
        prof = _golden_timelines()[0]["profile"]
        bench_line = {"metric": "x", "value": 1.0, "unit": "MB/s",
                      "phase_profile": prof,
                      "pipeline": {"depth": 4, "group_commit_batches": 2,
                                   "overlap_efficiency":
                                       prof["overlap_efficiency"]}}
        import io
        from contextlib import redirect_stdout
        for doc in (bench_line, prof):
            f = tmp_path / "in.json"
            f.write_text(json.dumps(doc))
            buf = io.StringIO()
            with redirect_stdout(buf):
                rc = gap_report.main(["--input", str(f), "--json"])
            assert rc == 0
            agg = json.loads(buf.getvalue())
            assert approx(agg["overlap_efficiency"],
                          prof["overlap_efficiency"])
            assert approx(agg["wall_s"], prof["wall_s"])
            phases = {r["phase"] for r in agg["phases"]}
            assert phases == set(prof["phases"])


# ----------------------------------------------------------- end to end


class TestE2E:
    def test_minicluster_smoke_attribution_bar(self):
        """The ISSUE acceptance gate: the gap_report smoke partitions
        >= 95% of MiniCluster write wall clock into named classes."""
        agg = gap_report.aggregate(gap_report.run_smoke())
        assert agg["blocks"] == gap_report.SMOKE_BLOCKS
        assert agg["attributed_frac"] >= 0.95, agg
        # partition exactness survives aggregation
        assert approx(sum(agg["classes"].values()), agg["wall_s"], tol=1e-6)
        # the dedup write path must show its signature phases
        rows = {r["phase"] for r in agg["phases"]}
        assert {"recv", "wal_commit", "container_io",
                "dedup_lookup"} <= rows

    def test_smoke_shows_hidden_overlap(self):
        """ISSUE 7 acceptance: with the pipeline on (default depth > 1) the
        smoke corpus shows overlap_efficiency > 0 — the ack/CRC pump hides
        host work under the client-stream transport waits even for
        sequential single-stream writes."""
        agg = gap_report.aggregate(gap_report.run_smoke())
        assert agg["overlap_efficiency"] > 0.0, agg
        assert agg["hidden_wait_s"] > 0.0

    def test_pipeline_enqueues_next_block_under_container_io(self):
        """Overlap-scheduling contract, pinned deterministically: while
        block K is parked inside its container append (the
        ``dedup.container_append`` fault point), block K+1's write runs to
        completion — so K+1's device prep dispatch (a ledger ``enqueue``
        ring event) lands BEFORE K's container_io finishes."""
        import random
        import threading

        from hdrf_tpu.testing.minicluster import MiniCluster
        from hdrf_tpu.utils import fault_injection

        def prep_enqueues() -> int:
            return sum(1 for e in device_ledger.events_snapshot()
                       if e["kind"] == "enqueue"
                       and e["op"] in ("resident.prep_batch",
                                       "resident.cdc_fused"))

        profiler.reset()
        parked = threading.Event()
        release = threading.Event()
        seen: dict = {}
        lock = threading.Lock()

        def park(block_id=None, **kw):
            with lock:
                if "first" in seen:
                    return  # only block K parks; K+1 sails through
                seen["first"] = block_id
                seen["enqueues_before"] = prep_enqueues()
            parked.set()
            release.wait(30)
            # still inside K's container_io phase: count K+1's dispatches
            seen["enqueues_during"] = prep_enqueues()

        pay_k = random.Random(11).randbytes(1 << 20)
        pay_k1 = random.Random(12).randbytes(1 << 20)
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=1 << 20, backend="tpu") as mc:
            def write_k():
                with mc.client("k") as c:
                    c.write("/ov/k", pay_k, scheme="dedup")

            with fault_injection.inject("dedup.container_append", park):
                t = threading.Thread(target=write_k)
                t.start()
                assert parked.wait(30), "block K never reached its append"
                with mc.client("k1") as c2:   # runs while K is parked
                    c2.write("/ov/k1", pay_k1, scheme="dedup")
                release.set()
                t.join(30)
                assert not t.is_alive()
        assert seen["enqueues_during"] > seen["enqueues_before"], seen

    def test_minicluster_tpu_backend_links_ledger(self):
        """A write through the jax reduction path (virtual-device mesh)
        produces a timeline whose device_wait spans carry the ledger event
        ids of the dispatches it waited on."""
        from hdrf_tpu.testing.minicluster import MiniCluster
        profiler.reset()
        import random
        payload = random.Random(5).randbytes(1 << 20)
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=1 << 20, backend="tpu") as mc:
            with mc.client("prof-e2e") as c:
                c.write("/prof/blk", payload, scheme="dedup")
        tls = profiler.timelines_snapshot()
        assert tls, "no timeline recorded for the write"
        tl = tls[-1]
        assert tl["ledger_ids"], "jax write produced no ledger links"
        evs = {e["id"] for e in device_ledger.events_snapshot()}
        assert set(tl["ledger_ids"]) <= evs
        assert tl["profile"]["phases"].get("device_wait", 0) > 0
