"""ReductionScheme registry + scheme round-trips + dedup pipeline."""

import os
import random

import pytest

from hdrf_tpu.config import ReductionConfig
from hdrf_tpu.index.chunk_index import ChunkIndex
from hdrf_tpu.utils import codec
from hdrf_tpu.reduction import scheme as schemes
from hdrf_tpu.reduction.scheme import ReductionContext
from hdrf_tpu.storage.container_store import ContainerStore


def make_ctx(tmp_path, **cfg_kw) -> ReductionContext:
    cfg = ReductionConfig(**cfg_kw)
    cfg.cdc.mask_bits = 10  # avg 1 KiB chunks: fast tests
    cfg.cdc.min_chunk = 256
    cfg.cdc.max_chunk = 8192
    return ReductionContext(
        config=cfg,
        containers=ContainerStore(str(tmp_path / "containers"),
                                  container_size=1 << 18, lanes=2),
        index=ChunkIndex(str(tmp_path / "index")),
        backend="native",
    )


def test_registry_has_all_schemes():
    for name in ("direct", "lz4", "gzip", "zstd", "dedup", "dedup_lz4",
                 "dedup_zstd"):
        assert schemes.get(name).name == name
    with pytest.raises(KeyError):
        schemes.get("snappy-nope")


@pytest.mark.parametrize("name", [
    "direct", "lz4", "gzip",
    pytest.param("zstd", marks=pytest.mark.skipif(
        not codec.available("zstd"),
        reason="zstandard module not installed"))])
def test_compress_schemes_roundtrip(name, tmp_path):
    s = schemes.get(name)
    ctx = ReductionContext(config=ReductionConfig())
    data = (b"The quick brown fox. " * 400) + os.urandom(512)
    stored = s.reduce(1, data, ctx)
    if name != "direct":
        assert len(stored) < len(data)
    assert s.reconstruct(1, stored, len(data), ctx) == data
    assert s.reconstruct(1, stored, len(data), ctx, offset=100, length=50) == data[100:150]
    assert s.reconstruct(1, stored, len(data), ctx, offset=len(data) - 10) == data[-10:]


class TestDedup:
    def test_roundtrip(self, tmp_path):
        ctx = make_ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        rng = random.Random(7)
        data = bytes(rng.randbytes(200_000))
        stored = s.reduce(1, data, ctx)
        assert stored == b""  # bytes live in containers
        assert s.reconstruct(1, b"", len(data), ctx) == data

    def test_range_read_is_chunk_granular(self, tmp_path):
        ctx = make_ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        data = random.Random(1).randbytes(100_000)
        s.reduce(5, data, ctx)
        for off, ln in [(0, 10), (50_000, 1000), (99_990, 10), (0, 100_000),
                        (31_337, 31_337)]:
            assert s.reconstruct(5, b"", len(data), ctx, off, ln) == data[off:off + ln]

    def test_cross_block_dedup(self, tmp_path):
        ctx = make_ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        data = random.Random(2).randbytes(150_000)
        s.reduce(1, data, ctx)
        stats1 = ctx.index.stats()
        s.reduce(2, data, ctx)  # identical content: zero new chunk bytes
        stats2 = ctx.index.stats()
        assert stats2["unique_chunk_bytes"] == stats1["unique_chunk_bytes"]
        assert stats2["blocks"] == 2
        assert s.reconstruct(2, b"", len(data), ctx) == data

    def test_intra_block_dedup_fires(self, tmp_path):
        # The reference's HashMap<byte[]> bug means this NEVER worked there
        # (DataDeduplicator.java:340-358). Repeating content must store less.
        ctx = make_ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        unit = random.Random(3).randbytes(40_000)
        data = unit * 8  # 320 KB logical, ~40 KB unique
        s.reduce(1, data, ctx)
        stats = ctx.index.stats()
        assert stats["unique_chunk_bytes"] < 2 * len(unit)
        assert s.reconstruct(1, b"", len(data), ctx) == data

    def test_delete_releases_chunks(self, tmp_path):
        ctx = make_ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        data = random.Random(4).randbytes(60_000)
        s.reduce(1, data, ctx)
        assert ctx.index.stats()["chunks"] > 0
        s.delete(1, ctx)
        assert ctx.index.stats() == {"blocks": 0, "chunks": 0,
                                     "sealed_containers": 0,
                                     "striped_containers": 0,
                                     "logical_bytes": 0,
                                     "unique_chunk_bytes": 0}

    def test_survives_container_rollover(self, tmp_path):
        ctx = make_ctx(tmp_path)  # 256 KB containers
        s = schemes.get("dedup_lz4")
        blobs = {i: random.Random(i).randbytes(300_000) for i in range(1, 4)}
        for bid, data in blobs.items():
            s.reduce(bid, data, ctx)  # forces rollovers + sealing
        for bid, data in blobs.items():
            assert s.reconstruct(bid, b"", len(data), ctx) == data
        assert ctx.index.stats()["sealed_containers"] > 0

    def test_index_survives_restart(self, tmp_path):
        ctx = make_ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        data = random.Random(5).randbytes(80_000)
        s.reduce(1, data, ctx)
        ctx.index.close()
        ctx2 = ReductionContext(
            config=ctx.config,
            containers=ContainerStore(str(tmp_path / "containers"),
                                      container_size=1 << 18, lanes=2),
            index=ChunkIndex(str(tmp_path / "index")),
            backend="native",
        )
        assert s.reconstruct(1, b"", len(data), ctx2) == data

    def test_tpu_backend_matches_native(self, tmp_path):
        ctx_n = make_ctx(tmp_path)
        data = random.Random(6).randbytes(120_000)
        s = schemes.get("dedup_lz4")
        s.reduce(1, data, ctx_n)
        hashes_native = ctx_n.index.get_block(1).hashes

        ctx_t = make_ctx(tmp_path / "t")
        ctx_t.backend = "tpu"
        s.reduce(1, data, ctx_t)
        assert ctx_t.index.get_block(1).hashes == hashes_native
        assert s.reconstruct(1, b"", len(data), ctx_t) == data


class TestDeviceReconstruction:
    """The read path's device half (SURVEY §2.1: DataConstructor ->
    device gather): chunk lanes gathered from HBM-resident container
    images must be byte-identical to the host reconstruction."""

    def test_device_recon_matches_host(self, tmp_path):
        import dataclasses
        import random

        from hdrf_tpu.ops.reconstruct import DeviceReconstructor
        from hdrf_tpu.reduction.dedup import DEVICE_RECON_MIN

        ctx = make_ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        rng = random.Random(9)
        data = (rng.randbytes(DEVICE_RECON_MIN) + b"Z" * 200_000
                + rng.randbytes(400_000))
        s.reduce(1, data, ctx)
        host = s.reconstruct(1, b"", len(data), ctx)
        assert host == data
        dctx = dataclasses.replace(ctx, recon=DeviceReconstructor())
        dev = s.reconstruct(1, b"", len(data), dctx)
        assert dev == data
        # ranged read >= threshold goes through the device path too
        lo = 123_457
        n = DEVICE_RECON_MIN + 10_000
        assert s.reconstruct(1, b"", len(data), dctx, offset=lo,
                             length=n) == data[lo:lo + n]
        # image cache hit on the second read
        from hdrf_tpu.utils import metrics

        snap = metrics.registry("device_recon").snapshot()["counters"]
        assert snap.get("image_hits", 0) >= 1

    def test_invalidate_on_container_delete(self, tmp_path):
        import dataclasses

        from hdrf_tpu.ops.reconstruct import DeviceReconstructor

        ctx = make_ctx(tmp_path)
        recon = DeviceReconstructor()
        ctx.containers._on_delete = recon.invalidate
        dctx = dataclasses.replace(ctx, recon=recon)
        s = schemes.get("dedup")
        import random

        data = random.Random(10).randbytes(2 << 20)
        s.reduce(5, data, dctx)
        assert s.reconstruct(5, b"", len(data), dctx) == data
        staged = set(recon._images)
        assert staged
        for cid in staged:
            ctx.containers.delete_container(cid)
        assert not recon._images  # stale HBM images dropped
