"""WebHDFS depth (WebHdfsFileSystem.java:136 analog): a pure-HTTP client
driving the filesystem — two-step CREATE/APPEND redirects, ranged OPEN,
delegation tokens in query params, and the FileSystem-parity op set."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from hdrf_tpu.server.http_gateway import HttpGateway
from hdrf_tpu.testing.minicluster import MiniCluster


class _HttpFs:
    """Minimal WebHDFS client: ONLY http requests, no RPC imports — what
    an external tool (curl, requests) would do."""

    def __init__(self, base: str, delegation: str | None = None):
        self.base = base
        self.delegation = delegation

    def _url(self, path: str, op: str, **params) -> str:
        q = [f"op={op}"] + [f"{k}={v}" for k, v in params.items()]
        if self.delegation:
            q.append(f"delegation={self.delegation}")
        return f"{self.base}/webhdfs/v1{path}?" + "&".join(q)

    def _req(self, method: str, url: str, data: bytes | None = None,
             follow: bool = True):
        req = urllib.request.Request(url, data=data, method=method)
        try:
            with urllib.request.urlopen(req) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            if e.code == 307 and follow:
                # urllib only auto-follows GET; re-issue writes manually
                return self._req(method, e.headers["Location"], data,
                                 follow=False)
            raise

    def _two_step(self, method: str, path: str, op: str, data: bytes,
                  **params) -> int:
        # step 1 carries NO body (the reference client sends the payload
        # only to the redirect target — that is the point of the dance);
        # noredirect=true fetches the Location as JSON
        out = self.op_json(method, path, op, noredirect="true", **params)
        st, _ = self._req(method, out["Location"], data, follow=False)
        return st

    def write(self, path: str, data: bytes, **params) -> None:
        assert self._two_step("PUT", path, "CREATE", data, **params) == 201

    def append(self, path: str, data: bytes) -> None:
        assert self._two_step("POST", path, "APPEND", data) == 200

    def read(self, path: str, **params) -> bytes:
        _, body = self._req("GET", self._url(path, "OPEN", **params))
        return body

    def op_json(self, method: str, path: str, op: str, **params):
        st, body = self._req(method, self._url(path, op, **params))
        return json.loads(body) if body else {}


@pytest.fixture
def fs():
    with MiniCluster(n_datanodes=2, replication=2,
                     block_size=1 << 20) as mc:
        gw = HttpGateway(mc.namenode.addr).start()
        try:
            yield _HttpFs(f"http://{gw.addr[0]}:{gw.addr[1]}"), mc
        finally:
            gw.stop()


class TestWebHdfsFileSystem:
    def test_http_only_write_read_lifecycle(self, fs):
        http, _ = fs
        payload = np.random.default_rng(3).integers(
            0, 256, 2_500_000, np.uint8).tobytes()  # spans 3 blocks
        assert http.op_json("PUT", "/w/d", "MKDIRS")["boolean"]
        http.write("/w/d/f", payload)
        assert http.read("/w/d/f") == payload
        # ranged OPEN through the redirect
        assert http.read("/w/d/f", offset=1_100_000, length=5000) == \
            payload[1_100_000:1_105_000]
        st = http.op_json("GET", "/w/d/f", "GETFILESTATUS")["FileStatus"]
        assert st["length"] == len(payload)
        cs = http.op_json("GET", "/w", "GETCONTENTSUMMARY")[
            "ContentSummary"]
        assert cs["length"] == len(payload)
        # append over HTTP (two-step POST)
        http.append("/w/d/f", b"tail-bytes")
        assert http.read("/w/d/f") == payload + b"tail-bytes"
        # truncate
        assert http.op_json("POST", "/w/d/f", "TRUNCATE",
                            newlength=1000)["boolean"]
        assert http.read("/w/d/f") == payload[:1000]
        # rename + liststatus + delete
        assert http.op_json("PUT", "/w/d/f", "RENAME",
                            destination="/w/d/g")["boolean"]
        ls = http.op_json("GET", "/w/d", "LISTSTATUS")
        assert {e["name"] for e in
                ls["FileStatuses"]["FileStatus"]} == {"g"}
        assert http.op_json("DELETE", "/w/d/g", "DELETE")["boolean"]

    def test_two_step_redirect_shape(self, fs):
        http, _ = fs
        # noredirect=true answers 200 + Location instead of a 307
        out = http.op_json("PUT", "/r/f", "CREATE", noredirect="true")
        assert "step=2" in out["Location"]
        # a bare PUT answers a real 307 with a Location header
        req = urllib.request.Request(http._url("/r/f", "CREATE"),
                                     method="PUT")
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected 307")
        except urllib.error.HTTPError as e:
            assert e.code == 307 and "step=2" in e.headers["Location"]

    def test_permissions_and_ownership_ops(self, fs):
        http, mc = fs
        http.write("/p/f", b"perm")
        http.op_json("PUT", "/p/f", "SETPERMISSION", permission="600")
        st = http.op_json("GET", "/p/f", "GETFILESTATUS")["FileStatus"]
        assert int(st.get("permission", st.get("mode", 0))) in (0o600, 600,
                                                                384)
        assert http.op_json("PUT", "/p/f", "SETREPLICATION",
                            replication=1)["boolean"]

    def test_delegation_token_in_query_params(self, fs):
        """Token-authenticated HTTP access against an NN that REQUIRES
        tokens: GETDELEGATIONTOKEN -> use &delegation= on every op."""
        http, mc = fs
        tok = http.op_json("GET", "/", "GETDELEGATIONTOKEN",
                           renewer="web")["Token"]["urlString"]
        assert tok
        mc.namenode.config.require_token_auth = True
        try:
            authed = _HttpFs(http.base, delegation=tok)
            authed.write("/t/f", b"token bytes")
            assert authed.read("/t/f") == b"token bytes"
            # renew + cancel round trip
            exp = authed.op_json("PUT", "/", "RENEWDELEGATIONTOKEN",
                                 token=tok)["long"]
            assert exp > 0
            # without a token the namespace op is refused
            with pytest.raises(urllib.error.HTTPError):
                http.op_json("GET", "/t/f", "GETFILESTATUS")
            authed.op_json("PUT", "/", "CANCELDELEGATIONTOKEN", token=tok)
            with pytest.raises(urllib.error.HTTPError):
                authed.op_json("GET", "/t/f", "GETFILESTATUS")
        finally:
            mc.namenode.config.require_token_auth = False

    def test_symlink_and_home(self, fs):
        http, _ = fs
        http.write("/s/target", b"sym")
        http.op_json("PUT", "/s/link", "CREATESYMLINK",
                     destination="/s/target")
        assert http.read("/s/link") == b"sym"
        assert http.op_json("GET", "/", "GETHOMEDIRECTORY")[
            "Path"].startswith("/user/")

    def test_snapshot_ops_and_diff(self, fs):
        """ALLOWSNAPSHOT / CREATESNAPSHOT / GETSNAPSHOTDIFF /
        DELETESNAPSHOT over pure HTTP (the reference's snapshot webhdfs
        op set)."""
        http, _ = fs
        assert http.op_json("PUT", "/snap", "MKDIRS")["boolean"]
        http.write("/snap/a", b"one")
        http.op_json("PUT", "/snap", "ALLOWSNAPSHOT")
        out = http.op_json("PUT", "/snap", "CREATESNAPSHOT",
                           snapshotname="s1")
        assert out["Path"] == "/snap/.snapshot/s1"
        http.write("/snap/b", b"two")
        rep = http.op_json("GET", "/snap", "GETSNAPSHOTDIFF",
                           oldsnapshotname="s1", snapshotname="")[
            "SnapshotDiffReport"]
        assert {"type": "CREATE", "path": "/b"} in rep["diffList"]
        # reading through the frozen tree still works
        assert http.read("/snap/.snapshot/s1/a") == b"one"
        http.op_json("DELETE", "/snap", "DELETESNAPSHOT",
                     snapshotname="s1")

    def test_snapshot_diff_missing_oldsnapshotname_is_400(self, fs):
        """An omitted oldsnapshotname must come back as a 400 with the
        parameter named — not a KeyError-shaped 500, and never a silent
        self-diff reporting "nothing changed"."""
        http, _ = fs
        assert http.op_json("PUT", "/sd400", "MKDIRS")["boolean"]
        http.op_json("PUT", "/sd400", "ALLOWSNAPSHOT")
        http.op_json("PUT", "/sd400", "CREATESNAPSHOT", snapshotname="s1")
        with pytest.raises(urllib.error.HTTPError) as ei:
            http.op_json("GET", "/sd400", "GETSNAPSHOTDIFF",
                         snapshotname="s1")
        assert ei.value.code == 400
        body = json.loads(ei.value.read())
        assert body["error"] == "IllegalArgumentException"
        assert "oldsnapshotname" in body["message"]

    def test_getfilechecksum(self, fs):
        http, _ = fs
        http.write("/fck", b"checksum-me" * 1000)
        out = http.op_json("GET", "/fck", "GETFILECHECKSUM")["FileChecksum"]
        from hdrf_tpu import native
        assert out["algorithm"] == "COMPOSITE-CRC32C"
        assert out["bytes"] == f"{native.crc32c(b'checksum-me' * 1000):08x}"
        assert out["length"] == 11_000
