"""Fault-injection and concurrency tests (the reference's
DataNodeFaultInjector / CheckpointFaultInjector test mechanism, §4):
crash windows in persistence paths, mid-stream pipeline failures, and
multi-client contention."""

import threading

import numpy as np
import pytest

from hdrf_tpu.config import NameNodeConfig
from hdrf_tpu.server.namenode import NameNode
from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.utils import fault_injection


class Boom(Exception):
    pass


class TestEditlogCrashWindows:
    def test_crash_between_checkpoint_and_truncate(self, tmp_path):
        """Crash after publishing the fsimage but before WAL truncation: the
        seq filter must not double-apply the replayed records."""
        cfg = NameNodeConfig(meta_dir=str(tmp_path / "n"),
                             editlog_checkpoint_every=10_000)
        nn = NameNode(cfg)
        for i in range(5):
            nn.rpc_mkdir(f"/d{i}")
        with fault_injection.inject("editlog.post_checkpoint",
                                    lambda **kw: (_ for _ in ()).throw(Boom())):
            with pytest.raises(Boom):
                nn.rpc_save_namespace()
        # simulate process death without close(): WAL still holds the records
        nn2 = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "n")))
        assert {e["name"] for e in nn2.rpc_listing("/")} == \
            {f"d{i}" for i in range(5)}
        nn2.rpc_mkdir("/after")  # and the log still appends
        nn2._editlog.close()
        nn._editlog.close()

    def test_append_failure_leaves_memory_untouched(self, tmp_path):
        nn = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "n")))
        with fault_injection.inject("editlog.append",
                                    lambda **kw: (_ for _ in ()).throw(OSError("disk full"))):
            with pytest.raises(OSError, match="disk full"):
                nn.rpc_mkdir("/lost")
        assert not any(e["name"] == "lost" for e in nn.rpc_listing("/"))
        nn.rpc_mkdir("/ok")  # subsequent ops proceed
        nn._editlog.close()


class TestPipelineFaults:
    def test_mid_stream_packet_crash_triggers_client_retry(self):
        """Kill the receiving DN thread mid-block: the client's block-granular
        retry abandons and re-requests targets (pipeline recovery)."""
        with MiniCluster(n_datanodes=3, replication=1) as mc:
            payload = np.random.default_rng(0).integers(
                0, 256, 600_000, dtype=np.uint8).tobytes()
            fired = threading.Event()

            def crash_once(**kw):
                if kw.get("seqno", 0) >= 3 and not fired.is_set():
                    fired.set()
                    raise Boom()

            with fault_injection.inject("block_receiver.packet", crash_once):
                with mc.client("ft") as c:
                    c.write("/ft/f", payload, scheme="direct")
                    assert c.read("/ft/f") == payload
            assert fired.is_set()


class TestConcurrency:
    def test_parallel_clients_distinct_files(self):
        with MiniCluster(n_datanodes=3, replication=2) as mc:
            rng = np.random.default_rng(1)
            payloads = {f"/c/f{i}": rng.integers(0, 256, 200_000,
                                                 dtype=np.uint8).tobytes()
                        for i in range(6)}
            errs = []

            def put(path, data):
                try:
                    with mc.client(f"w-{path}") as c:
                        c.write(path, data, scheme="dedup_lz4")
                except Exception as e:  # noqa: BLE001
                    errs.append((path, e))

            threads = [threading.Thread(target=put, args=(p, d))
                       for p, d in payloads.items()]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
                assert not t.is_alive(), "writer thread hung"
            assert not errs, errs
            with mc.client("reader") as c:
                for p, d in payloads.items():
                    assert c.read(p) == d

    def test_same_file_write_contention(self):
        with MiniCluster(n_datanodes=2, replication=1) as mc:
            with mc.client("w1") as c1, mc.client("w2") as c2:
                c1._nn.call("create", path="/c/shared", client=c1.name)
                from hdrf_tpu.proto.rpc import RpcError

                with pytest.raises(RpcError, match="leased"):
                    c2._nn.call("create", path="/c/shared", client=c2.name)


class TestLostContainerStartup:
    def test_dn_drops_blocks_with_missing_containers_on_restart(self):
        """fsync_containers=False crash window: the fsync'd index survives
        but a container's bytes never hit disk.  On restart the DN must
        cross-check and drop affected blocks BEFORE advertising them (the
        startup scanner from ADVICE r3) — the healthy peer still serves."""
        import glob
        import os

        rng = np.random.default_rng(41)
        data = rng.integers(0, 64, size=600_000, dtype=np.uint8).tobytes()
        with MiniCluster(n_datanodes=2, replication=2,
                         block_size=1 << 20) as mc:
            with mc.client("lost") as c:
                c.write("/lost/f", data, scheme="dedup_lz4")
                assert c.read("/lost/f") == data
            dn0_dir = mc.datanodes[0].config.data_dir
            mc.stop_datanode(0)
            hit = 0
            for p in glob.glob(os.path.join(dn0_dir, "volumes", "vol-0",
                                            "containers", "*")):
                if p.endswith(".raw"):
                    # the REAL crash artifact: a truncated tail, file present
                    os.truncate(p, 16)
                    hit += 1
                elif p.endswith(".sealed"):
                    os.unlink(p)
                    hit += 1
            assert hit > 0, "expected container files on dn0"
            dn0 = mc.restart_datanode(0)
            # the block referencing the lost container was dropped, not served
            assert dn0.index.block_ids() == []
            assert dn0.replicas.block_ids() == []
            # the surviving replica still serves the file
            with mc.client("lost2") as c:
                assert c.read("/lost/f") == data
