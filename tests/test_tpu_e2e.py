"""Real-chip end-to-end: DataNode reduction on the TPU backend.

Skipped in the default CPU suite (conftest forces a clean CPU env); run
deliberately with ``HDRF_TEST_TPU=1 python -m pytest tests/test_tpu_e2e.py``
on a machine with an attached chip.  This is the flagship path: client ->
DataNode -> device-resident reduction pipeline -> chunk store/index."""

import os

import numpy as np
import pytest


def _tpu_attached() -> bool:
    if os.environ.get("HDRF_TEST_TPU") != "1":
        return False
    try:
        import jax

        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:  # noqa: BLE001
        return False


@pytest.mark.skipif(not _tpu_attached(),
                    reason="needs HDRF_TEST_TPU=1 and an attached TPU")
def test_datanode_tpu_backend_end_to_end(tmp_path):
    from hdrf_tpu.client.filesystem import HdrfClient
    from hdrf_tpu.config import DataNodeConfig, NameNodeConfig
    from hdrf_tpu.server.datanode import DataNode
    from hdrf_tpu.server.namenode import NameNode

    nn = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "nn"),
                                 replication=1, block_size=8 << 20)).start()
    cfg = DataNodeConfig(data_dir=str(tmp_path / "dn"))
    cfg.reduction.backend = "tpu"
    dn = DataNode(cfg, nn.addr, dn_id="dn-tpu").start()
    try:
        rng = np.random.default_rng(0)
        base = rng.integers(0, 256, size=24 << 20, dtype=np.uint8)
        payload = base.tobytes() + base[:4 << 20].tobytes()
        with HdrfClient(nn.addr, name="tpu-e2e") as c:
            c.write("/tpu/f", payload, scheme="dedup_lz4")
            assert c.read("/tpu/f") == payload
            # dedup caught the planted duplicate span
            st = dn._stats()["index"]
            assert st["unique_chunk_bytes"] < st["logical_bytes"]
            # chunk-granular ranged reconstruction
            assert c.read("/tpu/f", offset=9_000_000, length=123_456) == \
                payload[9_000_000:9_123_456]
    finally:
        dn.stop()
        nn.stop()


@pytest.mark.skipif(not _tpu_attached(), reason="needs HDRF_TEST_TPU=1 + TPU")
def test_pallas_sha_nonmultiple_tile_rows_real_chip():
    """Real-chip companion of test_resident's stale-row regression: the
    CPU suite can only exercise the XLA branch, so the Pallas kernel's
    non-multiple-of-_TILE lane-row handling is asserted here."""
    import hashlib

    import jax

    from hdrf_tpu.ops.sha256_pallas import sha256_words_pallas

    for L in (384, 3840):
        rng = np.random.default_rng(L)
        data = rng.integers(0, 256, size=(L, 32), dtype=np.uint8)
        w = np.zeros((L, 16), dtype=np.uint32)
        be = data.reshape(L, 8, 4).astype(np.uint32)
        w[:, :8] = (be[:, :, 0] << 24) | (be[:, :, 1] << 16) \
            | (be[:, :, 2] << 8) | be[:, :, 3]
        w[:, 8] = 0x80000000
        w[:, 15] = 256
        out = np.asarray(sha256_words_pallas(
            jax.device_put(w), jax.device_put(np.ones(L, np.int32))))
        for i in range(L):
            assert bytes(out[i]) == hashlib.sha256(
                data[i].tobytes()).digest(), (L, i)


@pytest.mark.skipif(not _tpu_attached(), reason="needs HDRF_TEST_TPU=1 + TPU")
def test_worker_process_on_real_chip():
    """The north-star deployment on real hardware: a separate worker
    process owns the TPU; the DN streams block packets to it and bytes
    land in HBM mid-stream (reduction_worker._reduce_streaming_tpu)."""
    import numpy as np

    from hdrf_tpu.config import CdcConfig
    from hdrf_tpu.ops.dispatch import gear_mask
    from hdrf_tpu.server.reduction_worker import (WorkerClient,
                                                  spawn_local_worker)
    from hdrf_tpu import native

    proc, addr = spawn_local_worker(backend="auto")
    try:
        c = WorkerClient(addr)
        assert c.ping()["backend"] == "tpu"
        cdc = CdcConfig()
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, size=8 << 20, dtype=np.uint8).tobytes()
        pkts = [data[i:i + 65536] for i in range(0, len(data), 65536)]
        cuts, digs = c.reduce_stream(iter(pkts), cdc)
        wc = native.cdc_chunk(np.frombuffer(data, np.uint8),
                              gear_mask(cdc), cdc.min_chunk, cdc.max_chunk)
        starts = np.concatenate([[0], wc[:-1]]).astype(np.uint64)
        wd = native.sha256_batch(np.frombuffer(data, np.uint8), starts,
                                 (wc - starts).astype(np.uint64))
        np.testing.assert_array_equal(cuts, wc.astype(np.int64))
        np.testing.assert_array_equal(digs, wd)
        comp = c.compress("lz4", data[:1 << 20])
        assert native.lz4_decompress(comp, 1 << 20) == data[:1 << 20]
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)
