"""Mesh-sharded reduction plane: MeshReducer/ShardedBucketTable
(parallel/sharded.py) against the native oracle and the product's dedup
path (ISSUE 9 tentpole).

Everything runs on the conftest-provided 8-virtual-device XLA:CPU mesh.
Pinned here: bit-identity of the one-dispatch mesh step vs the native
C++ oracle (native/src/cdc.cpp:16-62 + sha256.cpp:8-150) across the 7
standard CDC corpora (tests/test_cdc_pallas.py::_corpora — same
generator seed/order, the shared fixture contract), the device-ledger
shape (one mesh step == ONE "sharded.step" enqueue, zero per-chunk host
round-trips in the probe), stale-bucket safety (false positive resolved
by the authoritative index re-check, false negative degrades to a
compactable duplicate append — never corruption; the
"sharded.bucket_refresh" fault point re-queues on failure), the
ContainerStore true-LRU decode cache, and the write-pipeline mixed-size
coalescer (server/write_pipeline.py:_pad_bucket).
"""

import numpy as np
import pytest

from hdrf_tpu import native
from hdrf_tpu.config import CdcConfig, ReductionConfig
from hdrf_tpu.index.chunk_index import ChunkIndex
from hdrf_tpu.parallel import sharded
from hdrf_tpu.reduction import scheme as schemes
from hdrf_tpu.reduction.scheme import ReductionContext
from hdrf_tpu.storage.container_store import ContainerStore
from hdrf_tpu.utils import device_ledger, fault_injection, metrics


def _corpora():
    """The 7 standard CDC corpora — generator params copied verbatim from
    tests/test_cdc_pallas.py::_corpora (seed 7, text drawn FIRST: draw
    order is part of the corpus identity)."""
    rng = np.random.default_rng(7)
    text = rng.integers(97, 123, size=200_000, dtype=np.uint8)
    yield "random", rng.integers(0, 256, 150_000, dtype=np.uint8), \
        0x1FFF, 2048, 65536
    yield "text-low-entropy", text, 0x1FFF, 2048, 65536
    yield "forced-max-runs", rng.integers(0, 256, 120_000, dtype=np.uint8), \
        0xFFFFFF, 512, 4096
    yield "dense", rng.integers(0, 256, 30_000, dtype=np.uint8), 0x7, 8, 64
    yield "tail-short-chunk", rng.integers(0, 256, 65536 + 37,
                                           dtype=np.uint8), \
        0x1FFF, 2048, 65536
    yield "single-tile", rng.integers(0, 256, 65536, dtype=np.uint8), \
        0x3FF, 256, 8192
    yield "sub-tile", rng.integers(0, 256, 300, dtype=np.uint8), 0x3F, 16, 128


def _oracle(a: np.ndarray, mask: int, mn: int, mx: int):
    a = np.ascontiguousarray(a)
    cuts = native.cdc_chunk(a, mask, mn, mx)
    starts = np.concatenate([[0], cuts[:-1]]).astype(np.uint64)
    digs = native.sha256_batch(a, starts, (cuts - starts).astype(np.uint64))
    return cuts, digs


def _mesh_reducer(mask: int, mn: int, mx: int, **kw) -> sharded.MeshReducer:
    cdc = CdcConfig(mask_bits=max(bin(mask).count("1"), 1),
                    min_chunk=mn, max_chunk=mx)
    mesh = sharded.make_mesh(n_data=8, n_seq=1)
    return sharded.MeshReducer(cdc, mesh, mask=mask, **kw)


@pytest.mark.parametrize("name,a,mask,mn,mx", list(_corpora()),
                         ids=[c[0] for c in _corpora()])
def test_mesh_step_bit_identical_to_oracle(name, a, mask, mn, mx):
    """The fused CDC->SHA->probe mesh step must be bit-identical to the
    serial native oracle on every corpus — a mixed-size group (full block
    + a truncated sibling), so lane binning, per-device digest-row
    reconstruction, and mesh-width padding all engage."""
    r = _mesh_reducer(mask, mn, mx)
    group = [a, np.ascontiguousarray(a[: max(len(a) // 2, 1)])]
    res = r.reduce_many(group)
    assert len(res) == len(group)
    for blk, (cuts, digs, probe) in zip(group, res):
        ref_cuts, ref_digs = _oracle(blk, mask, mn, mx)
        np.testing.assert_array_equal(cuts, ref_cuts)
        np.testing.assert_array_equal(digs, ref_digs)
        assert probe == frozenset()   # empty bucket table: no hits


def test_mesh_matches_serial_resident_reducer():
    """Cross-check against the serial single-device path itself (not just
    the shared native oracle): the ResidentReducer oracle the config knob
    keeps verbatim must agree with the mesh plane chunk-for-chunk."""
    from hdrf_tpu.ops.resident import ResidentReducer

    cdc = CdcConfig(mask_bits=10, min_chunk=256, max_chunk=4096)
    rng = np.random.default_rng(21)
    a = rng.integers(0, 256, 50_000, dtype=np.uint8)
    serial = ResidentReducer(cdc, fused_mode="off")
    s_cuts, s_digs = serial.reduce(a)
    mesh = sharded.make_mesh(n_data=8, n_seq=1)
    m_cuts, m_digs, _probe = \
        sharded.MeshReducer(cdc, mesh).reduce_many([a])[0]
    np.testing.assert_array_equal(m_cuts, np.asarray(s_cuts))
    np.testing.assert_array_equal(m_digs, np.asarray(s_digs))


def _enqueues_after(last_id: int):
    return [e for e in device_ledger.events_snapshot()
            if e["id"] > last_id and e["kind"] == "enqueue"]


def _last_id() -> int:
    evs = device_ledger.events_snapshot()
    return evs[-1]["id"] if evs else 0


class TestOneDispatchPerStep:
    def test_one_ledger_dispatch_per_mesh_step(self):
        """A coalesced group of 8 blocks = ONE "sharded.step" enqueue —
        no resident.* dispatch chain, no per-block programs (the ISSUE 9
        acceptance's device-ledger evidence, pinned)."""
        r = _mesh_reducer(0x3FF, 256, 4096)
        rng = np.random.default_rng(5)
        group = [rng.integers(0, 256, 20_000, np.uint8) for _ in range(8)]
        r.reduce_many(group)                      # warm: jit compile
        id0 = _last_id()
        steps0 = metrics.registry("mesh_plane").counter("steps")
        jobs = r.submit_many(group)
        r.finish_many(jobs)
        enq = _enqueues_after(id0)
        assert [e["op"] for e in enq] == ["sharded.step"], enq
        assert metrics.registry("mesh_plane").counter("steps") == steps0 + 1

    def test_probe_negative_skips_host_lookup_entirely(self, tmp_path):
        """Zero per-chunk host round-trips when the bucket probe voted all
        chunks unknown: dedup_commit's index walk runs over the EMPTY
        probe-positive set, not the chunk list."""
        from hdrf_tpu.reduction.dedup import dedup_commit

        index = ChunkIndex(str(tmp_path / "index"))
        containers = ContainerStore(str(tmp_path / "c"), lanes=2)
        looked_up: list[int] = []
        orig = index.lookup_chunks

        def counting(hashes):
            looked_up.append(len(hashes))
            return orig(hashes)

        index.lookup_chunks = counting
        rng = np.random.default_rng(9)
        data = rng.integers(0, 256, 60_000, np.uint8).tobytes()
        cuts, digs = _oracle(np.frombuffer(data, np.uint8), 0x3FF, 256, 4096)
        uniq = len({digs[i].tobytes() for i in range(len(digs))})
        m0 = metrics.registry("dedup").counter("probe_skipped_lookups")
        n, new, _ = dedup_commit(1, data, cuts, digs, index, containers,
                                 probe=frozenset())
        assert n == len(cuts) and new == uniq     # all committed as new
        assert sum(looked_up) == 0                # zero per-chunk walks
        assert metrics.registry("dedup").counter(
            "probe_skipped_lookups") == m0 + uniq


class TestStaleBucketSafety:
    def _ctx(self, tmp_path) -> ReductionContext:
        cfg = ReductionConfig()
        cfg.cdc.mask_bits = 10
        cfg.cdc.min_chunk = 256
        cfg.cdc.max_chunk = 8192
        return ReductionContext(
            config=cfg,
            containers=ContainerStore(str(tmp_path / "containers"),
                                      container_size=1 << 18, lanes=2),
            index=ChunkIndex(str(tmp_path / "index")),
            backend="native")

    def test_false_positive_resolved_by_host_recheck(self, tmp_path):
        """A stale/collided bucket entry flags an UNKNOWN chunk as a hit:
        the authoritative index lookup returns None, the chunk commits as
        new, and the block reads back bit-identical."""
        ctx = self._ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        data = bytes(np.random.default_rng(11).integers(
            0, 256, 80_000, np.uint8))
        arr = np.frombuffer(data, np.uint8)
        cuts, digs = _oracle(arr, 0x3FF, 256, 8192)
        fp0 = metrics.registry("dedup").counter("probe_false_positive")
        # every chunk falsely flagged possibly-known
        probe = frozenset(digs[i].tobytes() for i in range(len(digs)))
        s.reduce_with(7, data, cuts, digs, ctx, probe=probe)
        assert metrics.registry("dedup").counter(
            "probe_false_positive") == fp0 + len(probe)
        assert s.reconstruct(7, b"", len(data), ctx) == data

    def test_false_negative_appends_never_corrupts(self, tmp_path):
        """A stale table misses KNOWN chunks: they re-append (orphan
        container bytes) but commit_block's first-commit-wins keeps the
        original locations — dedup quality degrades, data never does."""
        ctx = self._ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        data = bytes(np.random.default_rng(12).integers(
            0, 256, 80_000, np.uint8))
        arr = np.frombuffer(data, np.uint8)
        cuts, digs = _oracle(arr, 0x3FF, 256, 8192)
        s.reduce_with(1, data, cuts, digs, ctx)       # authoritative commit
        unique0 = ctx.index.stats()["unique_chunk_bytes"]
        uniq = len({digs[i].tobytes() for i in range(len(digs))})
        stale0 = metrics.registry("dedup").counter("probe_stale_appends")
        # same content again, bucket table stale: probe misses everything
        s.reduce_with(2, data, cuts, digs, ctx, probe=frozenset())
        assert metrics.registry("dedup").counter(
            "probe_stale_appends") == stale0 + uniq
        # first commit won: no new unique bytes despite the re-append
        assert ctx.index.stats()["unique_chunk_bytes"] == unique0
        assert s.reconstruct(1, b"", len(data), ctx) == data
        assert s.reconstruct(2, b"", len(data), ctx) == data

    def test_refresh_failure_requeues_and_recovers(self):
        """A failed device refresh (fault point "sharded.bucket_refresh")
        leaves the step probing the STALE table — old verdicts hold, the
        pending rows re-queue, and the next healthy flush lands them."""
        r = _mesh_reducer(0x3FF, 256, 4096)
        rng = np.random.default_rng(3)
        a = rng.integers(0, 256, 40_000, np.uint8)
        _cuts, digs, probe = r.reduce_many([a])[0]
        assert probe == frozenset()
        half = [digs[i].tobytes() for i in range(0, len(digs), 2)]
        r.table.note_new(half)
        _c, _d, probe2 = r.reduce_many([a])[0]
        assert probe2 == frozenset(half)
        # host mirror agrees with the on-mesh verdicts
        hm = r.table.host_probe(digs)
        assert {i for i in np.nonzero(hm)[0]} == \
            {i for i in range(len(digs)) if digs[i].tobytes() in probe2}
        rest = [digs[i].tobytes() for i in range(1, len(digs), 2)]
        r.table.note_new(rest)
        fails0 = metrics.registry("mesh_plane").counter(
            "bucket_refresh_failures")

        def boom(**_kw):
            raise RuntimeError("refresh transport down")

        with fault_injection.inject("sharded.bucket_refresh", boom):
            _c, _d, probe3 = r.reduce_many([a])[0]
        assert probe3 == frozenset(half), "stale table must keep verdicts"
        assert metrics.registry("mesh_plane").counter(
            "bucket_refresh_failures") == fails0 + 1
        _c, _d, probe4 = r.reduce_many([a])[0]   # healthy flush: re-queued
        assert probe4 == frozenset(d.tobytes() for d in digs)


class TestContainerCacheLru:
    def _store(self, tmp_path, cap: int) -> ContainerStore:
        return ContainerStore(str(tmp_path / "c"), container_size=4096,
                              lanes=1, cache_containers=cap)

    def test_hit_refreshes_recency(self, tmp_path):
        """True LRU, not FIFO: a hit moves the container to most-recent,
        so cyclic re-reads of the hot container survive inserts that
        would have evicted the OLDEST-INSERTED entry."""
        store = self._store(tmp_path, cap=2)
        cids = []
        for i in range(3):          # 3 sealed single-chunk containers
            cid, _off, _ln = store.append_chunks([bytes([i]) * 3000])[0]
            store.flush_open()
            cids.append(cid)
        m = metrics.registry("container_store")
        h0, mi0, ev0 = (m.counter("cache_hit"), m.counter("cache_miss"),
                        m.counter("cache_evict"))
        store.read_container(cids[0])            # miss -> cache [0]
        store.read_container(cids[1])            # miss -> cache [0, 1]
        store.read_container(cids[0])            # HIT -> recency [1, 0]
        store.read_container(cids[2])            # miss, evicts 1 (LRU)
        assert m.counter("cache_hit") == h0 + 1
        assert m.counter("cache_miss") == mi0 + 3
        assert m.counter("cache_evict") == ev0 + 1
        h1 = m.counter("cache_hit")
        store.read_container(cids[0])            # still cached: FIFO would
        assert m.counter("cache_hit") == h1 + 1  # have evicted 0, not 1


class TestMixedSizeCoalescer:
    def test_pad_bucket_steps(self):
        from hdrf_tpu.server.write_pipeline import WritePipeline

        pb = WritePipeline._pad_bucket
        assert pb(1) == pb(4096) == 4096         # floor bucket
        for n in (5000, 70_000, 1 << 20, (1 << 20) + 1, 3_000_000):
            b = pb(n)
            top = 1 << (n - 1).bit_length()
            assert b >= n                        # never truncates
            assert b - n < max(top // 8, 4096)   # bounded padding
            assert b % 4096 == 0

    def test_group_buckets_by_lane_size_and_counts_padding(self):
        """Mixed-size submissions coalesce within a lane-size bucket (one
        device program per group, padded to the longest member) instead
        of one group per distinct size; the wasted bytes are surfaced as
        coalesce_pad_bytes."""
        from concurrent.futures import Future

        from hdrf_tpu.server.write_pipeline import WritePipeline, _Item

        class _FakeReducer:
            def max_group(self, n: int = 0) -> int:
                return 8

        wp = WritePipeline.__new__(WritePipeline)   # grouping only
        wp._depth = 8
        sizes = [10_000, 11_000, 12_000, 40_000]    # 3 share bucket 12288
        items = [_Item(i, np.zeros(s, np.uint8), None, Future())
                 for i, s in enumerate(sizes)]
        m0 = metrics.registry("write_pipeline").counter("coalesce_pad_bytes")
        groups = wp._group(_FakeReducer(), items)
        by_len = sorted(len(g) for g in groups)
        assert by_len == [1, 3]                      # bucketed, not per-size
        pad = metrics.registry("write_pipeline").counter(
            "coalesce_pad_bytes") - m0
        assert pad == (12_000 - 10_000) + (12_000 - 11_000)

    def test_mesh_reducer_handles_mixed_size_group(self):
        """One mesh step over blocks of different lengths: per-block
        true_n drives cut selection, so padding to the group max never
        leaks into cuts or digests."""
        r = _mesh_reducer(0x3FF, 256, 4096)
        rng = np.random.default_rng(17)
        group = [rng.integers(0, 256, n, np.uint8)
                 for n in (20_000, 9_999, 33_333, 300)]
        for blk, (cuts, digs, _p) in zip(group, r.reduce_many(group)):
            ref_cuts, ref_digs = _oracle(blk, 0x3FF, 256, 4096)
            np.testing.assert_array_equal(cuts, ref_cuts)
            np.testing.assert_array_equal(digs, ref_digs)


class TestWritePipelineMeshPlane:
    def test_pipeline_routes_groups_through_mesh(self):
        """The product wiring (ReductionConfig.mesh_plane -> WritePipeline
        mesh_reducer): submitted blocks resolve (cuts, digests, probe)
        3-tuples computed by ONE sharded.step dispatch per coalesced
        group, and the mesh_batches counters tick."""
        from hdrf_tpu.server.write_pipeline import WritePipeline

        cdc = CdcConfig(mask_bits=10, min_chunk=256, max_chunk=4096)
        wp = WritePipeline(cdc, "tpu", depth=4, mesh_plane=True,
                           mesh_lanes=1)
        assert wp.mesh_reducer is not None, "8-device mesh must engage"
        try:
            rng = np.random.default_rng(23)
            blocks = [rng.integers(0, 256, 16_000, np.uint8)
                      for _ in range(8)]
            wp.submit(900, blocks[0]).result(120)   # warm compile
            id0 = _last_id()
            m0 = metrics.registry("write_pipeline").counter("mesh_batches")
            futs = [wp.submit(1000 + i, b) for i, b in enumerate(blocks)]
            for blk, fut in zip(blocks, futs):
                cuts, digs, probe = fut.result(120)
                ref_cuts, ref_digs = _oracle(blk, wp.mesh_reducer.mask,
                                             256, 4096)
                np.testing.assert_array_equal(cuts, ref_cuts)
                np.testing.assert_array_equal(digs, ref_digs)
                assert probe == frozenset()
            enq = [e for e in _enqueues_after(id0)
                   if e["op"] == "sharded.step"]
            assert 1 <= len(enq) <= len(blocks) // \
                wp.mesh_reducer.ndata + 1   # coalesced, not per-block
            assert metrics.registry("write_pipeline").counter(
                "mesh_batches") > m0
        finally:
            wp.close()
