"""Control RPC + data-transfer framing."""

import socket
import threading

import pytest

from hdrf_tpu.proto import datatransfer as dt
from hdrf_tpu.proto.rpc import RpcClient, RpcError, RpcServer


class EchoService:
    def rpc_add(self, a, b):
        return a + b

    def rpc_boom(self):
        raise ValueError("kapow")

    def rpc_echo(self, **kw):
        return kw


@pytest.fixture
def server():
    srv = RpcServer("127.0.0.1", 0, EchoService(), "test").start()
    yield srv
    srv.stop()


class TestRpc:
    def test_roundtrip(self, server):
        with RpcClient(server.addr) as c:
            assert c.call("add", a=2, b=3) == 5

    def test_error_roundtrip(self, server):
        with RpcClient(server.addr) as c:
            with pytest.raises(RpcError) as ei:
                c.call("boom")
            assert ei.value.error == "ValueError" and "kapow" in ei.value.message

    def test_unknown_method(self, server):
        with RpcClient(server.addr) as c:
            with pytest.raises(RpcError) as ei:
                c.call("nope")
            assert ei.value.error == "NoSuchMethod"

    def test_binary_and_nested_payloads(self, server):
        with RpcClient(server.addr) as c:
            out = c.call("echo", blob=b"\x00\xff" * 100, nested={"a": [1, 2]})
            assert out["blob"] == b"\x00\xff" * 100
            assert out["nested"] == {"a": [1, 2]}

    def test_concurrent_clients(self, server):
        errs = []

        def worker(n):
            try:
                with RpcClient(server.addr) as c:
                    for i in range(50):
                        assert c.call("add", a=n, b=i) == n + i
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(n,)) for n in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs

    def test_reconnect_after_server_restart(self, server):
        c = RpcClient(server.addr)
        assert c.call("add", a=1, b=1) == 2
        c._sock.close()  # simulate broken connection
        with pytest.raises((ConnectionError, OSError)):
            c.call("add", a=1, b=1)
        assert c.call("add", a=2, b=2) == 4  # auto-reconnect on next call
        c.close()


class TestDataTransfer:
    def _pair(self):
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        cli = socket.create_connection(srv.getsockname())
        conn, _ = srv.accept()
        srv.close()
        return cli, conn

    def test_packet_roundtrip(self):
        a, b = self._pair()
        dt.write_packet(a, 7, b"hello", last=False)
        dt.write_packet(a, 8, b"", last=True)
        assert dt.read_packet(b) == (7, b"hello", False)
        assert dt.read_packet(b) == (8, b"", True)
        a.close(), b.close()

    def test_checksum_detects_corruption(self):
        a, b = self._pair()
        hdr = dt.PKT_HDR.pack(5, 1, 0, 12345)  # wrong crc
        a.sendall(hdr + b"hello")
        with pytest.raises(IOError, match="checksum"):
            dt.read_packet(b)
        a.close(), b.close()

    def test_stream_and_collect(self):
        a, b = self._pair()
        data = bytes(range(256)) * 1000
        n = dt.stream_bytes(a, data, packet_size=4096)
        # full data packets + partial tail packet + empty LAST trailer
        import math
        assert n == math.ceil(len(data) / 4096) + 1
        assert dt.collect_packets(b) == data
        a.close(), b.close()

    def test_op_header_roundtrip(self):
        a, b = self._pair()
        dt.send_op(a, dt.WRITE_BLOCK, block_id=5, targets=[{"addr": ["h", 1]}])
        op, fields = dt.recv_op(b)
        assert op == dt.WRITE_BLOCK and fields["block_id"] == 5
        a.close(), b.close()

    def test_acks(self):
        a, b = self._pair()
        dt.send_ack(a, 42, dt.ACK_SUCCESS)
        dt.send_ack(a, 43, dt.ACK_ERROR)
        assert dt.read_ack(b) == (42, dt.ACK_SUCCESS)
        assert dt.read_ack(b) == (43, dt.ACK_ERROR)
        a.close(), b.close()
