"""Centralized cache management (CacheManager.java:103 + the DN-side
FsDatasetCache.java:67 pinned-memory path): pools, directives, the cache
monitor driving DNA_CACHE/UNCACHE, and reads served from pinned memory."""

from __future__ import annotations

import time

import numpy as np
import pytest

from hdrf_tpu.testing.minicluster import MiniCluster

RNG = np.random.default_rng(41)


def _bytes(n):
    return RNG.integers(0, 256, size=n, dtype=np.uint8).tobytes()


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_datanodes=2, replication=1, block_size=1 << 20) as mc:
        yield mc


def _wait_cached(c, did, nblocks, timeout=12.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        (d,) = [x for x in c.list_cache_directives() if x["id"] == did]
        if d["blocks_cached"] >= nblocks:
            return d
        time.sleep(0.3)
    pytest.fail("blocks never reported cached")


class TestCacheDirectives:
    def test_pool_and_directive_lifecycle(self, cluster):
        with cluster.client("p") as c:
            assert c.add_cache_pool("gold")
            assert "gold" in c.list_cache_pools()
            c.write("/cached/f", _bytes(300_000))
            did = c.add_cache_directive("/cached/f", pool="gold")
            d = _wait_cached(c, did, 1)
            assert d["blocks"] == 1 and d["path"] == "/cached/f"
            assert c.remove_cache_directive(did)
            assert all(x["id"] != did for x in c.list_cache_directives())

    def test_cached_read_skips_disk(self, cluster):
        """The strong assertion: after caching, delete the replica's
        on-disk data file — the read STILL succeeds (served from pinned
        memory), proving the disk was never touched."""
        with cluster.client("s") as c:
            data = _bytes(500_000)
            c.write("/cached/skip", data, scheme="direct")
            c.add_cache_pool("hot") if "hot" not in c.list_cache_pools() \
                else None
            did = c.add_cache_directive("/cached/skip", pool="hot")
            _wait_cached(c, did, 1)
            # find the DN holding the pinned block and vandalize its disk
            loc = c._call("get_block_locations", path="/cached/skip")
            bid = loc["blocks"][0]["block_id"]
            dn = next(d for d in cluster.datanodes
                      if d is not None and bid in d.cache.ids())
            import os

            os.unlink(dn.replicas.data_path(bid))
            assert c.read("/cached/skip") == data  # RAM, not disk
            from hdrf_tpu.utils import metrics

            assert metrics.registry("datanode").snapshot()[
                "counters"].get("cache_hits", 0) > 0

    def test_uncache_on_directive_removal(self, cluster):
        with cluster.client("u") as c:
            c.write("/cached/u", _bytes(200_000))
            if "hot" not in c.list_cache_pools():
                c.add_cache_pool("hot")
            did = c.add_cache_directive("/cached/u", pool="hot")
            _wait_cached(c, did, 1)
            loc = c._call("get_block_locations", path="/cached/u")
            bid = loc["blocks"][0]["block_id"]
            c.remove_cache_directive(did)
            deadline = time.time() + 12
            while time.time() < deadline:
                if not any(d is not None and bid in d.cache.ids()
                           for d in cluster.datanodes):
                    break
                time.sleep(0.3)
            else:
                pytest.fail("block never uncached after directive removal")

    def test_directive_on_directory_caches_all_files(self, cluster):
        with cluster.client("d") as c:
            if "hot" not in c.list_cache_pools():
                c.add_cache_pool("hot")
            for i in range(3):
                c.write(f"/cdir/f{i}", _bytes(100_000))
            did = c.add_cache_directive("/cdir", pool="hot")
            d = _wait_cached(c, did, 3)
            assert d["blocks"] == 3

    def test_directives_survive_restart(self, tmp_path):
        from hdrf_tpu.config import NameNodeConfig
        from hdrf_tpu.server.namenode import NameNode

        nn = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "nn")))
        nn.rpc_add_cache_pool("p1")
        nn.rpc_mkdir("/x")
        did = nn.rpc_add_cache_directive("/x", pool="p1")
        nn._editlog.close()
        nn2 = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "nn")))
        assert "p1" in nn2.rpc_list_cache_pools()
        assert any(d["id"] == did for d in nn2.rpc_list_cache_directives())
        nn2._editlog.close()

    def test_pool_required(self, cluster):
        from hdrf_tpu.proto.rpc import RpcError

        with cluster.client("e") as c:
            c.write("/cached/np", b"x" * 100)
            with pytest.raises(RpcError):
                c.add_cache_directive("/cached/np", pool="nosuchpool")


class TestReviewHoles:
    def test_append_invalidates_pinned_block(self, cluster):
        """Copy-on-append rewrites a pinned block id: the stale pinned
        bytes must not serve the post-append read."""
        with cluster.client("ap") as c:
            if "hot" not in c.list_cache_pools():
                c.add_cache_pool("hot")
            data = _bytes(100_000)
            c.write("/cached/ap", data, scheme="direct")
            did = c.add_cache_directive("/cached/ap", pool="hot")
            _wait_cached(c, did, 1)
            c.append("/cached/ap", b"TAIL" * 100)
            assert c.read("/cached/ap") == data + b"TAIL" * 100
            c.remove_cache_directive(did)

    def test_rename_through_symlink(self, cluster):
        with cluster.client("rn") as c:
            c.mkdir("/rtarget")
            c.create_symlink("/rlink", "/rtarget")
            c.write("/rtarget/x", b"move-me")
            c.rename("/rlink/x", "/rlink/y")
            assert c.read("/rtarget/y") == b"move-me"

    def test_remove_directive_permission(self, cluster):
        from hdrf_tpu.proto.rpc import RpcError
        from hdrf_tpu.client.filesystem import HdrfClient

        with cluster.client("own") as c:
            if "hot" not in c.list_cache_pools():
                c.add_cache_pool("hot")
            c.mkdir("/home2")
            c.chmod("/home2", 0o777)
        al = HdrfClient(cluster.namenode.addr, user="alice")
        mal = HdrfClient(cluster.namenode.addr, user="mallory")
        try:
            al.write("/home2/f", b"mine")
            did = al.add_cache_directive("/home2/f", pool="hot")
            with pytest.raises(RpcError):
                mal.remove_cache_directive(did)
            assert al.remove_cache_directive(did)
        finally:
            al.close()
            mal.close()
