"""The in-tree perf harnesses run end-to-end at tiny sizes (the reference
keeps NNThroughputBenchmark etc. in the test tree; results are printed JSON,
not asserted)."""

import json
import io
from contextlib import redirect_stdout

from hdrf_tpu import benchmarks


def run(argv) -> list[dict]:
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert benchmarks.main(argv) == 0
    return [json.loads(line) for line in buf.getvalue().splitlines()]


def test_nn_throughput():
    out = run(["nn", "--ops", "50"])
    assert {o["op"] for o in out} >= {"mkdir", "delete"}
    assert all(o["ops_per_s"] > 0 for o in out)


def test_dfs_throughput():
    out = run(["dfs", "--mb", "2", "--datanodes", "2", "--replication", "1",
               "--schemes", "direct,dedup_lz4"])
    assert len(out) == 2 and all(o["write_MBps"] > 0 for o in out)


def test_ec_throughput():
    out = run(["ec", "--mb", "3", "--policy", "rs-3-2-4k"])
    assert len(out) == 4


def test_reduction_throughput():
    out = run(["reduction", "--mb", "4", "--backend", "native"])
    assert out[0]["chunks"] > 0


def test_sort_harness():
    out = run(["sort", "--tiles", "1", "--entries", "2048", "--inner", "2",
               "--repeats", "1"])
    ops = {o["op"] for o in out}
    # CPU mesh: only the XLA path times; the readback ledger always prints
    assert "match_deltas [xla]" in ops and "sort_rows [xla]" in ops
    (ledger,) = [o for o in out if o["op"] == "record readback"]
    assert ledger["reduction_pct"] >= 25.0  # the ISSUE acceptance bar
