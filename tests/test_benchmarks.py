"""The in-tree perf harnesses run end-to-end at tiny sizes (the reference
keeps NNThroughputBenchmark etc. in the test tree; results are printed JSON,
not asserted)."""

import json
import io
from contextlib import redirect_stdout

from hdrf_tpu import benchmarks


def run(argv) -> list[dict]:
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert benchmarks.main(argv) == 0
    return [json.loads(line) for line in buf.getvalue().splitlines()]


def test_nn_metadata_storm_one_json_line():
    """`benchmarks nn` contract (ISSUE 18 acceptance): EXACTLY one JSON
    line carrying the contention observatory's storm verdict —
    rpc_p99_ms, lock_saturation, the per-method lock-share curve and the
    attribution fraction.  Tiny storm: shape and sanity, not the bar."""
    out = run(["nn", "--ops", "60", "--clients", "3", "--meta-per-op", "2"])
    assert len(out) == 1
    (o,) = out
    assert o["bench"] == "nn_metadata_storm"
    assert o["clients"] == 3 and o["errors"] == 0
    assert o["ops_per_s"] > 0 and o["rpc_calls"] > 0
    assert o["rpc_p99_ms"] > 0
    assert 0.0 <= o["lock_saturation"] <= 1.0
    assert o["lock_wait_p99_us"] >= 0.0
    assert o["top_method"] in o["lock_share"]
    assert all(0.0 <= v <= 1.0 for v in o["lock_share"].values())
    assert o["attributed_frac"] >= 0.95


def test_dfs_throughput():
    out = run(["dfs", "--mb", "2", "--datanodes", "2", "--replication", "1",
               "--schemes", "direct,dedup_lz4"])
    assert len(out) == 2 and all(o["write_MBps"] > 0 for o in out)


def test_dfs_pipeline_ab_one_json_line():
    """`benchmarks dfs --pipeline-ab` contract: EXACTLY one JSON line with
    the paired depth-1 vs depth-N multi-stream rates and their median
    ratio (the ISSUE 7 acceptance shape).  Tiny corpus, one round — this
    asserts the protocol and line shape, not the speedup bar."""
    out = run(["dfs", "--pipeline-ab", "--mb", "1", "--streams", "2",
               "--rounds", "1", "--depth", "4"])
    assert len(out) == 1
    (o,) = out
    assert o["op"].startswith("dfs write pipeline A/B")
    assert o["streams"] == 2 and o["depth"] == 4
    assert o["depth1_MBps"] > 0 and o["depthN_MBps"] > 0
    assert o["speedup"] > 0


def test_ec_throughput():
    # PR 8 contract: the ec harness prints ONE JSON line — the paired
    # encode/intact/degraded slope report, oracle-pinned before timing
    out = run(["ec", "--mb", "3", "--policy", "rs-3-2-4k", "--inner", "2"])
    assert len(out) == 1
    (o,) = out
    assert o["parity_oracle_ok"] is True
    assert o["k"] == 3 and o["m"] == 2
    assert o["encode_MBps"] > 0 and o["degraded_read_MBps"] > 0


def test_ec_repair_ab_one_json_line():
    # PR 16 contract: the paired repair harness prints ONE JSON line —
    # coded partial-sum repair vs the classic full gather, every erasure
    # pattern oracle-pinned before timing, wire ratio well below k
    out = run(["ec", "--repair-ab", "--mb", "2", "--policy", "rs-3-2-4k",
               "--inner", "2", "--dns", "4"])
    assert len(out) == 1
    (o,) = out
    assert o["op"].startswith("ec repair A/B")
    assert o["parity_oracle_ok"] is True
    assert o["patterns_pinned"] > 0
    assert o["repair_wire_ratio_coded"] < o["repair_wire_ratio_full"]
    assert o["repair_wire_ratio_coded"] <= 1.0 + 1e-6
    assert abs(o["repair_wire_ratio_full"] - o["k"]) < 1e-6


def test_reduction_throughput():
    out = run(["reduction", "--mb", "4", "--backend", "native"])
    assert out[0]["chunks"] > 0


def test_cdc_harness_one_json_line():
    """`benchmarks cdc` contract: EXACTLY one JSON line carrying the
    fused-vs-XLA slope A/B and the per-block readback byte ledger (the
    ISSUE 4 acceptance shape).  Tiny corpus; the fused kernel runs in the
    Pallas interpreter on the CPU mesh."""
    out = run(["cdc", "--mb", "1", "--inner", "2", "--repeats", "1"])
    assert len(out) == 1
    (o,) = out
    assert o["op"].startswith("cdc_prep")
    assert o["interpret"] is True  # no chip on the test mesh
    assert o["fused_ms_per_block"] > 0 and o["xla_ms_per_block"] > 0
    assert o["cand_d2h_bytes_per_block_xla"] > \
        o["cut_table_d2h_bytes_per_block_fused"]
    assert o["serial_awaited_boundaries"] == {"xla": 2, "fused": 1}


def test_multichip_harness_one_json_line():
    """`benchmarks multichip` contract: EXACTLY one JSON line — the
    1/2/4/8-device service-rate curve, pinned bit-identical to the native
    oracle before timing, with device-ledger evidence that every mesh
    step was ONE dispatch (the ISSUE 9 acceptance shape).  Tiny corpus,
    one repeat — this asserts the protocol and line shape, not the
    scaling bar (PERF_NOTES round 13 carries the measured curve)."""
    out = run(["multichip", "--blocks", "16", "--repeats", "1"])
    assert len(out) == 1
    (o,) = out
    assert o["op"].startswith("multichip")
    assert o["oracle_ok"] is True
    assert o["one_dispatch_per_step"] is True
    assert set(o["MBps"]) == {"1", "2", "4", "8"}
    assert all(v > 0 for v in o["MBps"].values())
    assert o["ratio_8v1"] > 0
    assert o["steps"] == o["step_dispatches"]


def test_sort_harness():
    out = run(["sort", "--tiles", "1", "--entries", "2048", "--inner", "2",
               "--repeats", "1"])
    ops = {o["op"] for o in out}
    # CPU mesh: only the XLA path times; the readback ledger always prints
    assert "match_deltas [xla]" in ops and "sort_rows [xla]" in ops
    (ledger,) = [o for o in out if o["op"] == "record readback"]
    assert ledger["reduction_pct"] >= 25.0  # the ISSUE acceptance bar
