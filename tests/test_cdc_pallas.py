"""Fused Pallas CDC front end (ops/cdc_pallas.py) against the native C++
oracle and the XLA prep path.

Everything runs the kernel through the Pallas interpreter on the CPU mesh —
the IDENTICAL kernel program Mosaic compiles on a chip (the sort_pallas test
precedent) — so tier-1 pins the device-side cut selection bit-for-bit:
boundaries, SHA digests, the capacity-overflow fallback, the shared
window-warmup convention, and the ledger shape of the steady state (zero
candidate readbacks, SHA enqueued before the cut table lands).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hdrf_tpu import native
from hdrf_tpu.config import CdcConfig
from hdrf_tpu.ops import cdc_pallas, gear
from hdrf_tpu.ops.dispatch import gear_mask
from hdrf_tpu.ops.resident import ResidentReducer
from hdrf_tpu.utils import device_ledger


def _oracle_cuts(a: np.ndarray, mask: int, mn: int, mx: int) -> np.ndarray:
    return np.asarray(native.cdc_chunk(a.tobytes(), mask, mn, mx),
                      dtype=np.uint64)


def _corpora():
    rng = np.random.default_rng(7)
    text = rng.integers(97, 123, size=200_000, dtype=np.uint8)
    yield "random", rng.integers(0, 256, 150_000, dtype=np.uint8), \
        0x1FFF, 2048, 65536
    yield "text-low-entropy", text, 0x1FFF, 2048, 65536
    # sparse mask -> candidate droughts -> forced max-chunk runs
    yield "forced-max-runs", rng.integers(0, 256, 120_000, dtype=np.uint8), \
        0xFFFFFF, 512, 4096
    # dense mask + tiny limits: every-word candidates, lo>hi edge traffic
    yield "dense", rng.integers(0, 256, 30_000, dtype=np.uint8), 0x7, 8, 64
    # block tail shorter than min_chunk: final cut is the short remainder
    yield "tail-short-chunk", rng.integers(0, 256, 65536 + 37,
                                           dtype=np.uint8), \
        0x1FFF, 2048, 65536
    # one supertile exactly / less than one supertile
    yield "single-tile", rng.integers(0, 256, 65536, dtype=np.uint8), \
        0x3FF, 256, 8192
    yield "sub-tile", rng.integers(0, 256, 300, dtype=np.uint8), 0x3F, 16, 128


@pytest.mark.parametrize("skip_ahead", [True, False],
                         ids=["skip-ahead", "pr4-walk"])
@pytest.mark.parametrize("name,a,mask,mn,mx",
                         list(_corpora()),
                         ids=[c[0] for c in _corpora()])
def test_device_cuts_bit_identical_to_native(name, a, mask, mn, mx,
                                             skip_ahead):
    """ISSUE 15 A/B: BOTH scan variants — the skip-ahead + sequence-select
    kernel and the pinned PR 4 frontier walk — must reproduce the native
    oracle's cuts on every corpus (the acceptance gate that runs before
    any timing claim)."""
    cuts, overflowed = cdc_pallas.chunks_fused(
        a, mask, mn, mx, mask_bits=max(bin(mask).count("1"), 1),
        interpret=True, skip_ahead=skip_ahead)
    assert not overflowed
    np.testing.assert_array_equal(cuts, _oracle_cuts(a, mask, mn, mx))


def test_candidate_at_position_zero_and_warmup_vector():
    """The shared window-warmup convention (ISSUE 4 satellite): byte
    position 0 (pos1 = 1) can NEVER be a cut and the first admissible
    candidate is gear.MIN_CANDIDATE_POS1 — pinned with ONE vector against
    all three producers (XLA gear scan, fused kernel, native oracle)
    instead of two implicit implementations.  mask 0 makes every position
    hash-eligible, so only the warmup rule decides."""
    z = np.zeros(256, dtype=np.uint8)
    pos = gear.gear_candidates_jax(z, mask=0)
    assert pos[0] == gear.MIN_CANDIDATE_POS1 == gear.WINDOW
    cuts, of = cdc_pallas.chunks_fused(z, 0, 1, 4096, interpret=True)
    assert not of
    want = _oracle_cuts(z, 0, 1, 4096)
    assert cuts[0] == want[0] == gear.MIN_CANDIDATE_POS1
    np.testing.assert_array_equal(cuts, want)


def test_fused_reduce_matches_oracle_end_to_end():
    """Cuts AND digests through the fused ResidentReducer pipeline (group
    submit, on-device binning, enqueue-before-readback SHA) vs the XLA
    oracle reducer."""
    rng = np.random.default_rng(11)
    cdc = CdcConfig()
    rf = ResidentReducer(cdc, fused_mode="interpret")
    rx = ResidentReducer(cdc, fused_mode="off")
    datas = [rng.integers(0, 256, 1 << 19, dtype=np.uint8),
             rng.integers(0, 256, 1 << 19, dtype=np.uint8),
             rng.integers(0, 256, 333_333, dtype=np.uint8)]
    for (cf, df), (cx, dx) in zip(rf.reduce_many(datas),
                                  rx.reduce_many(datas)):
        np.testing.assert_array_equal(cf, cx)
        np.testing.assert_array_equal(df, dx)


def test_fused_device_resident_input():
    """The streamed-worker form: an HBM-resident (K, n) u8 group enters the
    fused path through the on-device LE word image (MXU combine), no host
    bytes involved."""
    rng = np.random.default_rng(21)
    cdc = CdcConfig()
    rf = ResidentReducer(cdc, fused_mode="interpret")
    rx = ResidentReducer(cdc, fused_mode="off")
    dev = jax.device_put(rng.integers(0, 256, (2, 1 << 19), dtype=np.uint8))
    bjf = rf.submit_many(dev)
    rf.start_sha_many(bjf)
    bjx = rx.submit_many(dev)
    rx.start_sha_many(bjx)
    for (cf, df), (cx, dx) in zip(rf.finish_many(bjf), rx.finish_many(bjx)):
        np.testing.assert_array_equal(cf, cx)
        np.testing.assert_array_equal(df, dx)


def _events_after(last_id: int):
    return [e for e in device_ledger.events_snapshot()
            if e["id"] > last_id]


def _last_event_id() -> int:
    evs = device_ledger.events_snapshot()
    return evs[-1]["id"] if evs else 0


def test_overflow_fallback_low_entropy_corpus():
    """ISSUE 4 satellite: a pathological block (zeros -> every position a
    candidate) overflows the kernel's cut capacity; the header flags it and
    the group reruns through the XLA prep + host-select oracle path —
    boundaries are never silently truncated."""
    cdc = CdcConfig(mask_bits=20, min_chunk=64, max_chunk=4096)
    rf = ResidentReducer(cdc, fused_mode="interpret")
    a = np.zeros(1 << 18, dtype=np.uint8)
    # the plan's distributional cap really is smaller than the cut count
    plan = cdc_pallas.plan_for(a.size, gear_mask(cdc), cdc.mask_bits,
                               cdc.min_chunk, cdc.max_chunk,
                               rf._b_small, rf._b_big)
    want = _oracle_cuts(a, gear_mask(cdc), cdc.min_chunk, cdc.max_chunk)
    assert len(want) > plan.cap
    t0 = _last_event_id()
    cuts, digs = rf.reduce(a)
    np.testing.assert_array_equal(cuts, want)
    starts = np.concatenate([[0], cuts[:-1]]).astype(np.uint64)
    np.testing.assert_array_equal(
        digs, native.sha256_batch(a, starts,
                                  (cuts - starts).astype(np.uint64)))
    ops = {e["op"] for e in _events_after(t0)}
    assert "resident.cdc_fused" in ops         # the fused attempt
    assert "resident.prep_batch" in ops        # ...and the oracle fallback


@pytest.mark.parametrize("skip_ahead", [True, False],
                         ids=["skip-ahead", "pr4-walk"])
def test_overflow_still_fires_at_smallest_controller_geometry(skip_ahead):
    """ISSUE 15 overflow-header regression: the skip-ahead plan's
    renewal-spacing cut capacity must stay TIGHT enough that the zeros
    corpus still overflows into the XLA fallback at the coarsest geometry
    the adaptive controller can emit (mask_bits floor, smallest min) —
    a looser cap would silently truncate boundaries instead."""
    from hdrf_tpu.reduction.accounting import AdaptiveChunkController

    mb = AdaptiveChunkController.MASK_BITS_MIN
    cdc = CdcConfig(mask_bits=mb, min_chunk=64, max_chunk=2048)
    a = np.zeros(1 << 18, dtype=np.uint8)
    plan = cdc_pallas.plan_for(a.size, gear_mask(cdc), cdc.mask_bits,
                               cdc.min_chunk, cdc.max_chunk, 1 << 30,
                               1 << 30, skip_ahead=skip_ahead)
    want = _oracle_cuts(a, gear_mask(cdc), cdc.min_chunk, cdc.max_chunk)
    assert len(want) > plan.cap          # the cap really is exceeded...
    cuts, overflowed = cdc_pallas.chunks_fused(
        a, gear_mask(cdc), cdc.min_chunk, cdc.max_chunk,
        mask_bits=cdc.mask_bits, interpret=True, skip_ahead=skip_ahead)
    assert overflowed                    # ...and the header reports it
    # the skip-ahead cap is never LOOSER than the PR 4 cap
    walk = cdc_pallas.plan_for(a.size, gear_mask(cdc), cdc.mask_bits,
                               cdc.min_chunk, cdc.max_chunk, 1 << 30,
                               1 << 30, skip_ahead=False)
    assert plan.cap <= walk.cap


def test_ledger_zero_candidate_d2h_and_one_fewer_boundary():
    """ISSUE 4 satellite (the test_health zero-dispatch pinning pattern):
    a steady-state fused reduce records ZERO candidate-readback events (no
    resident.prep* at all), and the SHA dispatches are ENQUEUED before the
    fused kernel's completion event — the prep->select->sha awaited
    boundary the XLA path pays is structurally absent."""
    rng = np.random.default_rng(31)
    cdc = CdcConfig()
    rf = ResidentReducer(cdc, fused_mode="interpret")
    datas = [rng.integers(0, 256, 1 << 19, dtype=np.uint8)
             for _ in range(2)]
    rf.reduce_many(datas)                      # steady state: shapes warm
    t0 = _last_event_id()
    led0 = device_ledger.stamp()
    bj = rf.submit_many(datas)
    rf.start_sha_many(bj)
    out = rf.finish_many(bj)
    assert all(int(c[-1]) == datas[0].size for c, _ in out)
    evs = _events_after(t0)
    prep_ops = {"resident.prep", "resident.prep_batch",
                "resident.prep_retry"}
    assert not [e for e in evs if e["op"] in prep_ops], evs
    # every SHA enqueue precedes the fused-CDC completion: nothing awaited
    # stands between cut selection and SHA placement
    fused_done = [e["id"] for e in evs if e["op"] == "resident.cdc_fused"
                  and e["kind"] == "dispatch"]
    sha_enq = [e["id"] for e in evs if e["op"] == "resident.sha"
               and e["kind"] == "enqueue"]
    assert fused_done and sha_enq
    assert max(sha_enq) < min(fused_done)
    # dispatch budget of the whole steady-state pass: 1 fused + 2 sha
    led = device_ledger.delta(led0)
    assert led["dispatch_total"] == 3, led

    # contrast: the XLA path's SHA enqueues FOLLOW its prep completion
    rx = ResidentReducer(cdc, fused_mode="off")
    rx.reduce_many(datas)
    t1 = _last_event_id()
    bj = rx.submit_many(datas)
    rx.start_sha_many(bj)
    rx.finish_many(bj)
    evs = _events_after(t1)
    prep_done = [e["id"] for e in evs if e["op"] == "resident.prep_batch"
                 and e["kind"] == "dispatch"]
    sha_enq = [e["id"] for e in evs if e["op"] == "resident.sha"
               and e["kind"] == "enqueue"]
    assert prep_done and sha_enq
    assert min(sha_enq) > max(prep_done)


def test_sharded_scan_kernel_bit_identical():
    """The scan-only kernel variant behind parallel/sharded.py: same halo,
    same packed-bitmap words as the XLA per-shard scan, on the 8-virtual-
    device mesh (shard_map + ppermute + psum actually execute)."""
    from hdrf_tpu.parallel import make_mesh
    from hdrf_tpu.parallel.sharded import candidate_words_sharded

    mesh = make_mesh(n_data=1, n_seq=len(jax.devices()))
    n_seq = mesh.shape["seq"]
    rng = np.random.default_rng(3)
    blk = jnp.asarray(rng.integers(0, 256, 4096 * n_seq, dtype=np.uint8))
    mask = jnp.uint32(0x1FFF)
    wx, cx = candidate_words_sharded(mesh, fused="off")(blk, mask)
    wp, cp = candidate_words_sharded(mesh, fused="interpret")(blk, mask)
    np.testing.assert_array_equal(np.asarray(wx), np.asarray(wp))
    assert int(cx) == int(cp)


def test_le_word_image_and_nibble_pack():
    """Helper contracts: le_word_image == numpy's LE u32 view; the nibble
    pack reproduces gear.pack_bitmap_words' bit layout exactly."""
    rng = np.random.default_rng(9)
    a = rng.integers(0, 256, 2048, dtype=np.uint8)
    np.testing.assert_array_equal(
        np.asarray(cdc_pallas.le_word_image(jnp.asarray(a))),
        a.view(np.uint32))
    bits = rng.integers(0, 2, 2048).astype(bool)
    want = np.asarray(gear.pack_bitmap_words(jnp.asarray(bits)))
    nib = np.asarray([int(bits[i]) | (int(bits[i + 1]) << 1)
                      | (int(bits[i + 2]) << 2) | (int(bits[i + 3]) << 3)
                      for i in range(0, 2048, 4)], dtype=np.int32)
    got = np.asarray(cdc_pallas._pack_nibbles(jnp.asarray(nib)))
    np.testing.assert_array_equal(got, want)
