"""End-to-end MiniCluster tests: the §3.1/§3.2 flagship paths, failure
handling, and reduced block mirroring."""

import os
import random
import time

import pytest

from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.utils import codec


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_datanodes=3, replication=2, block_size=256 * 1024) as c:
        yield c


def blob(seed: int, n: int) -> bytes:
    return random.Random(seed).randbytes(n)


class TestEndToEnd:
    def test_write_read_direct(self, cluster):
        data = blob(1, 700_000)  # spans 3 blocks
        with cluster.client() as c:
            c.write("/e2e/direct", data, scheme="direct")
            assert c.read("/e2e/direct") == data
            st = c.stat("/e2e/direct")
            assert st["length"] == len(data) and st["blocks"] == 3

    @pytest.mark.parametrize("scheme", [
        "lz4",
        pytest.param("zstd", marks=pytest.mark.skipif(
            not codec.available("zstd"),
            reason="zstandard module not installed")),
        "dedup_lz4"])
    def test_write_read_reduced(self, cluster, scheme):
        base = blob(2, 200_000)
        data = base * 3 + blob(3, 100_000)  # dedup-friendly
        with cluster.client() as c:
            c.write(f"/e2e/{scheme}", data, scheme=scheme)
            assert c.read(f"/e2e/{scheme}") == data

    def test_range_reads(self, cluster):
        data = blob(4, 600_000)
        with cluster.client() as c:
            c.write("/e2e/range", data, scheme="dedup_lz4")
            for off, ln in [(0, 100), (255_000, 3000), (599_990, 10),
                            (100_000, 400_000)]:
                assert c.read("/e2e/range", off, ln) == data[off:off + ln]

    def test_namespace_ops(self, cluster):
        with cluster.client() as c:
            c.mkdir("/ns/a")
            c.write("/ns/a/f", b"hello", scheme="direct")
            assert {e["name"] for e in c.ls("/ns/a")} == {"f"}
            c.rename("/ns/a/f", "/ns/b/g")
            assert c.read("/ns/b/g") == b"hello"
            assert c.delete("/ns/b/g")
            assert not c.exists("/ns/b/g")

    def test_empty_file(self, cluster):
        with cluster.client() as c:
            c.write("/e2e/empty", b"", scheme="direct")
            assert c.read("/e2e/empty") == b""

    def test_dedup_across_files_saves_space(self):
        # Dedicated 1-DN cluster: both files land on the same node, so the
        # second file's chunks must all dedup against the first's.
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=256 * 1024) as cluster:
            data = blob(5, 400_000)
            with cluster.client() as c:
                c.write("/dedup/one", data, scheme="dedup_lz4")
                c.write("/dedup/two", data, scheme="dedup_lz4")
                assert c.read("/dedup/two") == data
            st = cluster.datanodes[0].index.stats()
            assert st["logical_bytes"] == 2 * len(data)
            assert st["unique_chunk_bytes"] <= len(data) + 70_000  # ~one copy


class TestReducedMirroring:
    def test_mirror_has_reduced_form_not_rerun(self, cluster):
        """Replicas of a dedup'd block exist on 2 DNs with consistent logical
        bytes served from both."""
        data = blob(6, 300_000)
        with cluster.client() as c:
            c.write("/mirror/f", data, scheme="dedup_lz4", replication=2)
            cluster.wait_for_replication("/mirror/f", 2)
            loc = c._nn.call("get_block_locations", path="/mirror/f")
            for b in loc["blocks"]:
                assert len(b["locations"]) == 2
                # read from EACH location directly
                for l in b["locations"]:
                    got = c._read_from(tuple(l["addr"]), b["block_id"], 0, -1)
                    assert len(got) == b["length"]


class TestFailure:
    def test_read_failover_after_dn_death(self):
        with MiniCluster(n_datanodes=3, replication=2,
                         block_size=128 * 1024) as cluster:
            data = blob(7, 300_000)
            with cluster.client() as c:
                c.write("/fail/f", data, scheme="lz4")
                cluster.wait_for_replication("/fail/f", 2)
                cluster.kill_datanode(0)
                assert c.read("/fail/f") == data  # failover to live replica

    def test_rereplication_after_dn_death(self):
        with MiniCluster(n_datanodes=3, replication=2, block_size=128 * 1024,
                         heartbeat_s=0.1, dead_node_s=0.5) as cluster:
            data = blob(8, 200_000)
            with cluster.client() as c:
                c.write("/rerep/f", data, scheme="dedup_lz4")
                cluster.wait_for_replication("/rerep/f", 2)
                cluster.kill_datanode(0)
                # monitor notices death, schedules re-replication to dn 2
                cluster.wait_for_replication("/rerep/f", 2, timeout=20)
                assert c.read("/rerep/f") == data

    def test_datanode_restart_recovers_state(self):
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=128 * 1024) as cluster:
            data = blob(9, 250_000)
            with cluster.client() as c:
                c.write("/restart/f", data, scheme="dedup_lz4")
                cluster.stop_datanode(0)
                cluster.restart_datanode(0)
                cluster.wait_for_datanodes(1)
                assert c.read("/restart/f") == data

    def test_namenode_restart_recovers_namespace(self):
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=128 * 1024) as cluster:
            data = blob(10, 150_000)
            with cluster.client() as c:
                c.write("/nnrestart/f", data, scheme="lz4")
            cluster.restart_namenode()
            # DN re-registers on next heartbeat (reregister flag)
            cluster.wait_for_datanodes(1)
            deadline = time.monotonic() + 10
            with cluster.client() as c:
                while time.monotonic() < deadline:
                    try:
                        assert c.read("/nnrestart/f") == data
                        break
                    except IOError:
                        time.sleep(0.2)
                else:
                    pytest.fail("file unreadable after NN restart")


class TestPlacementAndTrash:
    def test_rack_aware_placement(self, tmp_path):
        from hdrf_tpu.config import DataNodeConfig, NameNodeConfig
        from hdrf_tpu.server.datanode import DataNode
        from hdrf_tpu.server.namenode import NameNode
        from hdrf_tpu.client.filesystem import HdrfClient
        import os

        nn = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "nn"),
                                     replication=2,
                                     block_size=64 * 1024)).start()
        dns = []
        try:
            for i in range(4):
                cfg = DataNodeConfig(
                    data_dir=str(tmp_path / f"dn{i}"),
                    rack=f"/rack{i % 2}", heartbeat_interval_s=0.2)
                dns.append(DataNode(cfg, nn.addr, dn_id=f"dn-{i}").start())
            with HdrfClient(nn.addr, name="rack") as c:
                import time
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    if sum(d["alive"] for d in c.datanode_report()) == 4:
                        break
                    time.sleep(0.05)
                for i in range(6):
                    c.write(f"/r/f{i}", b"z" * 10_000)
                    # complete() returns once ONE replica reported; wait for
                    # the second IBR before asserting rack spread
                    deadline = time.monotonic() + 10
                    while time.monotonic() < deadline:
                        loc = c._nn.call("get_block_locations",
                                         path=f"/r/f{i}")
                        if len(loc["blocks"][0]["locations"]) >= 2:
                            break
                        time.sleep(0.05)
                    racks = {nn._datanodes[ld["dn_id"]].rack
                             for ld in loc["blocks"][0]["locations"]}
                    assert len(racks) == 2, f"replicas on one rack: {racks}"
        finally:
            for dn in dns:
                dn.stop()
            nn.stop()

    def test_trash_and_expunge(self, cluster):
        with cluster.client("trash") as c:
            root = c._trash_root()
            c.write("/t/doomed", b"bytes" * 1000)
            c.delete("/t/doomed", skip_trash=False)
            assert not c.exists("/t/doomed")
            # same-second re-delete of a recreated path disambiguates
            c.write("/t/doomed", b"again")
            c.delete("/t/doomed", skip_trash=False)
            trash = c.ls(root)
            assert len(trash) == 2
            names = sorted(e["name"] for e in trash)
            restored = c.read(f"{root}/{names[0]}")
            assert restored == b"bytes" * 1000
            # -rm of a trash entry is a permanent delete, not a re-trash
            assert c.delete(f"{root}/{names[1]}", skip_trash=False)
            assert len(c.ls(root)) == 1
            assert c.expunge() == 1
            assert c.ls(root) == []
