"""hflush/hsync: mid-write durability + reader visibility.

The reference API under test: DFSOutputStream.java:573 (hflush — pushed to
every pipeline DN, visible to new readers), :580 (hsync — additionally
fsync'd on each DN).  The HBase/WAL contract: bytes a writer flushed must be
readable by a concurrent reader while the file stays open, and hsync'd
bytes must survive a DataNode crash.
"""

import os

import pytest

from hdrf_tpu.testing.minicluster import MiniCluster


@pytest.fixture()
def cluster():
    with MiniCluster(n_datanodes=2, replication=2,
                     block_size=256 * 1024) as mc:
        yield mc


def test_hflush_visible_to_concurrent_reader(cluster):
    data1 = os.urandom(100_000)   # partial checksum chunk on purpose
    data2 = os.urandom(50_000)
    with cluster.client("writer") as w, cluster.client("reader") as r:
        out = w.open_for_write("/wal")
        out.write(data1)
        out.hflush()
        # a NEW reader sees every flushed byte while the file is open
        assert r.read("/wal") == data1
        out.write(data2)
        out.hflush()
        assert r.read("/wal") == data1 + data2
        # range read of the open file
        assert r.read("/wal", offset=90_000, length=20_000) == \
            (data1 + data2)[90_000:110_000]
        out.close()
        assert r.read("/wal") == data1 + data2
        assert r.stat("/wal")["complete"]


def test_hflush_without_close_reader_gets_flushed_bytes(cluster):
    """Writer dies (never closes): flushed bytes stay readable."""
    data = os.urandom(64_000)
    w = cluster.client("dying-writer")
    out = w.open_for_write("/wal2")
    out.write(data)
    out.hflush()
    w.close()                      # client gone, no close(), lease dangling
    with cluster.client("reader") as r:
        assert r.read("/wal2") == data


def test_hflush_across_block_boundary(cluster):
    """Flush after the stream has rolled to a second block: the finished
    block's length is persisted too, so a reader sees the whole prefix."""
    bs = 256 * 1024
    data = os.urandom(bs + 70_000)
    with cluster.client("w") as w, cluster.client("r") as r:
        out = w.open_for_write("/multi")
        out.write(data)
        out.hflush()
        assert r.read("/multi") == data
        out.close()
        assert r.read("/multi") == data


def test_hsync_survives_datanode_crash():
    """hsync -> kill the DN (abrupt) -> restart over the same dir: the
    synced prefix is promoted to a finalized replica and served."""
    with MiniCluster(n_datanodes=1, replication=1,
                     block_size=256 * 1024) as mc:
        data = os.urandom(90_000)
        w = mc.client("writer")
        out = w.open_for_write("/synced")
        out.write(data)
        out.hsync()
        w.close()
        mc.kill_datanode(0)
        mc.restart_datanode(0)
        mc.wait_for_datanodes(1)
        import time
        with mc.client("reader") as r:
            deadline = time.monotonic() + 15
            while True:   # the promoted replica's block report may lag
                try:
                    assert r.read("/synced") == data
                    break
                except (IOError, OSError):
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)


def test_unflushed_tail_not_visible(cluster):
    """Bytes written after the last flush are NOT served to readers."""
    a, b = os.urandom(40_000), os.urandom(40_000)
    with cluster.client("w") as w, cluster.client("r") as r:
        out = w.open_for_write("/partial")
        out.write(a)
        out.hflush()
        out.write(b)               # buffered, never flushed
        assert r.read("/partial") == a
        out.close()
        assert r.read("/partial") == a + b


def test_stream_plain_write_roundtrip(cluster):
    """The stream with no flush at all behaves like write()."""
    data = os.urandom(600_000)     # > 2 blocks of 256 KiB
    with cluster.client("w") as w, cluster.client("r") as r:
        with w.open_for_write("/plain") as out:
            for i in range(0, len(data), 100_000):
                out.write(data[i:i + 100_000])
        assert r.read("/plain") == data
        st = r.stat("/plain")
        assert st["length"] == len(data) and st["complete"]


def test_hsync_metrics_and_empty_flush(cluster):
    with cluster.client("w") as w:
        out = w.open_for_write("/empty")
        out.hflush()               # nothing buffered: a no-op, not an error
        out.write(b"x")
        out.hsync()
        out.close()
    with cluster.client("r") as r:
        assert r.read("/empty") == b"x"
