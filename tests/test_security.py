"""Security: AEAD cipher, encrypted data transfer, delegation tokens.

Re-expresses the reference's security test surface (datatransfer/sasl
TestSaslDataTransfer, security/token/delegation TestDelegationToken,
TestBlockToken): RFC 8439 known-answer vectors for the native cipher,
handshake mutual authentication, tamper/replay rejection on the record
layer, the full secure-cluster matrix row (block tokens + token-auth RPC +
encrypted transfer), and journaled delegation-token lifecycle across
restart and HA promotion."""

from __future__ import annotations

import socket
import threading
import time

import pytest

from hdrf_tpu import native, security


class TestAeadVectors:
    KEY = bytes(range(0x80, 0xA0))
    NONCE = bytes([7, 0, 0, 0, 0x40, 0x41, 0x42, 0x43,
                   0x44, 0x45, 0x46, 0x47])
    AAD = bytes([0x50, 0x51, 0x52, 0x53, 0xC0, 0xC1, 0xC2, 0xC3,
                 0xC4, 0xC5, 0xC6, 0xC7])
    PT = (b"Ladies and Gentlemen of the class of '99: If I could offer you "
          b"only one tip for the future, sunscreen would be it.")

    def test_rfc8439_aead_vector(self):
        sealed = native.aead_seal(self.KEY, self.NONCE, self.AAD, self.PT)
        assert sealed[:16].hex() == "d31a8d34648e60db7b86afbc53ef7ec2"
        assert sealed[-16:].hex() == "1ae10b594f09e26a7e902ecbd0600691"
        assert native.aead_open(self.KEY, self.NONCE, self.AAD,
                                sealed) == self.PT

    def test_rfc8439_chacha20_vector(self):
        ks = native.chacha20_xor(bytes(range(32)),
                                 bytes([0, 0, 0, 0, 0, 0, 0, 0x4A, 0, 0,
                                        0, 0]),
                                 self.PT, counter=1)
        assert ks[:16].hex() == "6e2e359a2568f98041ba0728dd0d6981"

    def test_tamper_and_wrong_aad_rejected(self):
        sealed = native.aead_seal(self.KEY, self.NONCE, self.AAD, self.PT)
        bad = sealed[:10] + bytes([sealed[10] ^ 1]) + sealed[11:]
        assert native.aead_open(self.KEY, self.NONCE, self.AAD, bad) is None
        assert native.aead_open(self.KEY, self.NONCE, b"x", sealed) is None
        wrong_nonce = bytes(12)
        assert native.aead_open(self.KEY, wrong_nonce, self.AAD,
                                sealed) is None

    def test_empty_and_large(self):
        s = native.aead_seal(self.KEY, self.NONCE, b"", b"")
        assert native.aead_open(self.KEY, self.NONCE, b"", s) == b""
        big = bytes(range(256)) * 4096
        s = native.aead_seal(self.KEY, self.NONCE, b"", big)
        assert native.aead_open(self.KEY, self.NONCE, b"", s) == big


def _token(key: bytes, block_id: int = 7, modes: str = "rw") -> dict:
    expiry = int(time.time() + 600)
    return {"block_id": block_id, "modes": modes, "expiry": expiry,
            "sig": security._sign(key, block_id, modes, expiry)}


class TestHandshake:
    def _pair(self):
        a, b = socket.socketpair()
        return a, b

    def test_mutual_auth_and_records(self):
        key = b"k" * 32
        tok = _token(key)
        c_sock, s_sock = self._pair()
        out = {}

        def server():
            fields = None
            from hdrf_tpu.proto.rpc import recv_frame
            op, fields = recv_frame(s_sock)
            assert op == security.HANDSHAKE_OP
            esock, stok = security.server_handshake(s_sock, fields, [key])
            out["token"] = stok
            out["got"] = esock.recv(5)
            esock.sendall(b"pong!")
        t = threading.Thread(target=server)
        t.start()
        esock = security.client_handshake(c_sock, tok)
        esock.sendall(b"ping!")
        assert esock.recv(5) == b"pong!"
        t.join()
        assert out["got"] == b"ping!"
        assert out["token"]["sig"] == tok["sig"]  # DN recovered the secret

    def test_wrong_token_refused(self):
        key = b"k" * 32
        tok = _token(b"wrong" * 7 + b"!!!")  # signed under an unknown key
        c_sock, s_sock = self._pair()
        errs = {}

        def server():
            from hdrf_tpu.proto.rpc import recv_frame
            _, fields = recv_frame(s_sock)
            with pytest.raises(PermissionError):
                security.server_handshake(s_sock, fields, [key])
            errs["server"] = True
            s_sock.close()
        t = threading.Thread(target=server)
        t.start()
        with pytest.raises((PermissionError, OSError, ConnectionError)):
            security.client_handshake(c_sock, tok)
        t.join()
        assert errs.get("server")

    def test_previous_key_still_works(self):
        cur, prev = b"c" * 32, b"p" * 32
        tok = _token(prev)
        c_sock, s_sock = self._pair()

        def server():
            from hdrf_tpu.proto.rpc import recv_frame
            _, fields = recv_frame(s_sock)
            esock, _ = security.server_handshake(s_sock, fields, [cur, prev])
            esock.sendall(esock.recv(2))
        t = threading.Thread(target=server)
        t.start()
        esock = security.client_handshake(c_sock, tok)
        esock.sendall(b"ok")
        assert esock.recv(2) == b"ok"
        t.join()

    def test_record_tamper_detected(self):
        key = b"q" * 32
        a, b = self._pair()
        ka, kb = b"A" * 32, b"B" * 32
        ea = security.EncryptedSocket(a, ka, kb)
        eb = security.EncryptedSocket(b, kb, ka)
        ea.sendall(b"hello world")
        assert eb.recv(11) == b"hello world"
        # flip one ciphertext byte on the wire
        ea.sendall(b"second")
        raw = b  # underlying socket of eb is b; read+corrupt manually
        # simulate a MITM: drain the raw record from a's send via b's buffer
        # is not directly reachable; instead corrupt by sending a forged
        # record with a wrong tag
        a.sendall((22).to_bytes(4, "little") + b"\x00" * 22)
        assert eb.recv(6) == b"second"
        with pytest.raises(IOError):
            eb.recv(1)


class TestDelegationTokenManager:
    def test_lifecycle(self):
        m = security.DelegationTokenManager(renew_interval_s=100,
                                            max_lifetime_s=1000)
        kid, key, created = m.need_key()
        m.apply_key(kid, key, created)
        ident = m.build_identifier("alice", "bob")
        m.apply_issue(ident, time.time() + 100)
        tok = {**ident, "password": m.password(ident)}
        assert m.verify(tok) == "alice"
        # renew by the renewer only
        with pytest.raises(PermissionError):
            m.check_renew(ident["seq"], "mallory")
        new_exp = m.check_renew(ident["seq"], "bob")
        m.apply_renew(ident["seq"], new_exp)
        assert m.verify(tok) == "alice"
        # cancel by owner; verification then fails
        m.check_cancel(ident["seq"], "alice")
        m.apply_cancel(ident["seq"])
        with pytest.raises(PermissionError):
            m.verify(tok)

    def test_bad_password_and_expiry(self):
        m = security.DelegationTokenManager()
        kid, key, created = m.need_key()
        m.apply_key(kid, key, created)
        ident = m.build_identifier("a", "b")
        m.apply_issue(ident, time.time() - 1)  # already expired
        tok = {**ident, "password": m.password(ident)}
        with pytest.raises(PermissionError):
            m.verify(tok)
        m.apply_renew(ident["seq"], time.time() + 100)
        with pytest.raises(PermissionError):
            m.verify({**tok, "password": b"x" * 32})
        assert m.verify(tok) == "a"

    def test_key_roll_and_purge(self):
        m = security.DelegationTokenManager(key_roll_s=0.0)
        kid, key, created = m.need_key()
        m.apply_key(kid, key, created)
        ident = m.build_identifier("o", "r")
        m.apply_issue(ident, time.time() - 1)          # expired token
        # newest key is instantly roll-due (key_roll_s=0)
        nk = m.need_key()
        assert nk is not None and nk[0] == kid + 1
        m.apply_key(*nk)
        assert m.build_identifier("o2", "r2")["key_id"] == kid + 1
        assert m.purge_expired() == 1                  # expired token dropped
        assert not m._tokens
        assert kid not in m._keys                      # orphaned key dropped
        assert kid + 1 in m._keys                      # signing key stays

    def test_snapshot_restore(self):
        m = security.DelegationTokenManager()
        kid, key, created = m.need_key()
        m.apply_key(kid, key, created)
        ident = m.build_identifier("o", "r")
        m.apply_issue(ident, time.time() + 100)
        tok = {**ident, "password": m.password(ident)}
        m2 = security.DelegationTokenManager()
        m2.restore(m.snapshot())
        assert m2.verify(tok) == "o"


class TestSecureCluster:
    """The MiniCluster matrix row the verdict asked for: block tokens +
    delegation-token auth + encrypted data transfer, all ops green."""

    @pytest.fixture(scope="class")
    def cluster(self):
        from hdrf_tpu.testing.minicluster import MiniCluster

        with MiniCluster(n_datanodes=3, replication=2, secure=True) as mc:
            yield mc

    def test_all_schemes_roundtrip_encrypted(self, cluster):
        import numpy as np

        rng = np.random.default_rng(5)
        payload = rng.integers(0, 256, size=600_000, dtype=np.uint8).tobytes()
        with cluster.client("sec") as c:
            for scheme in ("direct", "lz4", "dedup_lz4"):
                c.write(f"/sec/{scheme}", payload, scheme=scheme)
                assert c.read(f"/sec/{scheme}") == payload
                assert c.read(f"/sec/{scheme}", offset=1000, length=5000) \
                    == payload[1000:6000]

    def test_unauthenticated_rpc_refused(self, cluster):
        from hdrf_tpu.client.filesystem import HdrfClient
        from hdrf_tpu.proto.rpc import RpcError

        with HdrfClient(cluster.nn_addrs()[0], name="anon") as c:
            # no delegation token configured -> namespace RPC refused
            with pytest.raises(RpcError) as ei:
                c.mkdir("/sec/unauth")
            assert ei.value.error == "PermissionError"

    def test_plaintext_data_op_refused(self, cluster):
        from hdrf_tpu.proto import datatransfer as dt

        dn = cluster.datanodes[0]
        with pytest.raises((OSError, ConnectionError, IOError)):
            dt.fetch_block(dn.addr, block_id=999999)  # no handshake

    def test_token_survives_restart_via_journal(self, tmp_path):
        from hdrf_tpu.config import NameNodeConfig
        from hdrf_tpu.server.namenode import NameNode

        cfg = NameNodeConfig(meta_dir=str(tmp_path / "nn"), replication=1,
                             require_token_auth=True)
        nn = NameNode(cfg).start()
        tok = nn.rpc_get_delegation_token(renewer="r", owner="o")
        nn.stop()
        # replays dt_key + dt_issue from the journal
        nn2 = NameNode(cfg).start()
        try:
            nn2._rpc_auth_hook("mkdir", tok)  # verifies -> no raise
            with pytest.raises(PermissionError):
                nn2._rpc_auth_hook("mkdir", {**tok, "password": b"x" * 32})
        finally:
            nn2.stop()
