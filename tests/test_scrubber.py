"""Continuous integrity-scrub & cluster invariant-audit plane.

Covers the DN-side scrubber (server/scrubber.py: sampled chunk-digest
re-verification of sealed containers, stripe CRC + any-k decode
spot-checks, replica invariants, the four-class garbage census and its
tmp/segment reclaim — the VolumeScanner.java:47 / DirectoryScanner.java:56
re-expression over reduced storage), the detection->response wiring
(quarantine-via-rename, rpc_bad_block / rpc_bad_stripe fan-in to the NN
monitors, server/namenode.py:3139-3174), and the NN invariant census
(``rpc_fsck``, server/namenode.py:3003 — the NamenodeFsck.java:112 analog)
surfaced through ``dfsadmin -fsck``, the gateway's /fsck and the /health
degraded verdict (server/http_gateway.py:454).

Fault points exercised: "scrub.container", "scrub.stripe",
"scrub.replica", "scrub.census".
"""

import io
import json
import os
import random
import time
import urllib.request
from contextlib import redirect_stdout

import pytest

from hdrf_tpu.server.http_gateway import HttpGateway
from hdrf_tpu.server.scrubber import QUAR_SUFFIX, Scrubber
from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.tools import cli
from hdrf_tpu.utils import fault_injection, metrics, retry

_S = metrics.registry("scrub")
_EC = metrics.registry("ec")
_NN = metrics.registry("namenode")


@pytest.fixture(autouse=True)
def _clear_faults():
    fault_injection.clear()
    yield
    fault_injection.clear()


def run_cli(argv) -> tuple[int, str]:
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(argv)
    return rc, buf.getvalue()


def blob(seed: int, n: int) -> bytes:
    return random.Random(seed).randbytes(n)


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10) as r:
        assert r.status == 200
        return r.read()


def _wait(pred, timeout=20.0, interval=0.1, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _cycle(dn, timeout: float = 12.0) -> dict:
    """Run one scrub cycle NOW, riding out transient breaker vetoes left
    behind by earlier cluster churn (breakers half-open within reset_s)."""
    deadline = time.monotonic() + timeout
    while True:
        before = _S.counter("scrub_cycles")
        census = dn.scrubber.run_cycle()
        if _S.counter("scrub_cycles") > before:
            return census
        if time.monotonic() > deadline:
            raise AssertionError("scrub cycle stayed vetoed")
        time.sleep(0.25)


def _seal_all(dn) -> None:
    dn.containers.flush_open(on_seal=dn.index.seal_container)
    dn.containers.drain_seals()


def _holder(mc):
    for dn in mc.datanodes:
        if dn is not None and dn.replicas.block_ids():
            return dn
    raise AssertionError("no datanode holds a replica")


# ---------------------------------------------------- clean-cluster baseline


class TestCleanCluster:
    def test_no_false_positives_and_fsck_healthy(self):
        """Acceptance gate: a healthy MiniCluster scrubs to ZERO corruption
        across every class, the census finds no dead/orphan/tmp garbage,
        and the invariant audit reports healthy."""
        with MiniCluster(n_datanodes=3, replication=2) as mc:
            payloads = {}
            with mc.client("clean") as c:
                for i, scheme in enumerate(("direct", "dedup", "dedup_lz4")):
                    d = blob(40 + i, 96_000)
                    c.write(f"/clean/{i}", d, scheme=scheme)
                    payloads[i] = d
            corrupt0 = Scrubber.corrupt_total()
            for dn in mc.datanodes:
                _seal_all(dn)
                _cycle(dn)
                # second cycle: foreign-stripe baselines and rotating
                # cursors armed — still quiet
                census = _cycle(dn)
                assert census["dead_chunks"] == 0
                assert census["orphan_append"] == 0
                assert census["tmp"] == 0
                assert census["quarantined"] == 0
            assert Scrubber.corrupt_total() == corrupt0
            with mc.client("clean") as c:
                fs = c._call("fsck")
                assert fs["healthy"] and fs["violations"] == 0
                assert all(n == 0 for n in fs["counts"].values())
                assert fs["blocks_checked"] >= 3
                for i, d in payloads.items():
                    assert c.read(f"/clean/{i}") == d

    def test_fault_points_fire_and_report_shape(self):
        """The scrubber's crash windows fire on every cycle leg; report()
        carries the heartbeat census the NN aggregates."""
        with MiniCluster(n_datanodes=1, replication=1) as mc:
            with mc.client("fp") as c:
                c.write("/fp/a", blob(9, 48_000), scheme="dedup")
            dn = mc.datanodes[0]
            _seal_all(dn)
            seen = {"container": [], "replica": [], "census": []}
            fault_injection.install(
                "scrub.container", lambda **kw: seen["container"].append(kw))
            fault_injection.install(
                "scrub.replica", lambda **kw: seen["replica"].append(kw))
            fault_injection.install(
                "scrub.census", lambda **kw: seen["census"].append(kw))
            _cycle(dn)
            assert seen["container"] and seen["replica"] and seen["census"]
            rep = dn.scrubber.report()
            assert rep["cycles"] >= 1
            assert rep["bytes_verified"] > 0
            assert set(rep["garbage"]) == {"dead_chunks", "orphan_append",
                                           "tmp", "mirror_segments",
                                           "quarantined"}

    def test_breaker_veto_skips_cycle(self):
        """An open breaker edge vetoes the whole cycle (never add scrub
        load to a sick node) and counts scrub_cycles_vetoed."""
        with MiniCluster(n_datanodes=1, replication=1) as mc:
            dn = mc.datanodes[0]
            _cycle(dn)  # prove a cycle runs before the veto
            b = retry.breaker("scrub-test-edge", failure_threshold=1,
                              reset_s=60.0)
            try:
                b.record_failure()
                assert b.state == "open"
                v0 = _S.counter("scrub_cycles_vetoed")
                c0 = _S.counter("scrub_cycles")
                dn.scrubber.run_cycle()
                assert _S.counter("scrub_cycles_vetoed") == v0 + 1
                assert _S.counter("scrub_cycles") == c0
            finally:
                retry.reset_breakers()


# ------------------------------------------------- container corruption e2e


class TestContainerScrub:
    def test_flipped_byte_quarantines_and_rereplicates(self):
        """Acceptance path: one flipped byte in a sealed container is
        detected within one cycle, the container is quarantined (never
        served again), the NN re-replicates from the healthy peer, and the
        repaired read is bit-identical to the original corpus."""
        with MiniCluster(n_datanodes=3, replication=2,
                         dn_config_overrides={"scrub_sample_frac": 1.0}) \
                as mc:
            d = blob(1, 120_000)
            with mc.client("it") as c:
                c.write("/scrub/a", d, scheme="dedup")
                assert c.read("/scrub/a") == d
            victim = _holder(mc)
            _seal_all(victim)
            cids = sorted(victim.index.container_live_bytes())
            assert cids
            cid = cids[0]
            vol = victim.volumes.volume_of_cid(cid)
            path = vol.containers._sealed_path(cid)
            raw = bytearray(open(path, "rb").read())
            raw[max(16, len(raw) // 2)] ^= 0xFF
            with open(path, "wb") as f:
                f.write(raw)
            # drop the decoded-container LRU so the scrub read hits disk
            with vol.containers._cache_lock:
                vol.containers._cache.clear()

            c0 = _S.counter("scrub_corrupt|class=container")
            r0 = _S.counter("scrub_repairs_triggered")
            _cycle(victim)
            assert _S.counter("scrub_corrupt|class=container") > c0
            assert _S.counter("scrub_repairs_triggered") > r0
            # quarantined: renamed aside, out of the store's accounting
            assert os.path.exists(path + QUAR_SUFFIX)
            assert cid not in victim.containers.container_ids()
            # the bad location was dropped and re-replicated from the
            # healthy peer; the repaired read is bit-identical
            mc.wait_for_replication("/scrub/a", 2)
            with mc.client("it") as c:
                assert c.read("/scrub/a") == d

            # surfacing: /prom family, /health verdict, cluster census
            gw = HttpGateway(mc.namenode.addr).start()
            try:
                base = f"http://{gw.addr[0]}:{gw.addr[1]}"
                prom = _get(base + "/prom").decode()
                assert any(
                    line.startswith("hdrf_scrub_corrupt_total{")
                    and 'class="container"' in line
                    for line in prom.splitlines())
                assert 'registry="scrub"' in prom
                _wait(lambda: json.loads(_get(base + "/health"))
                      ["scrub_corrupt_total"] > 0,
                      msg="heartbeat scrub census aggregation")
                health = json.loads(_get(base + "/health"))
                assert health["status"] == "degraded"
                assert health["scrub_repairs_triggered"] > 0
            finally:
                gw.stop()

    def test_dangling_reduced_replica_detected(self):
        """A reduced replica is 0 stored bytes backed by index entries; a
        lost entry makes it unreconstructable — scrub flags it, bad_block
        drops the location, re-replication restores the data."""
        with MiniCluster(n_datanodes=3, replication=2) as mc:
            d = blob(2, 64_000)
            with mc.client("it") as c:
                c.write("/scrub/r", d, scheme="dedup")
            victim = _holder(mc)
            bid = victim.replicas.block_ids()[0]
            # simulate index loss without the replica file going with it
            victim.index.delete_block(bid)
            c0 = _S.counter("scrub_corrupt|class=replica")
            _cycle(victim)
            assert _S.counter("scrub_corrupt|class=replica") > c0
            mc.wait_for_replication("/scrub/r", 2)
            with mc.client("it") as c:
                assert c.read("/scrub/r") == d

    def test_direct_replica_bitrot_deep_verify(self):
        """The rotating deep verify catches bit-rot in a direct replica's
        stored bytes against its finalize-time CRCs."""
        with MiniCluster(n_datanodes=3, replication=2) as mc:
            d = blob(5, 64_000)
            with mc.client("it") as c:
                c.write("/scrub/d", d, scheme="direct")
            victim = _holder(mc)
            bid = victim.replicas.block_ids()[0]
            path = victim.replicas.data_path(bid)
            raw = bytearray(open(path, "rb").read())
            raw[len(raw) // 3] ^= 0x40
            with open(path, "wb") as f:
                f.write(raw)
            c0 = _S.counter("scrub_corrupt|class=replica")
            _cycle(victim)  # one replica held -> the cursor lands on it
            assert _S.counter("scrub_corrupt|class=replica") > c0
            mc.wait_for_replication("/scrub/d", 2)
            with mc.client("it") as c:
                assert c.read("/scrub/d") == d


# ------------------------------------------------------------ garbage census


class TestGarbageCensus:
    def test_dead_chunk_census_exact_after_delete(self):
        """Zero-refcount accounting is EXACT: deleting one of two
        non-overlapping dedup blocks leaves precisely its chunk bytes as
        dead payload (container payload minus live index bytes)."""
        with MiniCluster(n_datanodes=1, replication=1) as mc:
            a, b = blob(7, 50_000), blob(8, 30_000)
            dn = mc.datanodes[0]
            with mc.client("g") as c:
                c.write("/g/a", a, scheme="dedup")
                bids_a = set(dn.index.block_ids())
                c.write("/g/b", b, scheme="dedup")
                bid_b = (set(dn.index.block_ids()) - bids_a).pop()
                census = _cycle(dn)
                assert census["dead_chunks"] == 0
                c.delete("/g/b")
                _wait(lambda: dn.index.get_block(bid_b) is None,
                      msg="delete propagation to the chunk index")
                census = _cycle(dn)
                assert census["dead_chunks"] == len(b)
                assert census["orphan_append"] == 0
                assert c.read("/g/a") == a

    def test_orphan_loser_bytes_census(self):
        """A dedup-race loser (commit_block returns the fingerprint, its
        appended bytes stay orphaned in the container) is attributed per
        container and censused as orphan_append, not dead_chunks."""
        with MiniCluster(n_datanodes=1, replication=1) as mc:
            dn = mc.datanodes[0]
            with mc.client("g") as c:
                c.write("/g/o", blob(11, 40_000), scheme="dedup")
            bid = dn.index.block_ids()[0]
            h = dn.index.get_block(bid).hashes[0]
            loc = dn.index.chunk_location(h)
            cid = loc.container_id
            # the losing racer appended its copy of the chunk before the
            # index commit decided the race
            n = 4096
            end = Scrubber._payload_size(
                dn.volumes.volume_of_cid(cid).containers, cid)
            with open(dn.volumes.volume_of_cid(cid).containers
                      ._raw_path(cid), "ab") as f:
                f.write(b"\x5c" * n)
            losers = dn.index.commit_block(9_999_999, n, [h],
                                           {h: (cid, end, n)})
            assert losers == [h]
            assert dn.index.orphan_bytes().get(cid) == n
            census = _cycle(dn)
            assert census["orphan_append"] == n
            assert census["dead_chunks"] == 0

    def test_tmp_reclaim_survives_restart(self):
        """Satellite 1: tmp+fsync+replace residue from a crashed seal /
        stripe put / segment put is reclaimed once aged — including
        orphans found after a DN restart (the crash shape) — while young
        tmp files are left for their writers and censused."""
        with MiniCluster(n_datanodes=1, replication=1,
                         dn_config_overrides={"scrub_tmp_age_s": 30.0}) \
                as mc:
            dn = mc.datanodes[0]
            with mc.client("t") as c:
                c.write("/t/a", blob(13, 20_000), scheme="dedup")
            old = time.time() - 3600
            aged = [
                os.path.join(dn.volumes.volumes[0].containers._dir,
                             "999.sealed.tmp"),
                os.path.join(dn.ec.store._dir, "dn-0.999.0.stripe.tmp"),
                os.path.join(dn.mirror._store._root, "999.0.seg.tmp"),
            ]
            for p in aged:
                with open(p, "wb") as f:
                    f.write(b"\x00" * 2048)
                os.utime(p, (old, old))
            young = os.path.join(dn.volumes.volumes[0].containers._dir,
                                 "998.sealed.tmp")
            with open(young, "wb") as f:
                f.write(b"\x00" * 512)
            # the crash: the writer died before the os.replace barrier
            mc.stop_datanode(0)
            dn = mc.restart_datanode(0)
            mc.wait_for_datanodes(1)
            r0 = _S.counter("scrub_tmp_reclaimed")
            b0 = _S.counter("scrub_tmp_reclaimed_bytes")
            census = _cycle(dn)
            assert _S.counter("scrub_tmp_reclaimed") == r0 + 3
            assert _S.counter("scrub_tmp_reclaimed_bytes") == b0 + 3 * 2048
            assert not any(os.path.exists(p) for p in aged)
            assert os.path.exists(young)
            assert census["tmp"] == 512

    def test_mirror_segment_reclaim_and_census(self):
        """Satellite 2: segments shadowed by a full local replica are
        dropped by the census; segments with no replica behind them are
        censused as garbage until their upgrade lands."""
        with MiniCluster(n_datanodes=1, replication=1) as mc:
            dn = mc.datanodes[0]
            with mc.client("m") as c:
                c.write("/m/a", blob(17, 24_000), scheme="direct")
            bid = dn.replicas.block_ids()[0]
            dn.mirror._store.put(bid, 0, {"v": 1}, b"z" * 2048)
            orphan_bid = 424_242
            dn.mirror._store.put(orphan_bid, 0, {"v": 1}, b"z" * 2048)
            orphan_path = os.path.join(dn.mirror._store._root,
                                       f"{orphan_bid}.0.seg")
            rec0 = metrics.registry("mirror").counter("reconciliations")
            census = _cycle(dn)
            # shadowed segment reconciled away; the orphan one censused
            assert bid not in dn.mirror._store.blocks()
            assert metrics.registry("mirror").counter(
                "reconciliations") > rec0
            assert census["mirror_segments"] == os.path.getsize(orphan_path)


# ----------------------------------------------------------- EC stripe scrub


@pytest.fixture
def ec_cluster():
    with MiniCluster(n_datanodes=5, block_size=256 * 1024,
                     container_size=32 * 1024) as mc:
        mc.namenode.config.ec_data_shards = 3
        mc.namenode.config.ec_parity_shards = 2
        mc.namenode.config.ec_demote_after_s = 0.0
        yield mc


def _owner_dn(mc):
    for dn in mc.datanodes:
        if dn is not None and dn.index.stripe_manifests():
            return dn
    return None


def _demote(mc, c, path: str, data: bytes):
    c.write(path, data, scheme="dedup_lz4")
    mc.namenode.config.ec_demote_after_s = 0.3
    time.sleep(0.3)
    _wait(lambda: c._call("ec_status")["demoted_blocks"] >= 1,
          msg="block demotion")
    _wait(lambda: c._call("ec_status")["striped_containers"] >= 1,
          msg="striped-container census")


class TestStripeScrub:
    def test_owner_local_stripe_repair(self, ec_cluster):
        """A CRC-failing stripe on the manifest OWNER is quarantined and
        repaired locally (any-k re-decode with ourselves as the target) —
        no NN round trip, data stays bit-identical."""
        mc = ec_cluster
        data = blob(21, 200_000)
        with mc.client("ec") as c:
            _demote(mc, c, "/cold/own", data)
            owner = _owner_dn(mc)
            assert owner is not None
            stripe_seen = []
            fault_injection.install(
                "scrub.stripe", lambda **kw: stripe_seen.append(kw))
            own = [s for s in owner.ec.store.iter_stripes()
                   if s[0] == owner.dn_id]
            assert own
            _, cid, idx, _nb = own[0]
            path = os.path.join(owner.ec.store._dir,
                                f"{owner.dn_id}.{cid}.{idx}.stripe")
            raw = bytearray(open(path, "rb").read())
            raw[len(raw) // 2] ^= 0xFF
            with open(path, "wb") as f:
                f.write(raw)
            c0 = _S.counter("scrub_corrupt|class=stripe")
            r0 = _S.counter("scrub_repairs_triggered")
            d0 = _S.counter("scrub_decode_checks")
            _cycle(owner)
            assert stripe_seen
            assert _S.counter("scrub_corrupt|class=stripe") > c0
            assert _S.counter("scrub_repairs_triggered") > r0
            assert _S.counter("scrub_decode_checks") > d0
            assert os.path.exists(path + QUAR_SUFFIX)
            # local repair re-decoded and rewrote the stripe in place
            _wait(lambda: os.path.exists(path), msg="local stripe repair")
            assert c.read("/cold/own") == data

    def test_foreign_stripe_reports_bad_stripe_and_monitor_repairs(
            self, ec_cluster):
        """A corrupt stripe on a NON-owner (no local manifest): first scrub
        records the CRC baseline, the second detects the flip, reports
        ``bad_stripe`` to the NN, and the stripe-repair monitor schedules
        the owner's re-decode."""
        mc = ec_cluster
        data = blob(23, 200_000)
        with mc.client("ec") as c:
            _demote(mc, c, "/cold/foreign", data)
            owner = _owner_dn(mc)
            cid, man = next(iter(owner.index.stripe_manifests().items()))
            fidx, f_dnid = next(
                (i, h[0]) for i, h in enumerate(man["holders"])
                if h[0] != owner.dn_id)
            fdn = mc.datanodes[int(f_dnid.split("-")[1])]
            _cycle(fdn)  # baseline CRC for the foreign stripe
            path = os.path.join(fdn.ec.store._dir,
                                f"{owner.dn_id}.{cid}.{fidx}.stripe")
            raw = bytearray(open(path, "rb").read())
            raw[len(raw) // 2] ^= 0xFF
            with open(path, "wb") as f:
                f.write(raw)
            c0 = _S.counter("scrub_corrupt|class=stripe")
            n0 = _NN.counter("corrupt_stripes_reported")
            rep0 = _EC.counter("stripes_repaired")
            _cycle(fdn)
            assert _S.counter("scrub_corrupt|class=stripe") > c0
            assert _NN.counter("corrupt_stripes_reported") > n0
            assert os.path.exists(path + QUAR_SUFFIX)
            _wait(lambda: _EC.counter("stripes_repaired") > rep0,
                  timeout=25.0, msg="monitor-scheduled stripe repair")
            assert c.read("/cold/foreign") == data


# ------------------------------------------------------- NN invariant audit


class TestFsck:
    def test_missing_extra_surfaced_on_every_plane(self):
        """The invariant census classes surface identically through
        rpc_fsck, ``dfsadmin -fsck``, the gateway's /fsck and the /health
        degraded verdict."""
        with MiniCluster(n_datanodes=2, replication=1) as mc:
            d = blob(3, 60_000)
            with mc.client("f") as c:
                c.write("/f/a", d, scheme="direct")
                holder = _holder(mc)
                hidx = int(holder.dn_id.split("-")[1])

                # extra: a DN claims a block the map never had
                nn = mc.namenode
                live_dn = next(iter(nn._datanodes))
                nn._datanodes[live_dn].blocks.add(987_654_321)
                fs = nn.rpc_fsck()
                assert fs["counts"]["extra"] >= 1 and not fs["healthy"]
                nn._datanodes[live_dn].blocks.discard(987_654_321)
                fs = nn.rpc_fsck()
                assert fs["counts"]["extra"] == 0

                # missing: kill the only holder; no byte source remains
                mc.kill_datanode(hidx)
                _wait(lambda: c._call("fsck")["counts"]["missing"] >= 1,
                      timeout=10.0, msg="missing-block detection")
                fs = c._call("fsck")
                assert not fs["healthy"] and fs["violations"] >= 1

                # monitor pass exports the gauges
                _wait(lambda: _NN.snapshot()["gauges"]
                      .get("fsck_violations", 0) >= 1,
                      msg="fsck monitor gauge")
                assert _NN.snapshot()["gauges"].get("fsck_missing", 0) >= 1

            nn_addr = f"{mc.namenode.addr[0]}:{mc.namenode.addr[1]}"
            rc, out = run_cli(["dfsadmin", "--namenode", nn_addr, "-fsck"])
            assert rc == 0
            doc = json.loads(out)
            assert doc["counts"]["missing"] >= 1

            gw = HttpGateway(mc.namenode.addr).start()
            try:
                base = f"http://{gw.addr[0]}:{gw.addr[1]}"
                gfs = json.loads(_get(base + "/fsck"))
                assert gfs["counts"]["missing"] >= 1
                health = json.loads(_get(base + "/health"))
                assert health["status"] == "degraded"
                assert health["fsck_violations"] >= 1
            finally:
                gw.stop()
