"""Control-plane contention observatory, end to end.

Exercises ISSUE 18's wiring above the unit-level lockprof math
(tests/test_lockprof.py): a two-client metadata storm against a real
MiniCluster with a slow lock holder injected at the ``editlog.append``
fault point (which fires UNDER the namesystem lock,
server/editlog.py:145) must show up on ``/contention`` — via the HTTP
gateway — as mkdir owning the lock, with >= 95% of profiled RPC service
time attributed to named phases.  Also pins the ``rpc_max_handlers``
accept-backpressure knob, the watchdog's lock-holder convoy capture
(utils/watchdog.py), and the ``rpc.dispatch`` fault point
(proto/rpc.py) the contention plane declares.
"""

import json
import threading
import time
import urllib.request

import pytest

from hdrf_tpu.proto.rpc import RpcClient, RpcError, RpcServer
from hdrf_tpu.server.http_gateway import HttpGateway
from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.utils import fault_injection, lockprof, metrics
from hdrf_tpu.utils.watchdog import StallWatchdog


def _get_json(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        assert r.status == 200
        return json.loads(r.read())


class TestContentionE2E:
    def test_storm_attributes_slow_holder(self):
        """Two wire clients mkdir-storm the NN while every edit append
        sleeps 20 ms under the namesystem lock; /contention (through the
        gateway) must name mkdir as the dominant lock holder and keep the
        service-time decomposition >= 95% attributed."""
        per_client, n_clients = 12, 2
        with MiniCluster(n_datanodes=1, replication=1) as mc:
            gw = HttpGateway(mc.namenode.addr).start()
            try:
                # The rpc.namenode registry is cumulative per PROCESS
                # (Prometheus counter semantics), so under a full pytest
                # run it already holds earlier clusters' traffic — assert
                # method-table deltas, not absolutes.  The lock books and
                # attributed_frac are per-NN-instance and need no delta.
                cont0 = _get_json(
                    f"http://{gw.addr[0]}:{gw.addr[1]}/contention")
                mk0 = cont0["methods"].get("mkdir", {})
                errs = []

                def storm(w):
                    try:
                        with RpcClient(mc.namenode.addr) as c:
                            for i in range(per_client):
                                c.call("mkdir", path=f"/storm{w}/d{i}")
                                c.call("stat", path=f"/storm{w}/d{i}")
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)

                with fault_injection.inject(
                        "editlog.append", lambda **kw: time.sleep(0.02)):
                    ts = [threading.Thread(target=storm, args=(w,))
                          for w in range(n_clients)]
                    for t in ts:
                        t.start()
                    for t in ts:
                        t.join()
                assert not errs
                cont = _get_json(
                    f"http://{gw.addr[0]}:{gw.addr[1]}/contention")

                # Per-method service table saw every storm call.
                mk = cont["methods"]["mkdir"]
                assert mk["calls"] - mk0.get("calls", 0) == \
                    per_client * n_clients
                assert mk["errors"] - mk0.get("errors", 0) == 0
                # The decomposition carved a locked phase out of mkdir.
                assert mk["phase_us"]["locked"] > 0
                # Lock books: mkdir owns the hold time (stat takes the
                # lock too, but without the editlog sleep).
                by = cont["lock"]["by_method"]
                assert by["mkdir"]["hold_share"] == max(
                    r["hold_share"] for r in by.values())
                assert by["mkdir"]["hold_s"] >= \
                    0.02 * per_client * n_clients
                # The method row is stamped with its lock share.
                assert mk["lock_share"] == pytest.approx(
                    by["mkdir"]["hold_share"])
                # Acceptance bar: the exclusive phase partition accounts
                # for >= 95% of profiled RPC service time.
                assert cont["attributed_frac"] >= 0.95
                assert 0.0 <= cont["lock"]["saturation"] <= 1.0

                # Flight sample carries the lock axis for slo_report's
                # REGRESS_UP comparison.
                sample = mc.namenode._flight_sample()
                assert 0.0 <= sample["nn_lock_saturation"] <= 1.0
                assert sample["nn_lock_wait_p99_us"] >= 0.0
                assert any(k.startswith("nn_lock_hold_p99_us|method=")
                           for k in sample)
            finally:
                gw.stop()


class _AddService:
    def rpc_add(self, a, b):
        return a + b


class TestMaxHandlers:
    def test_accept_backpressure(self):
        """With ``max_handlers=1`` the second connection parks in the
        accept path until the first client releases its handler thread by
        disconnecting — listen-backlog backpressure, not an error."""
        srv = RpcServer("127.0.0.1", 0, _AddService(), "ctest",
                        max_handlers=1).start()
        try:
            c1 = RpcClient(srv.addr)
            assert c1.call("add", a=1, b=2) == 3  # c1 now owns the slot
            done = threading.Event()
            res = []

            def second():
                with RpcClient(srv.addr) as c2:
                    res.append(c2.call("add", a=3, b=4))
                done.set()

            t = threading.Thread(target=second, daemon=True)
            t.start()
            # The second call must be parked while c1 holds its
            # connection (one handler thread per connection).
            assert not done.wait(0.3)
            c1.close()
            assert done.wait(10), "second client never got a handler slot"
            assert res == [7]
            t.join()
            snap = metrics.registry("rpc.ctest").snapshot()["gauges"]
            assert "rpc_handler_threads" in snap
            assert "rpc_inflight" in snap
        finally:
            srv.stop()


class TestWatchdogLockHolder:
    def test_stall_record_names_the_holder(self):
        """A stall scan while the instrumented lock is held must capture
        the holder's method, held-for and live stack on the record — the
        convoy culprit, not just N identical waiter stacks."""
        lk = lockprof.InstrumentedRLock("cv_lock")
        wd = StallWatchdog("cv", budget_s=1.0, tick_s=999, lock=lk)
        held, release = threading.Event(), threading.Event()

        def slow_holder():
            with lockprof.bind_request("slow_write"):
                with lk:
                    held.set()
                    release.wait(10)

        t = threading.Thread(target=slow_holder, daemon=True)
        t.start()
        assert held.wait(5)
        try:
            with wd.track("stuck_op"):
                t0 = time.monotonic()
                assert wd.scan(now=t0 + 2) == 1
        finally:
            release.set()
            t.join()
        rec = wd.stalls()[-1]
        h = rec["lock_holder"]
        assert h["method"] == "slow_write"
        assert h["held_for_s"] >= 0.0
        assert any("slow_holder" in line for line in h["stack"])

    def test_no_holder_no_key(self):
        lk = lockprof.InstrumentedRLock("cv_lock2")
        wd = StallWatchdog("cv2", budget_s=1.0, tick_s=999, lock=lk)
        with wd.track("stuck_op"):
            t0 = time.monotonic()
            assert wd.scan(now=t0 + 2) == 1
        assert "lock_holder" not in wd.stalls()[-1]


class TestDispatchFaultPoint:
    def test_rpc_dispatch_injection_surfaces_as_rpc_error(self):
        """``rpc.dispatch`` fires per-dispatch with the server name and
        method, before the handler runs — an injected raise travels back
        to the client as a normal RpcError."""
        srv = RpcServer("127.0.0.1", 0, _AddService(), "ctest2").start()
        seen = []

        def boom(**kw):
            seen.append(kw)
            if kw["method"] == "add":
                raise ValueError("injected dispatch fault")

        try:
            with fault_injection.inject("rpc.dispatch", boom):
                with RpcClient(srv.addr) as c:
                    with pytest.raises(RpcError) as ei:
                        c.call("add", a=1, b=2)
            assert ei.value.error == "ValueError"
            assert seen and seen[0]["server"] == "ctest2"
            assert seen[0]["method"] == "add"
        finally:
            srv.stop()
