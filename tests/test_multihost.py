"""Multi-host launcher (parallel/launch.py): jax.distributed over N real
OS processes on a CPU mesh, running the REAL variable-chunk sharded
pipeline and asserting oracle bit-identity on every rank — the deployable
form of SURVEY §2.4's multi-chip reduction (the reference's MPI/NCCL
process-group bring-up, re-expressed).

Spawning JAX twice makes this the suite's slowest file; the subprocess
environment mirrors conftest's clean-CPU relaunch recipe."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cpu_mesh_oracle_identity(tmp_path):
    from hdrf_tpu.utils.cleanenv import clean_cpu_env

    port = _free_port()
    env = clean_cpu_env(2)   # the canonical clean-CPU child recipe
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "hdrf_tpu.parallel.launch",
             "--coordinator", f"127.0.0.1:{port}",
             "--nprocs", "2", "--rank", str(rank),
             "--selftest", "1"],
            env=env, cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for rank in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=280)
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert "oracle_match=True" in out, f"rank {rank}:\n{out}"
        assert "devices=4" in out, f"rank {rank} saw wrong mesh:\n{out}"
