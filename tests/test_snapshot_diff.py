"""Snapshot diff (SnapshotDiffInfo.java:44, SnapshotManager
.getSnapshotDiffReport): created/deleted/modified/renamed deltas between two
snapshots of a snapshottable root — renames tracked by inode id, the feature
that makes snapshots usable for incremental backup/distcp."""

import pytest

from hdrf_tpu.testing.minicluster import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_datanodes=1, replication=1) as mc:
        yield mc


def _entries(report, typ):
    return sorted(e["path"] for e in report["entries"] if e["type"] == typ)


def _renames(report):
    return {e["path"]: e["target"] for e in report["entries"]
            if e["type"] == "RENAME"}


def test_diff_identical_snapshots_is_empty(cluster):
    with cluster.client() as c:
        c.mkdir("/d1")
        c.write("/d1/a", b"aaa")
        c.allow_snapshot("/d1")
        c.create_snapshot("/d1", "s1")
        c.create_snapshot("/d1", "s2")
        rep = c.snapshot_diff("/d1", "s1", "s2")
        assert rep["entries"] == []


def test_create_delete_modify(cluster):
    with cluster.client() as c:
        c.mkdir("/d2/sub")
        c.write("/d2/keep", b"k")
        c.write("/d2/gone", b"g")
        c.write("/d2/sub/mod", b"before")
        c.allow_snapshot("/d2")
        c.create_snapshot("/d2", "s1")
        c.write("/d2/new", b"n")
        c.delete("/d2/gone")
        c.append("/d2/sub/mod", b"-after")
        c.create_snapshot("/d2", "s2")
        rep = c.snapshot_diff("/d2", "s1", "s2")
        assert _entries(rep, "CREATE") == ["/new"]
        assert _entries(rep, "DELETE") == ["/gone"]
        assert "/sub/mod" in _entries(rep, "MODIFY")
        # parent dirs of membership changes are MODIFY (HDFS reports the
        # containing dir as modified)
        assert "/" in _entries(rep, "MODIFY")
        assert _renames(rep) == {}


def test_rename_tracked_by_inode_across_dirs(cluster):
    with cluster.client() as c:
        c.mkdir("/d3/x")
        c.mkdir("/d3/y")
        c.write("/d3/x/f", b"data")
        c.allow_snapshot("/d3")
        c.create_snapshot("/d3", "s1")
        c.rename("/d3/x/f", "/d3/y/g")
        c.create_snapshot("/d3", "s2")
        rep = c.snapshot_diff("/d3", "s1", "s2")
        assert _renames(rep) == {"/x/f": "/y/g"}
        assert _entries(rep, "CREATE") == []
        assert _entries(rep, "DELETE") == []


def test_dir_rename_does_not_cascade_to_children(cluster):
    """Renaming a directory reports ONE rename; unchanged children under
    it are silent (they moved with their parent)."""
    with cluster.client() as c:
        c.mkdir("/d4/old")
        c.write("/d4/old/a", b"a")
        c.write("/d4/old/b", b"b")
        c.allow_snapshot("/d4")
        c.create_snapshot("/d4", "s1")
        c.rename("/d4/old", "/d4/new")
        c.create_snapshot("/d4", "s2")
        rep = c.snapshot_diff("/d4", "s1", "s2")
        assert _renames(rep) == {"/old": "/new"}
        assert _entries(rep, "CREATE") == []
        assert _entries(rep, "DELETE") == []


def test_rename_plus_modify_reports_both(cluster):
    with cluster.client() as c:
        c.mkdir("/d5")
        c.write("/d5/f", b"v1")
        c.allow_snapshot("/d5")
        c.create_snapshot("/d5", "s1")
        c.rename("/d5/f", "/d5/f2")
        c.append("/d5/f2", b"v2")
        c.create_snapshot("/d5", "s2")
        rep = c.snapshot_diff("/d5", "s1", "s2")
        assert _renames(rep) == {"/f": "/f2"}
        assert "/f2" in _entries(rep, "MODIFY")


def test_diff_against_current_tree(cluster):
    """Empty ``to`` diffs snapshot vs the live directory state."""
    with cluster.client() as c:
        c.mkdir("/d6")
        c.write("/d6/a", b"a")
        c.allow_snapshot("/d6")
        c.create_snapshot("/d6", "s1")
        c.write("/d6/b", b"b")
        rep = c.snapshot_diff("/d6", "s1", "")
        assert _entries(rep, "CREATE") == ["/b"]


def test_recreated_same_name_is_delete_plus_create(cluster):
    """Delete + recreate under the same name is NOT a modify: a new inode
    means backup tools must re-copy, which is exactly what HDFS reports."""
    with cluster.client() as c:
        c.mkdir("/d7")
        c.write("/d7/f", b"one")
        c.allow_snapshot("/d7")
        c.create_snapshot("/d7", "s1")
        c.delete("/d7/f")
        c.write("/d7/f", b"two")
        c.create_snapshot("/d7", "s2")
        rep = c.snapshot_diff("/d7", "s1", "s2")
        assert _entries(rep, "CREATE") == ["/f"]
        assert _entries(rep, "DELETE") == ["/f"]
        assert _renames(rep) == {}


def test_diff_survives_namenode_restart():
    """Inode ids persist in the fsimage+editlog: a diff computed after a
    restart still matches renames instead of degrading to delete+create."""
    with MiniCluster(n_datanodes=1, replication=1) as mc:
        with mc.client() as c:
            c.mkdir("/dr")
            c.write("/dr/f", b"data")
            c.allow_snapshot("/dr")
            c.create_snapshot("/dr", "s1")
        mc.restart_namenode()
        mc.wait_for_datanodes(1)
        import time
        deadline = time.monotonic() + 10
        with mc.client() as c:
            while True:   # wait out startup safemode (block reports)
                try:
                    c.rename("/dr/f", "/dr/g")
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
            c.create_snapshot("/dr", "s2")
            rep = c.snapshot_diff("/dr", "s1", "s2")
            assert _renames(rep) == {"/f": "/g"}
