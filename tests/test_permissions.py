"""Permissions, ACLs, and xattrs (FSPermissionChecker.java:49,
AclStorage.java:65, FSDirXAttrOp.java:46 analogs).

The caller identity rides the RPC (`_user`/`_groups` -> per-thread context);
in-process calls act as the superuser, so these tests talk over the WIRE via
RpcClient/HdrfClient with explicit users."""

from __future__ import annotations

import getpass

import pytest

from hdrf_tpu.client.filesystem import HdrfClient
from hdrf_tpu.config import NameNodeConfig
from hdrf_tpu.proto.rpc import RpcError
from hdrf_tpu.server.namenode import NameNode

SUPER = getpass.getuser()


@pytest.fixture()
def nn(tmp_path):
    n = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "nn"),
                                replication=1, block_size=1 << 20)).start()
    yield n
    n.stop()


def client(nn, user, groups=()):
    return HdrfClient(nn.addr, user=user, groups=list(groups))


class TestModeBits:
    def test_owner_and_inheritance(self, nn):
        with client(nn, SUPER) as su, client(nn, "alice") as al:
            su.mkdir("/home")
            su.chmod("/home", 0o777)
            al.mkdir("/home/alice")
            st = al.stat("/home/alice")
            assert st["owner"] == "alice" and st["mode"] == 0o755

    def test_write_denied_without_parent_write(self, nn):
        with client(nn, SUPER) as su, client(nn, "bob") as bob:
            su.mkdir("/locked")          # superuser-owned, 0755
            with pytest.raises(RpcError) as ei:
                bob.mkdir("/locked/sub")
            assert ei.value.error == "PermissionError"
            with pytest.raises(RpcError):
                bob._call("create", path="/locked/f", client=bob.name)

    def test_read_denied_by_mode(self, nn):
        with client(nn, SUPER) as su, client(nn, "eve") as eve:
            su.mkdir("/priv")
            su.chmod("/priv", 0o700)
            with pytest.raises(RpcError):
                eve.ls("/priv")
            # traverse through a 0700 dir also fails (EXECUTE on ancestor)
            with pytest.raises(RpcError):
                eve._call("get_block_locations", path="/priv/x")

    def test_chmod_owner_only(self, nn):
        with client(nn, SUPER) as su, client(nn, "alice") as al, \
                client(nn, "bob") as bob:
            su.mkdir("/home")
            su.chmod("/home", 0o777)
            al.mkdir("/home/alice")
            with pytest.raises(RpcError):
                bob.chmod("/home/alice", 0o777)
            assert al.chmod("/home/alice", 0o700)
            assert al.stat("/home/alice")["mode"] == 0o700

    def test_chown_superuser_only(self, nn):
        with client(nn, SUPER) as su, client(nn, "alice") as al:
            su.mkdir("/d")
            with pytest.raises(RpcError):
                al.chown("/d", owner="alice")
            assert su.chown("/d", owner="alice", group="staff")
            st = su.stat("/d")
            assert st["owner"] == "alice" and st["group"] == "staff"

    def test_group_access(self, nn):
        with client(nn, SUPER) as su, \
                client(nn, "carol", groups=["eng"]) as carol:
            su.mkdir("/shared")
            su.chown("/shared", group="eng")
            su.chmod("/shared", 0o770)
            carol.mkdir("/shared/x")  # group WRITE via membership
            assert carol.ls("/shared")


class TestAcls:
    def test_named_user_acl_grants_access(self, nn):
        with client(nn, SUPER) as su, client(nn, "dave") as dave:
            su.mkdir("/acl")
            su.chmod("/acl", 0o700)
            with pytest.raises(RpcError):
                dave.ls("/acl")
            su.setfacl("/acl", spec="user:dave:r-x")
            assert dave.ls("/acl") == []
            # but no WRITE
            with pytest.raises(RpcError):
                dave.mkdir("/acl/w")

    def test_mask_limits_named_entries(self, nn):
        with client(nn, SUPER) as su, client(nn, "dave") as dave:
            su.mkdir("/m")
            su.chmod("/m", 0o700)
            su.setfacl("/m", spec="user:dave:rwx,mask::r-x")
            assert dave.ls("/m") == []          # r through mask
            with pytest.raises(RpcError):
                dave.mkdir("/m/w")              # w masked out

    def test_default_acl_inherited(self, nn):
        with client(nn, SUPER) as su, client(nn, "erin") as erin:
            su.mkdir("/proj")
            su.chmod("/proj", 0o777)
            su.setfacl("/proj", default_spec="user:erin:rwx")
            su.mkdir("/proj/sub")
            su.chmod("/proj/sub", 0o700)
            # child inherited the default ACL as its access ACL
            assert erin.ls("/proj/sub") == []
            acl = su.getfacl("/proj/sub")
            assert ["user", "erin", 7] in acl["acl"]

    def test_getfacl_strings(self, nn):
        with client(nn, SUPER) as su:
            su.mkdir("/fmt")
            su.setfacl("/fmt", spec="user:zed:rw-")
            ent = su.getfacl("/fmt")["entries"]
            assert "user:zed:rw-" in ent and any(
                e.startswith("user::") for e in ent)

    def test_remove_all(self, nn):
        with client(nn, SUPER) as su, client(nn, "dave") as dave:
            su.mkdir("/rb")
            su.chmod("/rb", 0o700)
            su.setfacl("/rb", spec="user:dave:r-x")
            assert dave.ls("/rb") == []
            su.setfacl("/rb", remove_all=True)
            with pytest.raises(RpcError):
                dave.ls("/rb")


class TestXattrs:
    def test_user_xattr_roundtrip(self, nn):
        with client(nn, SUPER) as su:
            su.mkdir("/x")
            su.setfattr("/x", "user.tag", b"gold")
            assert su.getfattr("/x") == {"user.tag": b"gold"}
            su.removefattr("/x", "user.tag")
            assert su.getfattr("/x") == {}

    def test_trusted_ns_superuser_only(self, nn):
        with client(nn, SUPER) as su, client(nn, "alice") as al:
            su.mkdir("/x")
            su.chmod("/x", 0o777)
            su.setfattr("/x", "trusted.t", b"1")
            with pytest.raises(RpcError):
                al.setfattr("/x", "trusted.evil", b"1")
            # trusted.* hidden from non-superusers
            assert "trusted.t" not in al.getfattr("/x")
            assert su.getfattr("/x")["trusted.t"] == b"1"

    def test_namespace_required(self, nn):
        with client(nn, SUPER) as su:
            su.mkdir("/x")
            with pytest.raises(RpcError):
                su.setfattr("/x", "nonamespace", b"v")


class TestPersistence:
    def test_attrs_survive_restart(self, nn, tmp_path):
        with client(nn, SUPER) as su:
            su.mkdir("/keep")
            su.chmod("/keep", 0o750)
            su.chown("/keep", owner="alice", group="eng")
            su.setfacl("/keep", spec="user:bob:r--")
            su.setfattr("/keep", "user.k", b"v")
        nn.stop()
        nn2 = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "nn"),
                                      replication=1)).start()
        try:
            with client(nn2, SUPER) as su:
                st = su.stat("/keep")
                assert (st["owner"], st["group"], st["mode"]) == \
                    ("alice", "eng", 0o750)
                assert ["user", "bob", 4] in su.getfacl("/keep")["acl"]
                assert su.getfattr("/keep")["user.k"] == b"v"
        finally:
            nn2.stop()

    def test_ha_failover_preserves_acls(self, tmp_path):
        """ACLs/xattrs set on the active survive a failover to the standby
        (they ride the shared edit log like every mutation)."""
        from hdrf_tpu.testing.minicluster import MiniCluster

        with MiniCluster(n_datanodes=1, replication=1, ha=True) as mc:
            with HdrfClient(mc.nn_addrs(), user=SUPER) as c:
                c.mkdir("/ha")
                c.chmod("/ha", 0o750)
                c.chown("/ha", owner="alice", group="eng")
                c.setfacl("/ha", spec="user:bob:rwx",
                          default_spec="user:bob:r-x")
                c.setfattr("/ha", "user.site", b"a1")
            mc.failover()
            with HdrfClient([mc.namenode.addr], user=SUPER) as c:
                st = c.stat("/ha")
                assert (st["owner"], st["group"], st["mode"]) == \
                    ("alice", "eng", 0o750)
                acl = c.getfacl("/ha")
                assert ["user", "bob", 7] in acl["acl"]
                assert ["user", "bob", 5] in acl["default_acl"]
                assert c.getfattr("/ha")["user.site"] == b"a1"
                # enforcement still live post-failover
                with HdrfClient([mc.namenode.addr], user="mallory") as m:
                    with pytest.raises(RpcError):
                        m.chmod("/ha", 0o777)


class TestCli:
    def test_chmod_acl_xattr_via_shell(self, nn, capsys):
        from hdrf_tpu.tools import cli

        addr = f"{nn.addr[0]}:{nn.addr[1]}"
        assert cli.main(["dfs", "--namenode", addr, "-mkdir", "/c"]) == 0
        assert cli.main(["dfs", "--namenode", addr, "-chmod", "750", "/c"]) == 0
        assert cli.main(["dfs", "--namenode", addr, "-chown", "alice:eng",
                         "/c"]) == 0
        assert cli.main(["dfs", "--namenode", addr, "-setfacl", "-m",
                         "user:bob:rwx,default:user:bob:r-x", "/c"]) == 0
        assert cli.main(["dfs", "--namenode", addr, "-getfacl", "/c"]) == 0
        out = capsys.readouterr().out
        assert "user:bob:rwx" in out and "default:user:bob:r-x" in out
        assert cli.main(["dfs", "--namenode", addr, "-setfattr", "-n", "user.k",
                         "-v", "v1", "/c"]) == 0
        assert cli.main(["dfs", "--namenode", addr, "-getfattr", "/c"]) == 0
        assert "user.k=v1" in capsys.readouterr().out
        st = nn.rpc_stat("/c")
        assert (st["owner"], st["group"], st["mode"]) == \
            ("alice", "eng", 0o750)


class TestReviewHoles:
    def test_snapshot_path_does_not_bypass_mode(self, nn):
        """A 0600 file must not become readable through
        /dir/.snapshot/name/... (the frozen inode keeps its attrs)."""
        with client(nn, SUPER) as su, client(nn, "mallory") as m:
            su.mkdir("/d")
            su.chmod("/d", 0o755)
            su._call("create", path="/d/secret", client="w")
            su._call("complete", path="/d/secret", client="w",
                     block_lengths={})
            su.chmod("/d/secret", 0o600)
            su._call("allow_snapshot", path="/d")
            su._call("create_snapshot", path="/d", name="s1")
            with pytest.raises(RpcError) as ei:
                m._call("get_block_locations", path="/d/.snapshot/s1/secret")
            assert ei.value.error == "PermissionError"

    def test_snapshot_and_quota_ops_checked(self, nn):
        with client(nn, SUPER) as su, client(nn, "mallory") as m:
            su.mkdir("/q")
            su._call("allow_snapshot", path="/q")
            su.create_snapshot("/q", "s1")
            with pytest.raises(RpcError):
                m._call("allow_snapshot", path="/q")
            with pytest.raises(RpcError):
                m.delete_snapshot("/q", "s1")
            with pytest.raises(RpcError):
                m.set_quota("/q", namespace_quota=1)

    def test_stat_requires_traverse(self, nn):
        with client(nn, SUPER) as su, client(nn, "mallory") as m:
            su.mkdir("/p2")
            su.chmod("/p2", 0o700)
            with pytest.raises(RpcError):
                m.stat("/p2/x")
            with pytest.raises(RpcError):
                m.content_summary("/p2")

    def test_chgrp_requires_membership(self, nn):
        with client(nn, SUPER) as su, \
                client(nn, "alice", groups=["eng"]) as al:
            su.mkdir("/home")
            su.chmod("/home", 0o777)
            al.mkdir("/home/alice")
            with pytest.raises(RpcError):
                al.chown("/home/alice", group="finance")
            assert al.chown("/home/alice", group="eng")

    def test_modify_recalculates_stale_mask(self, nn):
        with client(nn, SUPER) as su, client(nn, "carol") as carol:
            su.mkdir("/msk")
            su.chmod("/msk", 0o700)
            su.setfacl("/msk", spec="user:bob:r--,mask::r--")
            su.setfacl("/msk", spec="user:carol:rwx")  # mask must recalc
            carol.mkdir("/msk/w")  # write works: not limited by stale r--
