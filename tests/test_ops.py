"""JAX op tests: gear scan + SHA-256 lanes vs the native C++ oracles.

Runs on the 8-device virtual CPU backend (conftest.py); the same code paths
compile for TPU.
"""

import hashlib

import numpy as np

from hdrf_tpu import native
from hdrf_tpu.ops import gear, sha256 as jsha

RNG = np.random.default_rng(11)


def test_gear_table_matches_native():
    assert np.array_equal(gear.gear_table_np(), native.gear_table())


def test_gear_candidates_match_native():
    for n in [0, 31, 32, 100, 4096, 1 << 17]:
        data = RNG.integers(0, 256, n, dtype=np.uint8)
        mask = 0x3F0  # ~6 bits -> dense-ish
        got = gear.gear_candidates_jax(data, mask)
        want = native.gear_candidates(data, mask)
        assert np.array_equal(got, want), n


def test_gear_candidates_dense_mask():
    data = RNG.integers(0, 256, 8192, dtype=np.uint8)
    got = gear.gear_candidates_jax(data, 0x0)  # every position >= 32 matches
    want = native.gear_candidates(data, 0x0)
    assert np.array_equal(got, want)


def test_cdc_chunk_jax_equals_native():
    for n in [0, 5000, 1 << 18]:
        data = RNG.integers(0, 256, n, dtype=np.uint8)
        got = gear.cdc_chunk_jax(data, 0x1FF, 512, 8192)
        want = native.cdc_chunk(data, 0x1FF, 512, 8192)
        assert np.array_equal(got, want), n


def test_sha256_lanes_vs_hashlib():
    # Lengths straddling every padding edge case.
    lengths = [1, 54, 55, 56, 63, 64, 65, 119, 120, 128, 1000]
    data = RNG.integers(0, 256, sum(lengths), dtype=np.uint8)
    cuts = np.cumsum(lengths).astype(np.uint64)
    got = jsha.fingerprint_chunks(data, cuts)
    off = 0
    for i, ln in enumerate(lengths):
        want = hashlib.sha256(data[off:off + ln].tobytes()).digest()
        assert got[i].tobytes() == want, (i, ln)
        off += ln


def test_fingerprint_chunks_vs_native_batch():
    data = RNG.integers(0, 256, 1 << 18, dtype=np.uint8)
    cuts = native.cdc_chunk(data, 0x1FFF, 2048, 65536)
    got = jsha.fingerprint_chunks(data, cuts)
    offs = np.concatenate([[0], cuts[:-1]])
    lens = cuts - offs
    want = native.sha256_batch(data, offs, lens)
    assert np.array_equal(got, want)


def test_fingerprint_empty():
    assert jsha.fingerprint_chunks(b"", np.array([], dtype=np.uint64)).shape == (0, 32)
