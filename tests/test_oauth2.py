"""OAuth2 for the WebHDFS gateway (web/oauth2/AccessTokenProvider.java,
ConfCredentialBasedAccessTokenProvider, ConfRefreshTokenBased...): client
providers fetch bearer tokens from an IdP; the gateway validates bearers by
RFC 7662 introspection and uses the introspected identity.  A stub IdP
drives the whole path — no external identity provider needed."""

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

import pytest

from hdrf_tpu.client.oauth2 import (
    ConfCredentialBasedAccessTokenProvider,
    ConfRefreshTokenBasedAccessTokenProvider)
from hdrf_tpu.server.http_gateway import HttpGateway
from hdrf_tpu.testing.minicluster import MiniCluster


class StubIdP:
    """Tiny OAuth2 server: /token (client_credentials + refresh_token
    grants) and /introspect (RFC 7662)."""

    def __init__(self):
        self.issued: dict[str, str] = {}       # access token -> username
        self.grants_served: list[str] = []
        idp = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                form = {k: v[0] for k, v in
                        parse_qs(self.rfile.read(n).decode()).items()}
                if self.path == "/token":
                    grant = form.get("grant_type", "")
                    idp.grants_served.append(grant)
                    if grant == "client_credentials" and \
                            form.get("client_secret") == "s3cret":
                        tok = f"at-{len(idp.issued)}"
                        idp.issued[tok] = form["client_id"]
                        return self._json({"access_token": tok,
                                           "expires_in": 3600})
                    if grant == "refresh_token" and \
                            form.get("refresh_token") == "refresh-ok":
                        tok = f"at-{len(idp.issued)}"
                        idp.issued[tok] = form["client_id"]
                        return self._json({"access_token": tok,
                                           "expires_in": 120})
                    return self._json({"error": "invalid_grant"}, 400)
                if self.path == "/introspect":
                    user = idp.issued.get(form.get("token", ""))
                    return self._json({"active": user is not None,
                                       **({"username": user} if user
                                          else {})})
                self._json({"error": "not found"}, 404)

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.addr = self._server.server_address
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()

    def url(self, p):
        return f"http://{self.addr[0]}:{self.addr[1]}{p}"

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


@pytest.fixture()
def stack():
    idp = StubIdP()
    with MiniCluster(n_datanodes=1, replication=1) as mc:
        gw = HttpGateway(mc.namenode.addr,
                         oauth2_introspect_url=idp.url("/introspect"),
                         gate_token_issue=True).start()
        try:
            yield idp, gw, mc
        finally:
            gw.stop()
            idp.stop()


def _get(url, bearer=None):
    req = urllib.request.Request(url)
    if bearer:
        req.add_header("Authorization", f"Bearer {bearer}")
    with urllib.request.urlopen(req) as r:
        return r.status, json.loads(r.read())


def test_credential_provider_and_bearer_auth(stack):
    idp, gw, mc = stack
    prov = ConfCredentialBasedAccessTokenProvider(
        idp.url("/token"), client_id="alice", client_secret="s3cret")
    tok = prov.access_token()
    assert prov.access_token() == tok          # cached, one grant served
    assert idp.grants_served == ["client_credentials"]
    base = f"http://{gw.addr[0]}:{gw.addr[1]}/webhdfs/v1"
    st, out = _get(f"{base}/?op=GETHOMEDIRECTORY", bearer=tok)
    assert st == 200
    assert out["Path"] == "/user/alice"        # introspected identity


def test_refresh_token_provider(stack):
    idp, gw, _ = stack
    prov = ConfRefreshTokenBasedAccessTokenProvider(
        idp.url("/token"), client_id="bob", refresh_token="refresh-ok")
    tok = prov.access_token()
    base = f"http://{gw.addr[0]}:{gw.addr[1]}/webhdfs/v1"
    st, out = _get(f"{base}/?op=GETHOMEDIRECTORY", bearer=tok)
    assert out["Path"] == "/user/bob"


def test_invalid_bearer_rejected(stack):
    _, gw, _ = stack
    base = f"http://{gw.addr[0]}:{gw.addr[1]}/webhdfs/v1"
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(f"{base}/?op=GETHOMEDIRECTORY", bearer="forged")
    assert e.value.code == 401


def test_token_issue_gated(stack):
    """GETDELEGATIONTOKEN refuses unauthenticated callers when gated, and
    mints for the INTROSPECTED identity when bearer-authenticated —
    closing the claimed-user.name spoof."""
    idp, gw, _ = stack
    base = f"http://{gw.addr[0]}:{gw.addr[1]}/webhdfs/v1"
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(f"{base}/?op=GETDELEGATIONTOKEN&user.name=root")
    assert e.value.code == 403
    prov = ConfCredentialBasedAccessTokenProvider(
        idp.url("/token"), client_id="carol", client_secret="s3cret")
    st, out = _get(f"{base}/?op=GETDELEGATIONTOKEN&user.name=root",
                   bearer=prov.access_token())
    assert st == 200
    from hdrf_tpu.server.http_gateway import decode_token
    assert decode_token(out["Token"]["urlString"])["owner"] == "carol"


def test_bearer_marker_cannot_be_spoofed_via_query(stack):
    """'?_bearer=1' in the URL must not impersonate an authenticated
    caller past the token-issue gate."""
    _, gw, _ = stack
    base = f"http://{gw.addr[0]}:{gw.addr[1]}/webhdfs/v1"
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(f"{base}/?op=GETDELEGATIONTOKEN&user.name=root&_bearer=1")
    assert e.value.code == 403


def test_forged_delegation_param_cannot_pass_gate(stack):
    """A base64/msgpack blob claiming owner=root is NOT authentication:
    the gate verifies the delegation token with the NameNode."""
    import base64
    import msgpack
    _, gw, _ = stack
    forged = base64.urlsafe_b64encode(
        msgpack.packb({"owner": "root", "seq": 1, "key_id": 1,
                       "renewer": "", "password": b"x" * 32})).decode()
    base = f"http://{gw.addr[0]}:{gw.addr[1]}/webhdfs/v1"
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(f"{base}/?op=GETDELEGATIONTOKEN&delegation={forged}")
    assert e.value.code == 403
