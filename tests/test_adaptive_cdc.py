"""Adaptive chunk sizing (ISSUE 15 leg 3): the AdaptiveChunkController's
decisions (reduction/accounting.py), the DataNode live-reconfig path that
applies them (server/datanode.py _reconfigure_cdc / _cdc_tick), and the
end-to-end loop — dedup-poor evidence coarsens the live geometry while
data committed under the OLD geometry reads back bit-identical (the
content-addressed-fingerprint safety argument, ARCHITECTURE.md decision
15).  The oracle property test pins EVERY geometry the controller can
emit against native.cdc_chunk through both the XLA scan and the fused
Pallas kernel, so no retune can steer cuts onto an unverified shape.
"""

import numpy as np

from hdrf_tpu import native
from hdrf_tpu.config import CdcConfig
from hdrf_tpu.ops import cdc_pallas, gear
from hdrf_tpu.ops.dispatch import gear_mask
from hdrf_tpu.reduction import accounting
from hdrf_tpu.reduction.accounting import AdaptiveChunkController


# ------------------------------------------------------- controller decisions


class TestController:
    def test_defaults_reproduce_shipped_geometry(self):
        """Enabling the controller must be a no-op until evidence moves
        it: the default target reproduces CdcConfig's 2048/65536."""
        ctl = AdaptiveChunkController()
        cdc = CdcConfig()
        assert ctl.geometry(ctl.target) == (cdc.min_chunk, cdc.max_chunk)

    def test_window_gating_no_decision_on_thin_evidence(self):
        ctl = AdaptiveChunkController(window_chunks=512)
        assert ctl.observe(10, 100, 13) == []          # 110 < 512
        assert ctl.observe(20, 200, 13) == []          # 330 < 512
        # the window accumulates across calls: crossing it decides
        steps = ctl.observe(20, 500, 13)
        assert steps                                    # 720 >= 512, poor

    def test_coarsen_on_dedup_poor_and_step_order(self):
        ctl = AdaptiveChunkController(window_chunks=64)
        steps = ctl.observe(0, 64, 13)                  # ratio 0 < LOW_HIT
        mn, mx = ctl.geometry(14)
        # growing: max first, then min, mask bits last — min<=max holds at
        # every intermediate state starting from geometry(13)
        assert steps == [("cdc_max_chunk", mx), ("cdc_min_chunk", mn),
                         ("cdc_mask_bits", 14)]

    def test_refine_toward_target_when_dedup_rich(self):
        ctl = AdaptiveChunkController(target_mask_bits=13, window_chunks=64)
        steps = ctl.observe(40, 24, 15)                 # ratio > HIGH_HIT
        mn, mx = ctl.geometry(14)
        # shrinking: min first, then max
        assert steps == [("cdc_min_chunk", mn), ("cdc_max_chunk", mx),
                         ("cdc_mask_bits", 14)]

    def test_no_move_at_target_or_midband(self):
        ctl = AdaptiveChunkController(window_chunks=64)
        assert ctl.observe(40, 24, 13) == []            # rich AND at target
        ctl2 = AdaptiveChunkController(window_chunks=64)
        assert ctl2.observe(10, 54, 13) == []           # mid-band ratio

    def test_clamped_at_mask_bits_max(self):
        ctl = AdaptiveChunkController(window_chunks=64)
        assert ctl.observe(0, 64, ctl.MASK_BITS_MAX) == []

    def test_counter_reset_restarts_window(self):
        ctl = AdaptiveChunkController(window_chunks=64)
        assert ctl.observe(0, 60, 13) == []
        # process restart: cumulative counters went BACKWARD; the partial
        # window is discarded rather than polluted with a bogus delta
        assert ctl.observe(0, 10, 13) == []
        assert ctl._win_hit == ctl._win_miss == 0
        assert ctl.observe(0, 30, 13) == []             # 20 new misses only

    def test_rollback_hold_sits_out_full_windows(self):
        """After a guard rollback the controller must not re-propose the
        same retune from the very next window: note_rollback(2) consumes
        two FULL dedup-poor windows before deciding again."""
        ctl = AdaptiveChunkController(window_chunks=64)
        ctl.note_rollback(hold_windows=2)
        assert ctl.observe(0, 64, 13) == []     # full poor window: held
        assert ctl.observe(0, 128, 13) == []    # second window: held
        steps = ctl.observe(0, 192, 13)         # hold expired: decides
        assert steps and steps[-1] == ("cdc_mask_bits", 14)

    def test_hold_only_burns_on_full_windows(self):
        ctl = AdaptiveChunkController(window_chunks=64)
        ctl.note_rollback(hold_windows=1)
        assert ctl.observe(0, 10, 13) == []     # partial: hold untouched
        assert ctl._hold_windows == 1
        assert ctl.observe(0, 64, 13) == []     # full window burns it
        assert ctl._hold_windows == 0

    def test_steps_keep_min_le_max_at_every_intermediate(self):
        """Property over every (old, new) pair in the emit range: applying
        the ordered steps one at a time never passes through a state with
        min_chunk > max_chunk — the invariant _reconfigure_cdc enforces,
        so a mis-ordered plan would strand the retune halfway."""
        ctl = AdaptiveChunkController()
        lo, hi = ctl.MASK_BITS_MIN, ctl.MASK_BITS_MAX
        for old in range(lo, hi + 1):
            for new in range(lo, hi + 1):
                if old == new:
                    continue
                state = dict(zip(("min_chunk", "max_chunk"),
                                 ctl.geometry(old)))
                for key, value in ctl.steps(old, new):
                    field = key[len("cdc_"):]
                    if field in state:
                        state[field] = value
                    assert state["min_chunk"] <= state["max_chunk"], \
                        (old, new, key)
                assert state == dict(zip(("min_chunk", "max_chunk"),
                                         ctl.geometry(new)))


# ------------------------------------------- oracle pin over the emit range


def test_every_emittable_geometry_matches_oracle():
    """ANY (mask_bits, min, max) the controller can request produces cuts
    bit-identical to native.cdc_chunk through BOTH re-expressions — the
    XLA scan (gear.cdc_chunk_jax) and the fused Pallas kernel (interpret
    mode) — or overflows into the declared fallback.  A retune can never
    steer the write path onto an unverified geometry."""
    rng = np.random.default_rng(15)
    a = rng.integers(0, 256, 160_000, dtype=np.uint8)
    a[:40_000] = rng.integers(97, 123, size=40_000, dtype=np.uint8)
    ctl = AdaptiveChunkController()
    for mb, mn, mx in ctl.emit_range():
        mask = gear_mask(CdcConfig(mask_bits=mb, min_chunk=mn,
                                   max_chunk=mx))
        want = np.asarray(native.cdc_chunk(a.tobytes(), mask, mn, mx),
                          dtype=np.uint64)
        np.testing.assert_array_equal(
            gear.cdc_chunk_jax(a, mask, mn, mx).astype(np.uint64), want,
            err_msg=f"xla scan diverges at mask_bits={mb}")
        cuts, overflowed = cdc_pallas.chunks_fused(
            a, mask, mn, mx, mask_bits=mb, interpret=True, skip_ahead=True)
        if overflowed:
            continue      # the declared oracle-fallback path takes over
        np.testing.assert_array_equal(
            cuts, want, err_msg=f"fused kernel diverges at mask_bits={mb}")


# ------------------------------------------------- live-reconfig validation


class TestCdcReconfigure:
    def test_bounds_min_max_and_routing(self):
        from hdrf_tpu.testing.minicluster import MiniCluster

        with MiniCluster(n_datanodes=1, replication=1) as mc:
            dn = mc.datanodes[0]
            for key in ("cdc_mask_bits", "cdc_min_chunk", "cdc_max_chunk"):
                assert key in dn.RECONFIGURABLE
            r = dn.reconfigure("cdc_mask_bits", 25)      # outside [6, 20]
            assert not r["ok"] and "outside" in r["error"]
            r = dn.reconfigure("cdc_mask_bits", "junk")
            assert not r["ok"]
            # min > live max refuses and names the fix
            r = dn.reconfigure("cdc_min_chunk", 1 << 20)
            assert not r["ok"] and "reorder" in r["error"]
            # a valid change lands on the SHARED CdcConfig the write
            # pipeline resolves its reducer from
            cdc = dn.reduction_ctx.config.cdc
            r = dn.reconfigure("cdc_max_chunk", 1 << 17)
            assert r["ok"] and r["old"] == 65536
            assert cdc.max_chunk == 1 << 17
            assert dn.reduction_ctx.config.cdc is cdc


# --------------------------------------------------------- end-to-end loop


def test_adaptive_retune_end_to_end_and_old_reads_survive():
    """The acceptance scenario: a dedup-poor corpus drives the controller
    to a coarser mask through the DataNode's live-reconfig path, and data
    committed under the OLD geometry still reads back bit-identical."""
    import time

    from hdrf_tpu.testing.minicluster import MiniCluster

    overrides = {"cdc_adaptive": True, "cdc_target_mask_bits": 13}
    with MiniCluster(n_datanodes=1, replication=1,
                     reduction_overrides=overrides) as mc:
        dn = mc.datanodes[0]
        ctl = dn._cdc_controller
        assert ctl is not None
        # park the heartbeat loop's tick so exactly ONE deterministic
        # observation decides (the loop fires every 0.2s here and would
        # otherwise consume the window mid-write)
        dn._cdc_controller = None
        ctl.observe(*accounting.dedup_counters(), 13)   # absorb baseline
        ctl._win_hit = ctl._win_miss = 0
        ctl.window_chunks = 64
        cdc = dn.reduction_ctx.config.cdc
        mb0 = cdc.mask_bits
        rng = np.random.default_rng(42)
        old_data = rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
        _, miss0 = accounting.dedup_counters()
        retunes0 = int(accounting.snapshot()["counters"]
                       .get("cdc_retunes", 0))
        with mc.client("adaptive") as c:
            c.write("/adaptive/old-geometry", old_data, scheme="dedup_lz4")
            # the commit stage may be asynchronous: wait until the all-miss
            # chunk commits are on the counters before ticking
            deadline = time.monotonic() + 10
            while (accounting.dedup_counters()[1] - miss0
                   < ctl.window_chunks and time.monotonic() < deadline):
                time.sleep(0.05)
            assert accounting.dedup_counters()[1] - miss0 \
                >= ctl.window_chunks
            # >= 64 all-miss chunk commits accumulated: one heartbeat tick
            # must coarsen by one bit through reconfigure()
            dn._cdc_controller = ctl
            dn._cdc_tick()
            dn._cdc_controller = None
            assert cdc.mask_bits == min(mb0 + 1, ctl.MASK_BITS_MAX)
            assert cdc.min_chunk == ctl.geometry(cdc.mask_bits)[0]
            assert cdc.max_chunk == ctl.geometry(cdc.mask_bits)[1]
            assert cdc.min_chunk <= cdc.max_chunk
            retunes = int(accounting.snapshot()["counters"]
                          .get("cdc_retunes", 0))
            assert retunes >= retunes0 + 3      # max, min, mask_bits steps
            # new writes commit under the NEW geometry...
            new_data = rng.integers(0, 256, 1 << 19, dtype=np.uint8)\
                .tobytes()
            c.write("/adaptive/new-geometry", new_data, scheme="dedup_lz4")
            # ...and both generations read back bit-identical: fingerprints
            # are content-addressed, offsets live in the chunk index, so
            # the retune only moved where NEW cuts land
            assert c.read("/adaptive/old-geometry") == old_data
            assert c.read("/adaptive/new-geometry") == new_data


def test_retune_guard_rolls_back_regressing_geometry():
    """ISSUE 17 leg c: a retune whose post-change flight window regresses
    a blast-radius gauge (write_p95_ms here) is auto-reverted through the
    same reconfigure path, the rollback is booked on retune_rollbacks,
    and the controller holds before re-proposing."""
    from hdrf_tpu.testing.minicluster import MiniCluster

    overrides = {"cdc_adaptive": True, "cdc_target_mask_bits": 13}
    with MiniCluster(n_datanodes=1, replication=1,
                     reduction_overrides=overrides) as mc:
        dn = mc.datanodes[0]
        ctl = dn._cdc_controller
        assert ctl is not None
        dn._cdc_controller = None        # park the heartbeat tick
        cdc = dn.reduction_ctx.config.cdc
        mb0 = cdc.mask_bits
        for key, value in ctl.steps(mb0, mb0 + 1):   # the retune lands
            assert dn.reconfigure(key, value)["ok"]
        assert cdc.mask_bits == mb0 + 1
        # deterministic flight history: healthy baseline, then a post-
        # retune window with write p95 tripled (ring injection keeps the
        # guard's inputs exact; sample cadence is never the semantics)
        dn.flight._ring.clear()
        dn.flight._ring.extend(
            {"t": float(i), "mono": float(i), "write_p95_ms": 10.0}
            for i in range(4))
        dn._arm_cdc_guard(mb0, mb0 + 1)
        assert dn._cdc_guard is not None
        dn.flight._ring.extend(
            {"t": float(10 + i), "mono": float(10 + i),
             "write_p95_ms": 30.0}
            for i in range(dn.GUARD_MIN_SAMPLES))
        before = int(accounting.snapshot()["counters"]
                     .get("retune_rollbacks", 0))
        dn._cdc_guard_tick(ctl)
        assert dn._cdc_guard is None                 # guard consumed
        assert cdc.mask_bits == mb0                  # geometry reverted
        assert (cdc.min_chunk, cdc.max_chunk) == ctl.geometry(mb0)
        assert int(accounting.snapshot()["counters"]
                   ["retune_rollbacks"]) == before + 1
        assert ctl._hold_windows > 0                 # sits out re-propose


def test_retune_guard_keeps_healthy_geometry():
    """The mirror case: post-retune samples no worse than baseline leave
    the new geometry in place and book no rollback."""
    from hdrf_tpu.testing.minicluster import MiniCluster

    overrides = {"cdc_adaptive": True, "cdc_target_mask_bits": 13}
    with MiniCluster(n_datanodes=1, replication=1,
                     reduction_overrides=overrides) as mc:
        dn = mc.datanodes[0]
        ctl = dn._cdc_controller
        dn._cdc_controller = None
        cdc = dn.reduction_ctx.config.cdc
        mb0 = cdc.mask_bits
        for key, value in ctl.steps(mb0, mb0 + 1):
            assert dn.reconfigure(key, value)["ok"]
        dn.flight._ring.clear()
        dn.flight._ring.extend(
            {"t": float(i), "mono": float(i), "write_p95_ms": 10.0}
            for i in range(4))
        dn._arm_cdc_guard(mb0, mb0 + 1)
        dn.flight._ring.extend(
            {"t": float(10 + i), "mono": float(10 + i),
             "write_p95_ms": 10.0}
            for i in range(dn.GUARD_MIN_SAMPLES))
        before = int(accounting.snapshot()["counters"]
                     .get("retune_rollbacks", 0))
        dn._cdc_guard_tick(ctl)
        assert dn._cdc_guard is None
        assert cdc.mask_bits == mb0 + 1              # retune survives
        assert int(accounting.snapshot()["counters"]
                   .get("retune_rollbacks", 0)) == before
