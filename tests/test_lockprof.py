"""Instrumented namesystem lock: exact books under injected clocks.

Pins the lockprof math (utils/lockprof.py, the FSNamesystemLock.java:60
metrics analog): wait/hold/saturation as exact sums under a scripted
clock, reentrant acquires counted once, per-method attribution via the
ambient request context, the long-hold stack capture + its
``lockprof.long_hold`` fault point, and the overhead guard — the
instrumented lock must add no blocking beyond the underlying RLock.
"""

import threading
import time

import pytest

from hdrf_tpu.utils import fault_injection, lockprof, metrics


class ScriptClock:
    """Returns scripted times in call order; repeats the last one after
    the script runs out (so incidental reads can't derail a test)."""

    def __init__(self, times):
        self.times = list(times)

    def __call__(self):
        if len(self.times) > 1:
            return self.times.pop(0)
        return self.times[0]


class TestLockprofMath:
    def test_wait_hold_exact_sums(self):
        # script: epoch=0 | acquire(t0=0, granted=0.5) | release(=2.5)
        #         | acquire(t0=3, granted=3) | release(=3.5) | now=4
        clk = ScriptClock([0.0, 0.0, 0.5, 2.5, 3.0, 3.0, 3.5, 4.0])
        lk = lockprof.InstrumentedRLock("t", clock=clk)
        with lk:
            pass
        with lk:
            pass
        s = lk.contention_summary(now=4.0)
        assert s["acquires"] == 2
        assert s["wait_s"] == pytest.approx(0.5)
        assert s["hold_s"] == pytest.approx(2.0 + 0.5)
        # saturation over the trailing window, exact: lock age 4 s < the
        # 60 s window, so wall=4 and held=2.5
        assert s["saturation"] == pytest.approx(2.5 / 4.0)
        # rolling windows saw both acquires
        assert s["wait_us"]["p99"] == pytest.approx(0.5e6)
        assert s["hold_us"]["p99"] == pytest.approx(2.0e6)

    def test_reentrant_acquires_counted_once(self):
        clk = ScriptClock([0.0, 0.0, 0.0, 1.0, 2.0])
        lk = lockprof.InstrumentedRLock("t", clock=clk)
        with lk:          # outermost: t0=0, granted=0, released at 1.0
            with lk:      # reentrant: no clock reads, no books
                with lk:
                    pass
        s = lk.contention_summary(now=2.0)
        assert s["acquires"] == 1
        assert s["hold_s"] == pytest.approx(1.0)
        assert s["wait_s"] == pytest.approx(0.0)

    def test_method_attribution_via_request_context(self):
        clk = ScriptClock([0.0, 0.0, 0.25, 1.25, 2.0, 2.0, 2.5, 3.0])
        lk = lockprof.InstrumentedRLock("t", clock=clk)
        spans = []
        with lockprof.bind_request("mkdir", spans):
            with lk:
                pass
        with lk:  # no ambient method -> "other"
            pass
        s = lk.contention_summary(now=3.0)
        by = s["by_method"]
        assert by["mkdir"]["acquires"] == 1
        assert by["mkdir"]["wait_s"] == pytest.approx(0.25)
        assert by["mkdir"]["hold_s"] == pytest.approx(1.0)
        assert by["other"]["acquires"] == 1
        assert by["mkdir"]["hold_share"] == pytest.approx(1.0 / 1.5)
        # the decomposition spans landed on the request context
        assert ("lock_wait", 0.0, 0.25) in spans
        assert ("locked", 0.25, 1.25) in spans

    def test_saturation_includes_in_progress_hold(self):
        clk = ScriptClock([0.0, 0.0, 0.0])
        lk = lockprof.InstrumentedRLock("t", clock=clk)
        lk.acquire()
        try:
            # held since t=0, never released: at now=10 the lock was held
            # for the whole (age-clamped) window
            assert lk.saturation(now=10.0) == pytest.approx(1.0)
        finally:
            lk.release()

    def test_long_hold_captures_stack_and_fires_fault_point(self):
        clk = ScriptClock([0.0, 0.0, 0.0, 2.0, 3.0])
        reg = metrics.MetricsRegistry("lockprof-test")
        lk = lockprof.InstrumentedRLock("t", clock=clk, registry=reg,
                                        long_hold_s=1.0)
        fired = []
        with fault_injection.inject("lockprof.long_hold",
                                    lambda **kw: fired.append(kw)):
            with lockprof.bind_request("slow_op"):
                with lk:  # hold = 2.0 s >= budget
                    pass
        assert fired and fired[0]["method"] == "slow_op"
        assert fired[0]["hold_s"] == pytest.approx(2.0)
        s = lk.contention_summary(now=3.0)
        (rec,) = s["long_holds"]
        assert rec["method"] == "slow_op"
        assert rec["hold_s"] == pytest.approx(2.0)
        assert any("test_lockprof" in line for line in rec["stack"])
        assert reg.counter("nn_lock_long_holds") == 1

    def test_blocked_acquire_attributes_wait(self):
        """A real two-thread contention: the waiter's measured wait covers
        the holder's sleep (wall clocks here, so bounded not exact)."""
        lk = lockprof.InstrumentedRLock("t")
        held = threading.Event()

        def holder():
            with lk:
                held.set()
                time.sleep(0.2)

        t = threading.Thread(target=holder)
        t.start()
        held.wait()
        with lockprof.bind_request("waiter"):
            with lk:
                pass
        t.join()
        by = lk.contention_summary()["by_method"]
        assert by["waiter"]["wait_s"] >= 0.1
        assert by["other"]["hold_s"] >= 0.1


class TestLockprofContract:
    def test_drop_in_rlock_semantics(self):
        lk = lockprof.InstrumentedRLock("t")
        assert lk.acquire() is True
        assert lk.acquire() is True  # reentrant
        lk.release()
        lk.release()
        with pytest.raises(RuntimeError):
            lk.release()  # over-release raises like a plain RLock

    def test_holder_probe(self):
        lk = lockprof.InstrumentedRLock("t")
        assert lk.holder() is None
        with lockprof.bind_request("stat"):
            with lk:
                h = lk.holder()
                assert h["thread"] == threading.get_ident()
                assert h["method"] == "stat"
                assert h["held_for_s"] >= 0.0
        assert lk.holder() is None

    def test_uncontended_overhead_bounded(self):
        """The 'no extra blocking' guard: an uncontended instrumented
        acquire/release pair must stay within a small constant of the
        plain RLock — no secondary mutex, no syscalls on the fast path.
        The bound is deliberately loose (wall clocks under a shared VM)
        but far below any lock-queueing effect."""
        n = 5000
        plain = threading.RLock()
        t0 = time.perf_counter()
        for _ in range(n):
            with plain:
                pass
        base = time.perf_counter() - t0
        lk = lockprof.InstrumentedRLock("t")
        t0 = time.perf_counter()
        for _ in range(n):
            with lk:
                pass
        inst = time.perf_counter() - t0
        # overhead per pair under 100 µs — instrumentation costs a few
        # µs; actual blocking (futex waits) would blow far past this
        assert (inst - base) / n < 100e-6

    def test_saturation_gauge_lands_on_registry(self):
        reg = metrics.MetricsRegistry("lockprof-sat")
        clk = ScriptClock([0.0, 0.0, 0.0, 1.0, 2.0])
        lk = lockprof.InstrumentedRLock("t", clock=clk, registry=reg)
        with lk:
            pass
        assert lk.saturation(now=2.0) == pytest.approx(0.5)
        assert reg.snapshot()["gauges"]["nn_lock_saturation"] == \
            pytest.approx(0.5)
