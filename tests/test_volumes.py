"""Multi-volume DataNodes (FsVolumeImpl/FsVolumeList analog,
storage/volumes.py): placement across volumes, per-volume storage types,
volume-failure ejection (DN survives), and the DiskBalancer-lite planner."""

import time

import numpy as np
import pytest

from hdrf_tpu.storage.volumes import CID_SHIFT, VolumeSet
from hdrf_tpu.testing.minicluster import MiniCluster


def _payload(seed: int, n: int = 300_000) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, n, np.uint8).tobytes()


class TestVolumeSet:
    def test_blocks_spread_across_volumes(self, tmp_path):
        vs = VolumeSet(str(tmp_path), ["DISK", "DISK"], container_kw={})
        for bid in range(8):
            w = vs.create_rbw(bid)
            w.write(b"x" * 10_000)
            w.finalize(10_000, "direct", [1], 64 * 1024)
        homes = {vs._where[b] for b in range(8)}
        assert homes == {0, 1}, "placement never used the second volume"
        assert sorted(vs.block_ids()) == list(range(8))
        # report carries each replica's volume type
        assert {t[3] for t in vs.block_report()} == {"DISK"}

    def test_type_hint_routes_to_matching_volume(self, tmp_path):
        vs = VolumeSet(str(tmp_path), ["DISK", "SSD"], container_kw={})
        for bid, want in enumerate(["SSD", "DISK", "SSD"]):
            w = vs.create_rbw(bid, storage_type=want)
            w.write(b"y" * 1000)
            w.finalize(1000, "direct", [1], 64 * 1024)
            vol = vs.volumes[vs._where[bid]]
            assert vol.storage_type == want

    def test_container_cids_route_by_namespace(self, tmp_path):
        vs = VolumeSet(str(tmp_path), ["DISK", "DISK"], container_kw={})
        chunks = [b"c" * 5000, b"d" * 5000]
        locs = vs.containers.append_chunks(chunks, on_seal=lambda c: None)
        for (cid, off, ln), orig in zip(locs, chunks):
            assert vs.volumes[cid >> CID_SHIFT] is vs.volume_of_cid(cid)
        back = vs.containers.read_chunks(locs)
        assert [bytes(b) for b in back] == chunks

    def test_eject_drops_blocks_and_survivors_serve(self, tmp_path):
        vs = VolumeSet(str(tmp_path), ["DISK", "DISK"], container_kw={})
        for bid in range(6):
            w = vs.create_rbw(bid)
            w.write(b"z" * 2000)
            w.finalize(2000, "direct", [1], 64 * 1024)
        lost = vs.eject(0)
        assert lost and set(lost).isdisjoint(vs.block_ids())
        assert vs.alive_count() == 1
        for bid in vs.block_ids():
            assert vs.read_data(bid) == b"z" * 2000
        with pytest.raises(IOError):
            vs.read_data(lost[0])

    def test_disk_balancer_evens_a_skewed_set(self, tmp_path):
        vs = VolumeSet(str(tmp_path), ["DISK", "DISK"], container_kw={})
        # skew everything onto vol-0 by hand
        for bid in range(10):
            w = vs.volumes[0].replicas.create_rbw(bid)
            w.write(b"b" * 100_000)
            w.finalize(100_000, "direct", [1], 64 * 1024)
            vs._where[bid] = 0
        assert vs.volumes[1].used_bytes() == 0
        plan = vs.plan_moves(threshold=0.10)
        assert plan, "planner found nothing to move on a fully skewed DN"
        moved = vs.execute_moves(plan)
        assert moved == len(plan)
        u0, u1 = (vs.volumes[i].used_bytes() for i in (0, 1))
        assert abs(u0 - u1) <= 0.25 * max(u0, u1)
        # moved replicas still serve, routed to their new volume
        for bid in range(10):
            assert vs.read_data(bid) == b"b" * 100_000


class TestMultiVolumeCluster:
    def test_volume_failure_ejects_volume_not_dn(self):
        """VERDICT r3 #7 'done' criterion: a volume dies -> its blocks
        re-replicate from peers, the DataNode itself survives and keeps
        serving its other volume."""
        data = {f"/mv/f{i}": _payload(i) for i in range(6)}
        with MiniCluster(n_datanodes=2, replication=2,
                         volume_types=["DISK", "DISK"],
                         block_size=1 << 20) as mc:
            with mc.client("mv") as c:
                for p, d in data.items():
                    c.write(p, d)
            dn0 = mc.datanodes[0]
            victim = next(v.vol_id for v in dn0.volumes.volumes
                          if v.replicas.block_ids())
            before = set(dn0.volumes.block_ids())
            dn0.eject_volume(victim)
            # DN is alive and still registered; reads keep working (the
            # healthy peer covers the ejected volume's blocks)
            assert dn0.volumes.alive_count() == 1
            with mc.client("mv2") as c:
                for p, d in data.items():
                    assert c.read(p) == d
            # the NN re-replicates the lost replicas back onto dn0's
            # surviving volume or keeps them safe on dn1
            deadline = time.time() + 10
            lost = before - set(dn0.volumes.block_ids())
            while time.time() < deadline:
                rep = mc.namenode.rpc_cluster_status()
                if rep["under_replicated"] == 0 and all(
                        len(mc.namenode._blocks[b].locations) >= 2
                        for b in lost if b in mc.namenode._blocks):
                    break
                time.sleep(0.4)
            for b in lost:
                info = mc.namenode._blocks.get(b)
                if info is not None:
                    assert len(info.locations) >= 2, \
                        f"block {b} not re-replicated: {info.locations}"

    def test_one_ssd_policy_lands_on_ssd_volume(self):
        """Policy placement reaches INTO a mixed DN: with one_ssd, the
        first replica must land on a volume of type SSD (the NN's slot
        hint rides the write op; the DN routes by it)."""
        with MiniCluster(n_datanodes=2, replication=2,
                         volume_types=["DISK", "SSD"],
                         block_size=1 << 20) as mc:
            with mc.client("pol") as c:
                c.mkdir("/ssd")
                c._call("set_storage_policy", path="/ssd", policy="one_ssd")
                c.write("/ssd/f", _payload(9))
            types = set()
            for dn in mc.datanodes:
                for v in dn.volumes.volumes:
                    for bid in v.replicas.block_ids():
                        types.add(v.storage_type)
            assert "SSD" in types, f"no replica landed on an SSD volume"
            # NN learned per-replica types from the 4-tuple block report
            info = next(iter(mc.namenode._blocks.values()))
            deadline = time.time() + 6
            while time.time() < deadline and not info.storage_of:
                time.sleep(0.3)
            assert set(info.storage_of.values()) & {"SSD", "DISK"}

    def test_diskbalancer_op_over_the_wire(self):
        import socket

        from hdrf_tpu.proto import datatransfer as dt
        from hdrf_tpu.proto.rpc import recv_frame

        with MiniCluster(n_datanodes=1, replication=1,
                         volume_types=["DISK", "DISK"],
                         block_size=1 << 20) as mc:
            with mc.client("db") as c:
                for i in range(4):
                    c.write(f"/db/f{i}", _payload(20 + i))
            dn = mc.datanodes[0]
            with socket.create_connection(dn.addr, timeout=30) as s:
                dt.send_op(s, "disk_balance", threshold=0.05)
                r = recv_frame(s)
            assert {v["vol"] for v in r["volumes"]} == {0, 1}
            assert r["moved"] == r["planned"]
