"""Test env: force JAX onto an 8-device virtual CPU mesh.

Sharding tests (tests/test_sharding.py) exercise real Mesh/shard_map code
paths on these virtual devices, mirroring how the driver's dryrun validates
multi-chip compilation without real chips.

The dev tunnel's sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon, which bakes the axon platform into jax's config defaults;
the late ``jax.config.update("jax_platforms", "cpu")`` escape hatch leaves
compilation routed through the tunnel's remote-compile helper, where XLA-CPU
programs hang.  Platform selection must happen via process env at interpreter
start, so when the env is wrong we relaunch pytest once in a child process
with the corrected environment (suspending pytest's fd capture so the child's
report reaches the terminal).  HDRF_TEST_TPU=1 opts out, running the suite
against the real attached chip instead.
"""

import os
import subprocess
import sys

_WRONG_ENV = (os.environ.get("HDRF_TEST_TPU") != "1"
              and (os.environ.get("JAX_PLATFORMS") != "cpu"
                   # JAX_PLATFORMS=cpu alone is not enough: the axon
                   # sitecustomize force-registers the tunnel backend
                   # whenever the pool var is present.
                   or "PALLAS_AXON_POOL_IPS" in os.environ))


def pytest_configure(config):
    if not _WRONG_ENV or config.option.collectonly:
        return
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Without this the tunnel's sitecustomize registers the axon backend,
    # which force-selects jax_platforms="axon,cpu" no matter what the env
    # says; the CPU suite must not touch the tunnel at all.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.suspend_global_capture(in_=True)
    rc = subprocess.call([sys.executable, "-m", "pytest", *sys.argv[1:]],
                         env=env)
    os._exit(rc)


flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _clear_fault_injection():
    yield
    from hdrf_tpu.utils import fault_injection
    fault_injection.clear()
