"""Test env: force JAX onto an 8-device virtual CPU mesh.

Sharding tests (tests/test_sharding.py) exercise real Mesh/shard_map code
paths on these virtual devices, mirroring how the driver's dryrun validates
multi-chip compilation without real chips.

The dev tunnel's sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon, which bakes the axon platform into jax's config defaults;
the late ``jax.config.update("jax_platforms", "cpu")`` escape hatch leaves
compilation routed through the tunnel's remote-compile helper, where XLA-CPU
programs hang.  Platform selection must happen via process env at interpreter
start, so when the env is wrong we relaunch pytest once in a child process
with the corrected environment (suspending pytest's fd capture so the child's
report reaches the terminal).  HDRF_TEST_TPU=1 opts out, running the suite
against the real attached chip instead.
"""

import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hdrf_tpu.utils.cleanenv import env_is_tunneled  # noqa: E402

# JAX_PLATFORMS=cpu alone is not enough: the axon sitecustomize
# force-registers the tunnel backend whenever the pool var is present.
_WRONG_ENV = (os.environ.get("HDRF_TEST_TPU") != "1"
              and (os.environ.get("JAX_PLATFORMS") != "cpu"
                   or env_is_tunneled()))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: full-size kernel runs excluded from the tier-1 "
        "sweep (the Pallas interpreter pays ~1 min per full-width network)")
    if not _WRONG_ENV or config.option.collectonly:
        return
    # Shared recipe (also used by __graft_entry__.dryrun_multichip): drop the
    # tunnel's pool var so its sitecustomize can't register the axon backend,
    # select XLA:CPU at interpreter start, default 8 virtual devices while
    # honoring an operator-set device-count flag.
    from hdrf_tpu.utils.cleanenv import clean_cpu_env
    env = clean_cpu_env(8, keep_existing_count=True)
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.suspend_global_capture(in_=True)
    rc = subprocess.call([sys.executable, "-m", "pytest", *sys.argv[1:]],
                         env=env)
    os._exit(rc)


from hdrf_tpu.utils.cleanenv import ensure_device_count_flag  # noqa: E402

ensure_device_count_flag(8)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _clear_fault_injection():
    yield
    from hdrf_tpu.utils import fault_injection
    fault_injection.clear()
