"""Test env: force JAX onto an 8-device virtual CPU mesh before jax imports.

Sharding tests (tests/test_sharding.py) exercise real Mesh/shard_map code paths on
these virtual devices, mirroring how the driver's dryrun validates multi-chip
compilation without real chips.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _clear_fault_injection():
    yield
    from hdrf_tpu.utils import fault_injection
    fault_injection.clear()
