"""Read-plane serving engine: position→chunk-range resolver edges, the
DN-wide decoded-chunk cache (zero decode bytes on hit, cross-file hits,
byte-budget eviction, retirement invalidation), the read coalescer, and
hedged replica reads — plus the PR's acceptance assertions (range reads
decode exactly the overlapping containers; chunk-cache reads beat the
full-decode baseline on read amplification)."""

import threading

import numpy as np
import pytest

from hdrf_tpu.client.filesystem import HdrfClient
from hdrf_tpu.config import ClientConfig, ReductionConfig
from hdrf_tpu.index.chunk_index import ChunkIndex
from hdrf_tpu.reduction import scheme as schemes
from hdrf_tpu.reduction.scheme import ReductionContext
from hdrf_tpu.server.read_plane import (ChunkCache, ReadCoalescer, ReadPlane,
                                        resolve_chunk_plan)
from hdrf_tpu.storage.container_store import ContainerStore
from hdrf_tpu.utils import metrics

_RP = metrics.registry("read_plane")
_ACC = metrics.registry("reduction_accounting")
_CL = metrics.registry("client")


def _phys() -> int:
    """Decoded-container bytes booked against the dedup_lz4 scheme — the
    read-amplification ledger's physical side (a chunk-cache hit must
    leave this untouched)."""
    return _ACC.counter("read_physical_bytes__dedup_lz4")


def make_ctx(tmp_path, *, container_size: int = 1 << 18,
             cache_containers: int = 4, with_plane: bool = True,
             chunk_cache_mb: float = 8.0, window_ms: float = 0.0,
             batched=None, mask_bits: int = 10, min_chunk: int = 256,
             max_chunk: int = 8192) -> ReductionContext:
    cfg = ReductionConfig()
    cfg.cdc.mask_bits = mask_bits
    cfg.cdc.min_chunk = min_chunk
    cfg.cdc.max_chunk = max_chunk
    containers = ContainerStore(str(tmp_path / "containers"),
                                container_size=container_size, lanes=2,
                                cache_containers=cache_containers)
    ctx = ReductionContext(
        config=cfg, containers=containers,
        index=ChunkIndex(str(tmp_path / "index")), backend="native")
    if with_plane:
        rp = ReadPlane(containers, chunk_cache_mb=chunk_cache_mb,
                       window_ms=window_ms, backend="native", batched=batched)
        rp.attach_store(containers)
        ctx.read_plane = rp
    return ctx


def _chunk_starts(ctx, block_id: int) -> list:
    """Logical start offset of every chunk in the block, from the index
    (the ground truth the resolver walks)."""
    entry = ctx.index.get_block(block_id)
    locmap = ctx.index.lookup_chunks(list(set(entry.hashes)))
    starts, pos = [], 0
    for h in entry.hashes:
        starts.append(pos)
        pos += locmap[h].length
    return starts


# The 7 standard corpora (tests/test_cdc_pallas.py::_corpora, copied
# verbatim — the test_mesh_plane.py precedent) drive the bit-identity
# sweep; (mask, mn, mx) map onto CdcConfig via mask.bit_count().
def _corpora():
    rng = np.random.default_rng(7)
    text = rng.integers(97, 123, size=200_000, dtype=np.uint8)
    yield "random", rng.integers(0, 256, 150_000, dtype=np.uint8), \
        0x1FFF, 2048, 65536
    yield "text-low-entropy", text, 0x1FFF, 2048, 65536
    # sparse mask -> candidate droughts -> forced max-chunk runs
    yield "forced-max-runs", rng.integers(0, 256, 120_000, dtype=np.uint8), \
        0xFFFFFF, 512, 4096
    # dense mask + tiny limits: every-word candidates, lo>hi edge traffic
    yield "dense", rng.integers(0, 256, 30_000, dtype=np.uint8), 0x7, 8, 64
    # block tail shorter than min_chunk: final cut is the short remainder
    yield "tail-short-chunk", rng.integers(0, 256, 65536 + 37,
                                           dtype=np.uint8), \
        0x1FFF, 2048, 65536
    # one supertile exactly / less than one supertile
    yield "single-tile", rng.integers(0, 256, 65536, dtype=np.uint8), \
        0x3FF, 256, 8192
    yield "sub-tile", rng.integers(0, 256, 300, dtype=np.uint8), 0x3F, 16, 128


def _blob(seed: int, n: int) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


# ------------------------------------------------------------ the resolver


class TestResolver:
    def test_zero_length_and_past_eof(self, tmp_path):
        ctx = make_ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        data = _blob(3, 50_000)
        s.reduce(1, data, ctx)
        for off, ln in [(1000, 0), (len(data), -1), (len(data) + 5, 100)]:
            plan = resolve_chunk_plan(ctx.index, 1, off, ln)
            assert plan.out_len == 0 and not plan.wanted
            assert s.reconstruct(1, b"", len(data), ctx, off, ln) == b""

    def test_unknown_block_raises(self, tmp_path):
        ctx = make_ctx(tmp_path)
        with pytest.raises(KeyError):
            resolve_chunk_plan(ctx.index, 404)

    def test_offset_exactly_on_cut_boundary(self, tmp_path):
        ctx = make_ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        data = _blob(4, 80_000)
        s.reduce(2, data, ctx)
        starts = _chunk_starts(ctx, 2)
        assert len(starts) >= 3
        cut = starts[2]  # an interior cut boundary
        plan = resolve_chunk_plan(ctx.index, 2, cut, 100)
        # the preceding chunk must NOT be touched: the first wanted chunk
        # begins at the cut itself (src_lo == 0)
        assert plan.spans[0] == (0, 0, min(100, plan.out_len))
        assert s.reconstruct(2, b"", len(data), ctx, cut, 100) \
            == data[cut:cut + 100]

    def test_tail_read_open_length(self, tmp_path):
        ctx = make_ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        data = _blob(5, 60_000)
        s.reduce(3, data, ctx)
        plan = resolve_chunk_plan(ctx.index, 3, len(data) - 777, -1)
        assert plan.out_len == 777
        assert s.reconstruct(3, b"", len(data), ctx, len(data) - 777, -1) \
            == data[-777:]

    def test_span_across_container_seal_boundary(self, tmp_path):
        # 64 KiB containers force a multi-container block; a range
        # straddling the seal boundary must touch exactly the two
        # adjacent containers.
        ctx = make_ctx(tmp_path, container_size=1 << 16)
        s = schemes.get("dedup_lz4")
        data = _blob(6, 300_000)
        s.reduce(4, data, ctx)
        full = resolve_chunk_plan(ctx.index, 4)
        assert len(full.containers()) >= 2
        edge = next(i for i in range(1, len(full.wanted))
                    if full.wanted[i][0] != full.wanted[i - 1][0])
        boundary = full.spans[edge][0]  # logical start of the first chunk
        plan = resolve_chunk_plan(ctx.index, 4, boundary - 16, 32)
        assert plan.containers() == [full.wanted[edge - 1][0],
                                     full.wanted[edge][0]]
        assert s.reconstruct(4, b"", len(data), ctx, boundary - 16, 32) \
            == data[boundary - 16:boundary + 16]

    def test_pre_resolved_plan_is_honored(self, tmp_path):
        ctx = make_ctx(tmp_path)
        s = schemes.get("dedup_lz4")
        data = _blob(8, 40_000)
        s.reduce(5, data, ctx)
        plan = resolve_chunk_plan(ctx.index, 5, 1000, 2000)
        assert s.reconstruct(5, b"", len(data), ctx, plan=plan) \
            == data[1000:3000]

    @pytest.mark.parametrize("name,a,mask,mn,mx", list(_corpora()),
                             ids=[c[0] for c in _corpora()])
    def test_range_bit_identity(self, tmp_path, name, a, mask, mn, mx):
        ctx = make_ctx(tmp_path, container_size=1 << 16,
                       mask_bits=mask.bit_count(), min_chunk=mn,
                       max_chunk=mx)
        s = schemes.get("dedup_lz4")
        data = a.tobytes()
        s.reduce(9, data, ctx)
        assert s.reconstruct(9, b"", len(data), ctx) == data
        n = len(data)
        ranges = [(0, 10), (0, -1), (n // 3, n // 3), (n - 7, -1),
                  (n // 2, 1), (1, n - 2)]
        ranges += [(c, 64) for c in _chunk_starts(ctx, 9)[:3]]
        for off, ln in ranges:
            end = n if ln < 0 else min(off + ln, n)
            assert s.reconstruct(9, b"", len(data), ctx, off, ln) \
                == data[off:end], (name, off, ln)


# -------------------------------------------- acceptance: decode fan-out


class TestRangeDecodesOnlyOverlap:
    def test_single_container_span_decodes_one(self, tmp_path):
        # chunk cache OFF and container LRU OFF so every read's decode
        # fan-out is observable in containers_fetched / physical bytes
        ctx = make_ctx(tmp_path, container_size=1 << 16, cache_containers=0,
                       chunk_cache_mb=0)
        s = schemes.get("dedup_lz4")
        data = _blob(10, 300_000)
        s.reduce(6, data, ctx)
        full = resolve_chunk_plan(ctx.index, 6)
        assert len(full.containers()) >= 2
        f0, p0, phys0 = (_RP.counter("containers_fetched"),
                         _RP.counter("plans_served"), _phys())
        assert s.reconstruct(6, b"", len(data), ctx, 100, 64) \
            == data[100:164]
        assert _RP.counter("plans_served") - p0 == 1
        assert _RP.counter("containers_fetched") - f0 == 1
        phys_range = _phys() - phys0
        phys1 = _phys()
        assert s.reconstruct(6, b"", len(data), ctx) == data
        phys_full = _phys() - phys1
        # the ≤1-container range decoded strictly less than the full block
        assert 0 < phys_range < phys_full


# ------------------------------------------------------ decoded-chunk LRU


class TestChunkCacheSemantics:
    def test_hit_books_zero_decode_bytes(self, tmp_path):
        ctx = make_ctx(tmp_path, cache_containers=0)
        s = schemes.get("dedup_lz4")
        data = _blob(11, 120_000)
        s.reduce(7, data, ctx)
        assert s.reconstruct(7, b"", len(data), ctx) == data  # warm
        h0, f0, phys0 = (_RP.counter("chunk_cache_hit"),
                         _RP.counter("containers_fetched"), _phys())
        assert s.reconstruct(7, b"", len(data), ctx) == data
        assert _phys() == phys0                       # ZERO decode bytes
        assert _RP.counter("containers_fetched") == f0
        assert _RP.counter("chunk_cache_hit") > h0

    def test_cross_file_dedup_hit(self, tmp_path):
        # same content under a DIFFERENT block id: dedup maps both hash
        # lists onto the same chunks, so reading file B after file A is
        # pure cache hits — zero decode bytes booked for B.
        ctx = make_ctx(tmp_path, cache_containers=0)
        s = schemes.get("dedup_lz4")
        data = _blob(12, 100_000)
        s.reduce(1, data, ctx)
        s.reduce(2, data, ctx)
        assert s.reconstruct(1, b"", len(data), ctx) == data  # warm via A
        h0, phys0 = _RP.counter("chunk_cache_hit"), _phys()
        assert s.reconstruct(2, b"", len(data), ctx) == data  # read B
        assert _phys() == phys0
        assert _RP.counter("chunk_cache_hit") > h0

    def test_byte_budget_eviction_order(self):
        cache = ChunkCache(1000)
        e0 = _RP.counter("chunk_cache_evict")
        cache.put(b"a" * 32, b"x" * 400, cid=1)
        cache.put(b"b" * 32, b"y" * 400, cid=1)
        assert cache.get(b"a" * 32) is not None  # recency bump: a is MRU
        cache.put(b"c" * 32, b"z" * 400, cid=2)  # over budget -> evict LRU
        assert _RP.counter("chunk_cache_evict") - e0 == 1
        assert cache.get(b"b" * 32) is None      # b was LRU, not a
        assert cache.get(b"a" * 32) == b"x" * 400
        assert cache.get(b"c" * 32) == b"z" * 400
        assert cache.bytes_used <= cache.capacity

    def test_disabled_and_oversized(self):
        off = ChunkCache(0)
        off.put(b"f" * 32, b"data", cid=1)
        assert off.get(b"f" * 32) is None and off.bytes_used == 0
        small = ChunkCache(10)
        small.put(b"g" * 32, b"x" * 11, cid=1)  # would evict everything
        assert small.get(b"g" * 32) is None and small.bytes_used == 0

    def test_quarantine_invalidates_cached_chunks(self, tmp_path):
        ctx = make_ctx(tmp_path, container_size=1 << 16)
        s = schemes.get("dedup_lz4")
        data = _blob(13, 300_000)
        s.reduce(8, data, ctx)
        assert s.reconstruct(8, b"", len(data), ctx) == data  # warm
        cache = ctx.read_plane.cache
        assert cache.bytes_used > 0
        plan = resolve_chunk_plan(ctx.index, 8)
        victim = plan.containers()[0]
        inv0 = _RP.counter("chunk_cache_invalidated")
        ctx.containers.quarantine(victim)
        assert _RP.counter("chunk_cache_invalidated") > inv0
        for fp, (cid, _, _) in zip(plan.hashes, plan.wanted):
            if cid == victim:
                assert cache.get(fp) is None  # retired bytes never served

    def test_delete_invalidates_cached_chunks(self, tmp_path):
        ctx = make_ctx(tmp_path, container_size=1 << 16)
        s = schemes.get("dedup_lz4")
        data = _blob(14, 300_000)
        s.reduce(9, data, ctx)
        assert s.reconstruct(9, b"", len(data), ctx) == data
        cache = ctx.read_plane.cache
        plan = resolve_chunk_plan(ctx.index, 9)
        victim = plan.containers()[-1]
        before = cache.bytes_used
        ctx.containers.delete_container(victim)
        assert cache.bytes_used < before
        for fp, (cid, _, _) in zip(plan.hashes, plan.wanted):
            if cid == victim:
                assert cache.get(fp) is None

    def test_read_amp_strictly_below_full_decode_baseline(self, tmp_path):
        # the PR's headline acceptance: repeated reads through the chunk
        # cache book strictly fewer physical bytes than the same reads
        # through the full-decode path (container LRU off on both sides —
        # the fleet-scale working set where containers don't fit the LRU)
        data = _blob(15, 150_000)
        s = schemes.get("dedup_lz4")
        costs = {}
        for mode, with_plane in (("plane", True), ("baseline", False)):
            ctx = make_ctx(tmp_path / mode, cache_containers=0,
                           with_plane=with_plane)
            s.reduce(1, data, ctx)
            phys0 = _phys()
            for _ in range(3):
                assert s.reconstruct(1, b"", len(data), ctx) == data
            costs[mode] = _phys() - phys0
        assert 0 < costs["plane"] < costs["baseline"]


# --------------------------------------------------------- read coalescer


class TestCoalescer:
    def _commit(self, tmp_path, seed=16, n=300_000):
        ctx = make_ctx(tmp_path, container_size=1 << 16, with_plane=False)
        schemes.get("dedup_lz4").reduce(1, _blob(seed, n), ctx)
        return ctx, resolve_chunk_plan(ctx.index, 1).containers()

    def test_inline_fallback_on_native_backend(self, tmp_path):
        ctx, cids = self._commit(tmp_path)
        co = ReadCoalescer(ctx.containers, window_ms=2.0, backend="native")
        assert co._thread is None  # non-TPU backend: no worker spun up
        i0 = _RP.counter("inline_decodes")
        datas = co.fetch(cids[:2])
        assert _RP.counter("inline_decodes") - i0 == 1
        for cid in cids[:2]:
            assert datas[cid] == ctx.containers.read_container(cid)
        co.close()

    def test_batched_groups_concurrent_readers(self, tmp_path):
        ctx, cids = self._commit(tmp_path)
        co = ReadCoalescer(ctx.containers, window_ms=300.0, max_inflight=8,
                           batched=True)
        try:
            b0, c0 = (_RP.counter("read_batches"),
                      _RP.counter("coalesced_reads"))
            barrier = threading.Barrier(2)
            results = [None, None]

            def reader(i):
                barrier.wait()
                results[i] = co.fetch([cids[0]])

            ts = [threading.Thread(target=reader, args=(i,))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            # both landed in ONE window: one batch, both members coalesced
            assert _RP.counter("read_batches") - b0 == 1
            assert _RP.counter("coalesced_reads") - c0 == 2
            want = ctx.containers.read_container(cids[0])
            assert results[0][cids[0]] == results[1][cids[0]] == want
        finally:
            co.close()

    def test_batched_propagates_errors(self, tmp_path):
        ctx, _ = self._commit(tmp_path)
        co = ReadCoalescer(ctx.containers, window_ms=1.0, batched=True)
        try:
            with pytest.raises(Exception):
                co.fetch([987654])  # no such container
        finally:
            co.close()


# ------------------------------------------------------ hedged replica reads


def _hedge_client(**cfg_kw) -> HdrfClient:
    cfg = ClientConfig(short_circuit=False, **cfg_kw)
    return HdrfClient(("127.0.0.1", 1), config=cfg, name="hedge-test")


def _binfo():
    return {"block_id": 42, "token": None,
            "locations": [{"addr": ("10.0.0.1", 1001)},
                          {"addr": ("10.0.0.2", 1002)}]}


class TestHedgedReads:
    def test_hedge_fires_on_primary_failure(self):
        c = _hedge_client(read_hedge_floor_s=5.0)

        def fake_read(addr, block_id, offset, length, token=None):
            if addr[0] == "10.0.0.1":
                raise ConnectionError("primary down")
            return b"replica-bytes"

        c._read_from = fake_read
        f0, w0 = (_CL.counter("read_hedges_fired"),
                  _CL.counter("read_hedge_wins"))
        assert c._read_block(_binfo(), 0, -1) == b"replica-bytes"
        # fail-fast: the hedge launched immediately, well before the 5 s
        # deadline, and the hedge leg won
        assert _CL.counter("read_hedges_fired") - f0 == 1
        assert _CL.counter("read_hedge_wins") - w0 == 1

    def test_hedge_fires_on_slow_primary(self):
        c = _hedge_client(read_hedge_floor_s=0.05)
        release = threading.Event()

        def fake_read(addr, block_id, offset, length, token=None):
            if addr[0] == "10.0.0.1":
                release.wait(timeout=10)  # primary stalls past the deadline
                return b"slow-primary"
            return b"fast-hedge"

        c._read_from = fake_read
        w0 = _CL.counter("read_hedge_wins")
        try:
            assert c._read_block(_binfo(), 0, -1) == b"fast-hedge"
        finally:
            release.set()
        assert _CL.counter("read_hedge_wins") - w0 == 1

    def test_primary_win_is_not_a_hedge_win(self):
        c = _hedge_client(read_hedge_floor_s=5.0)
        c._read_from = lambda *a, **k: b"primary"
        f0, w0 = (_CL.counter("read_hedges_fired"),
                  _CL.counter("read_hedge_wins"))
        assert c._read_block(_binfo(), 0, -1) == b"primary"
        assert _CL.counter("read_hedges_fired") == f0
        assert _CL.counter("read_hedge_wins") == w0

    def test_disabled_restores_serial_failover(self):
        c = _hedge_client(hedged_reads=False)
        calls = []

        def fake_read(addr, block_id, offset, length, token=None):
            calls.append(addr)
            if len(calls) == 1:
                raise ConnectionError("first replica down")
            return b"serial"

        c._read_from = fake_read
        f0 = _CL.counter("read_hedges_fired")
        assert c._read_block(_binfo(), 0, -1) == b"serial"
        assert calls == [("10.0.0.1", 1001), ("10.0.0.2", 1002)]
        assert _CL.counter("read_hedges_fired") == f0

    def test_all_locations_failed(self):
        c = _hedge_client(read_hedge_floor_s=0.01)

        def fake_read(addr, block_id, offset, length, token=None):
            raise ConnectionError(f"{addr} down")

        c._read_from = fake_read
        with pytest.raises(IOError, match="all 2 locations failed"):
            c._read_block(_binfo(), 0, -1)
