"""Aux subsystems: short-circuit local reads (fd passing), block scanner
corruption detection + NN-driven recovery, HTTP gateway (WebHDFS surface)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.utils import metrics


@pytest.fixture
def cluster():
    with MiniCluster(n_datanodes=3, replication=2) as mc:
        yield mc


class TestShortCircuit:
    def test_local_read_uses_fd_passing(self, cluster):
        payload = np.random.default_rng(0).integers(
            0, 256, size=200_000, dtype=np.uint8).tobytes()
        with cluster.client("sc") as c:
            c.write("/sc/f", payload, scheme="direct")
            before = metrics.registry("shortcircuit").snapshot()[
                "counters"].get("local_reads", 0)
            assert c.read("/sc/f") == payload
            after = metrics.registry("shortcircuit").snapshot()[
                "counters"].get("local_reads", 0)
            assert after > before  # all DNs are 127.0.0.1 in MiniCluster
            # ranged pread through the passed fd
            assert c.read("/sc/f", offset=1234, length=999) == \
                payload[1234:2233]

    def test_cached_fd_revoked_on_replica_invalidate(self, cluster):
        """ShortCircuitRegistry.java:83 'done' criterion: SC read with a
        CACHED fd, the local replica is deleted (NN invalidate — the
        balancer-move / excess-replica path), and the next read falls
        back to a remote copy instead of serving the dead inode."""
        payload = np.random.default_rng(5).integers(
            0, 256, size=150_000, dtype=np.uint8).tobytes()
        with cluster.client("scr") as c:
            c.write("/sc/rev", payload, scheme="direct")
            cluster.wait_for_replication("/sc/rev", 2)
            assert c.read("/sc/rev") == payload
            assert c.read("/sc/rev") == payload   # second read: cached fd
            snap = metrics.registry("shortcircuit").snapshot()["counters"]
            assert snap.get("cached_fd_reads", 0) > 0, "fd cache never hit"
            assert c._sc_cache is not None and c._sc_cache._fds
            # the grant came from the FIRST location's DN; invalidate its
            # replica (what an NN invalidate command does)
            loc = c._nn.call("get_block_locations", path="/sc/rev")
            binfo = loc["blocks"][0]
            dn = cluster.datanodes[
                int(binfo["locations"][0]["dn_id"].split("-")[1])]
            dn._invalidate(binfo["block_id"])
            # next read: slot is zeroed -> cached fd dropped -> re-request
            # answers no_block -> remote fallback serves the good copy
            assert c.read("/sc/rev") == payload, \
                "read after invalidate did not fall back cleanly"
            snap = metrics.registry("shortcircuit").snapshot()["counters"]
            assert snap.get("cached_fd_revoked", 0) > 0, \
                "no grant was ever revoked"

    def test_append_after_cached_read_serves_new_bytes(self, cluster):
        """Supersede flavor: whatever block layout append produces, a
        client that cached fds beforehand must observe the appended
        bytes."""
        payload = np.random.default_rng(6).integers(
            0, 256, size=150_000, dtype=np.uint8).tobytes()
        with cluster.client("sca") as c:
            c.write("/sc/app", payload, scheme="direct")
            assert c.read("/sc/app") == payload
            assert c.read("/sc/app") == payload   # cached
            c.append("/sc/app", b"TAIL" * 10)
            assert c.read("/sc/app") == payload + b"TAIL" * 10

    def test_dn_restart_orphans_cached_fds_safely(self, cluster):
        """A DN restart orphans the client's shm mapping (the new registry
        knows nothing of old grants): the liveness channel's EOF must
        invalidate every cached fd for that DN — reads after the restart
        must never be served from a stale mapping."""
        payload = np.random.default_rng(8).integers(
            0, 256, size=120_000, dtype=np.uint8).tobytes()
        with cluster.client("scrs") as c:
            c.write("/sc/rs", payload, scheme="direct")
            assert c.read("/sc/rs") == payload
            assert c.read("/sc/rs") == payload   # cached fd in play
            loc = c._nn.call("get_block_locations", path="/sc/rs")
            holder = loc["blocks"][0]["locations"][0]["dn_id"]
            i = int(holder.split("-")[1])
            cluster.stop_datanode(i)
            cluster.restart_datanode(i)
            time.sleep(0.5)
            c.append("/sc/rs", b"NEW")
            assert c.read("/sc/rs") == payload + b"NEW", \
                "stale cached fd survived the DN restart"
            snap = metrics.registry("shortcircuit").snapshot()["counters"]
            assert snap.get("shm_channels_lost", 0) > 0, \
                "liveness channel never signaled the restart"

    def test_reduced_block_falls_back_to_tcp(self, cluster):
        payload = (b"abcd" * 50_000)
        with cluster.client("sc2") as c:
            c.write("/sc/r", payload, scheme="dedup_lz4")
            assert c.read("/sc/r") == payload  # metadata-only -> TCP path

    def test_fd_passing_requires_token_when_enabled(self, cluster):
        """With block tokens enabled, REQUEST_SHORT_CIRCUIT_FDS must verify a
        READ token like the TCP path does — any local process reaching
        sc.sock must not read arbitrary blocks (DataXceiver's
        requestShortCircuitFds gate)."""
        import os

        from hdrf_tpu.server import shortcircuit as scmod

        payload = b"tok" * 50_000
        with cluster.client("sctok") as c:
            c.write("/sc/t", payload, scheme="direct")
            loc = c._nn.call("get_block_locations", path="/sc/t")
            binfo = loc["blocks"][0]
            bid = binfo["block_id"]
            dn_loc = binfo["locations"][0]
            sc_path = dn_loc["sc_path"]
            # enable tokens DN-side (normally keys arrive via heartbeat)
            key = os.urandom(32)
            for d in cluster.datanodes:
                if d is not None:
                    d.tokens.update_keys([key])
            assert scmod.read_local(sc_path, bid, 0, 100) is None
            dn = next(d for d in cluster.datanodes
                      if d is not None and d.dn_id == dn_loc["dn_id"])
            tok = dn.tokens.mint(bid, "r")
            assert scmod.read_local(sc_path, bid, 0, 100,
                                    token=tok) == payload[:100]


class TestBlockScanner:
    def test_corrupt_replica_detected_and_rereplicated(self, cluster):
        payload = np.random.default_rng(1).integers(
            0, 256, size=100_000, dtype=np.uint8).tobytes()
        with cluster.client("scan") as c:
            c.write("/scan/f", payload, scheme="direct")
            cluster.wait_for_replication("/scan/f", 2)
            loc = c._nn.call("get_block_locations", path="/scan/f")
            binfo = loc["blocks"][0]
            dn_id = binfo["locations"][0]["dn_id"]
            dn = cluster.datanodes[int(dn_id.split("-")[1])]
            # flip bytes in the on-disk replica
            p = dn.replicas.data_path(binfo["block_id"])
            with open(p, "r+b") as f:
                f.seek(100)
                f.write(b"\xff\xff\xff\xff")
            assert dn.verify_block(binfo["block_id"]) is True
            # push through the scanner's report path and verify NN recovery
            c._nn.call("bad_block", dn_id=dn_id, block_id=binfo["block_id"])
            dn._invalidate(binfo["block_id"])
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                loc2 = c._nn.call("get_block_locations", path="/scan/f")
                locs = {d["dn_id"] for d in loc2["blocks"][0]["locations"]}
                if len(locs) >= 2 and dn_id not in locs or len(locs) >= 2:
                    break
                time.sleep(0.2)
            assert c.read("/scan/f") == payload

    def test_clean_replica_passes(self, cluster):
        with cluster.client("scan2") as c:
            c.write("/scan/ok", b"y" * 50_000, scheme="dedup_lz4")
            loc = c._nn.call("get_block_locations", path="/scan/ok")
            binfo = loc["blocks"][0]
            dn = cluster.datanodes[
                int(binfo["locations"][0]["dn_id"].split("-")[1])]
            assert dn.verify_block(binfo["block_id"]) is False


class TestHttpGateway:
    def test_webhdfs_surface(self, cluster):
        from hdrf_tpu.server.http_gateway import HttpGateway

        gw = HttpGateway(cluster.namenode.addr).start()
        try:
            base = f"http://{gw.addr[0]}:{gw.addr[1]}"

            def put(path_q: str, data: bytes = b"") -> dict:
                req = urllib.request.Request(base + path_q, data=data,
                                             method="PUT")
                with urllib.request.urlopen(req) as r:
                    return json.loads(r.read())

            def get(path_q: str) -> bytes:
                with urllib.request.urlopen(base + path_q) as r:
                    return r.read()

            assert put("/webhdfs/v1/web/d?op=MKDIRS")["boolean"]
            payload = b"hello web " * 10_000
            # two-step CREATE (WebHdfsFileSystem redirect dance): ask for
            # the data location, then PUT the bytes there
            loc = put("/webhdfs/v1/web/f?op=CREATE&scheme=lz4"
                      "&noredirect=true")["Location"]
            assert "step=2" in loc
            put(loc[loc.index("/webhdfs"):], payload)
            st = json.loads(get("/webhdfs/v1/web/f?op=GETFILESTATUS"))
            assert st["FileStatus"]["length"] == len(payload)
            assert get("/webhdfs/v1/web/f?op=OPEN") == payload
            assert get("/webhdfs/v1/web/f?op=OPEN&offset=6&length=3") == \
                payload[6:9]
            ls = json.loads(get("/webhdfs/v1/web?op=LISTSTATUS"))
            names = {e["name"] for e in ls["FileStatuses"]["FileStatus"]}
            assert names == {"d", "f"}
            assert put("/webhdfs/v1/web/f?op=RENAME&destination=/web/g")[
                "boolean"]
            status = json.loads(get("/status"))
            assert status["live"] == 3
            req = urllib.request.Request(
                base + "/webhdfs/v1/web/g?op=DELETE", method="DELETE")
            with urllib.request.urlopen(req) as r:
                assert json.loads(r.read())["boolean"]
            # HTML explorer renders the namespace
            page = get("/explorer?path=/web").decode()
            assert "hdrf_tpu" in page and "d/" in page
            # errors surface as structured JSON
            try:
                get("/webhdfs/v1/nope?op=GETFILESTATUS")
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            gw.stop()

    def test_web_ui_pages_render_live_cluster_data(self, cluster):
        """dfshealth/datanode/journal dashboards (webapps/{hdfs,datanode,
        journal} analogs) render real cluster state, not placeholders."""
        from hdrf_tpu.server.http_gateway import HttpGateway

        gw = HttpGateway(cluster.namenode.addr).start()
        try:
            base = f"http://{gw.addr[0]}:{gw.addr[1]}"

            def get(path_q: str) -> str:
                with urllib.request.urlopen(base + path_q) as r:
                    return r.read().decode()

            with cluster.client("ui") as c:
                c.write("/ui/f", b"ui bytes " * 30_000, scheme="dedup_lz4")
            # NN overview: role, safemode off, all DNs listed live
            page = get("/dfshealth")
            assert "active" in page and "safemode" in page
            assert ">3 live / 0 dead / 0 decommissioning<" in page
            for i in range(3):
                assert f"dn-{i}" in page
            # per-DN page: block count + index stats from heartbeat stats
            dn_page = get("/datanode?id=dn-0")
            assert "dn-0" in dn_page and "logical bytes" in dn_page
            assert get("/datanode?id=nope").count("unknown datanode") == 1
            # journal page: this cluster runs the shared-dir transport
            jp = get("/journal")
            assert "Journal" in jp and "seq" in jp
            # the root path serves the overview too
            assert "NameNode" in get("/")
        finally:
            gw.stop()


class TestVolumeChecker:
    def test_probe_and_fatal_shutdown(self, tmp_path):
        from hdrf_tpu.config import DataNodeConfig
        from hdrf_tpu.server.datanode import DataNode
        from hdrf_tpu.server.namenode import NameNode
        from hdrf_tpu.config import NameNodeConfig

        nn = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "nn"))).start()
        try:
            cfg = DataNodeConfig(data_dir=str(tmp_path / "dn"),
                                 volume_check_interval_s=0)  # manual probes
            dn = DataNode(cfg, nn.addr, dn_id="dn-vol").start()
            try:
                assert dn.check_volume() is True
                # simulate volume death: the dir vanishes out from under the
                # DN (root ignores permission bits, so chmod won't do)
                dn.config.data_dir = str(tmp_path / "gone")
                assert dn.check_volume() is False
            finally:
                dn.stop()
        finally:
            nn.stop()


class TestSimulatedDataset:
    def test_protocol_flow_without_disk(self, tmp_path):
        from hdrf_tpu.config import DataNodeConfig, NameNodeConfig
        from hdrf_tpu.server.datanode import DataNode
        from hdrf_tpu.server.namenode import NameNode
        from hdrf_tpu.client.filesystem import HdrfClient
        from hdrf_tpu.config import ClientConfig

        nn = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "nn"),
                                     replication=1,
                                     block_size=256 * 1024)).start()
        try:
            cfg = DataNodeConfig(data_dir=str(tmp_path / "dn"),
                                 simulated_dataset=True)
            dn = DataNode(cfg, nn.addr, dn_id="dn-sim").start()
            try:
                payload = b"simulated!" * 30_000
                # short-circuit is unavailable on the RAM dataset
                ccfg = ClientConfig(short_circuit=False)
                with HdrfClient(nn.addr, config=ccfg, name="sim") as c:
                    c.write("/sim/f", payload, scheme="direct")
                    assert c.read("/sim/f") == payload
                assert dn.replicas.physical_bytes() == len(payload)
                import os
                assert not os.listdir(os.path.join(cfg.data_dir)) or \
                    "replicas" not in os.listdir(cfg.data_dir)
            finally:
                dn.stop()
        finally:
            nn.stop()


class TestInotify:
    def test_event_stream(self, cluster):
        with cluster.client("ev") as c:
            start = c._nn.call("get_events")["last_seq"]
            c.mkdir("/ev/d")
            c.write("/ev/f", b"x" * 1000)
            c.rename("/ev/f", "/ev/g")
            c.delete("/ev/g")
            resp = c._nn.call("get_events", since_seq=start)
            kinds = [(e["type"], e["path"]) for e in resp["events"]]
            assert ("mkdir", "/ev/d") in kinds
            assert ("create", "/ev/f") in kinds
            assert ("close", "/ev/f") in kinds
            assert ("unlink", "/ev/g") in kinds
            rn = [e for e in resp["events"] if e["type"] == "rename"]
            assert rn and rn[0]["dst"] == "/ev/g"


class TestBlockTokens:
    def test_tokens_enforced_end_to_end(self, tmp_path):
        import socket as _socket

        from hdrf_tpu.testing.minicluster import MiniCluster
        from hdrf_tpu.proto import datatransfer as dt
        from hdrf_tpu.proto.rpc import recv_frame

        with MiniCluster(n_datanodes=3, replication=2) as mc:
            mc.nn_config.block_tokens = True  # too late for this NN; restart
            mc.restart_namenode()
            mc.wait_for_datanodes(3)
            time.sleep(0.5)  # let heartbeats deliver the block keys
            payload = b"secret" * 30_000
            with mc.client("tok") as c:
                c.write("/sec/f", payload)
                assert c.read("/sec/f") == payload  # tokens flow end-to-end
                loc = c._nn.call("get_block_locations", path="/sec/f")
                binfo = loc["blocks"][0]
                addr = tuple(binfo["locations"][0]["addr"])
                # no token -> rejected
                s = _socket.create_connection(addr, timeout=10)
                try:
                    dt.send_op(s, dt.READ_BLOCK, block_id=binfo["block_id"],
                               offset=0, length=-1)
                    try:
                        hdr = recv_frame(s)
                        raise AssertionError(f"served without token: {hdr}")
                    except (ConnectionError, OSError):
                        pass  # DN dropped the unauthorized connection
                finally:
                    s.close()
                # tampered token -> rejected
                bad = dict(binfo["token"])
                bad["modes"] = "rw"
                s = _socket.create_connection(addr, timeout=10)
                try:
                    dt.send_op(s, dt.READ_BLOCK, block_id=binfo["block_id"],
                               offset=0, length=-1, token=bad)
                    try:
                        recv_frame(s)
                        raise AssertionError("served with tampered token")
                    except (ConnectionError, OSError):
                        pass
                finally:
                    s.close()

    def test_ec_with_tokens(self, tmp_path):
        import numpy as np

        from hdrf_tpu.testing.minicluster import MiniCluster

        with MiniCluster(n_datanodes=5, block_size=64 * 1024) as mc:
            mc.nn_config.block_tokens = True
            mc.restart_namenode()
            mc.wait_for_datanodes(5)
            time.sleep(0.5)
            data = np.random.default_rng(9).integers(
                0, 256, 120_000, dtype=np.uint8).tobytes()
            with mc.client("ectok") as c:
                c.write("/sec/ec", data, ec="rs-3-2-4k")
                mc.stop_datanode(0)
                assert c.read("/sec/ec") == data  # degraded read with tokens


class TestSlowPeers:
    def test_slow_peer_reports_aggregate(self):
        """Per-peer transfer latencies ride heartbeats; the NN flags the
        3x-median outlier (SlowPeerTracker.java:56 analog)."""
        import time

        import numpy as np

        from hdrf_tpu.testing.minicluster import MiniCluster

        rng = np.random.default_rng(71)
        with MiniCluster(n_datanodes=3, replication=2,
                         block_size=1 << 20) as mc:
            with mc.client("sp") as c:
                for i in range(4):
                    c.write(f"/sp/f{i}",
                            rng.integers(0, 256, size=200_000,
                                         dtype=np.uint8).tobytes())
            # synthesize a pathological peer: dn-2 reported slow by others
            for dn in mc.datanodes[:2]:
                for _ in range(8):
                    dn.note_peer_latency("dn-2", 50.0)  # 50 s/MB
            deadline = time.time() + 12  # generous: CI hosts load-spike
            while time.time() < deadline:
                rep = mc.namenode.rpc_slow_peers()
                if "dn-2" in rep["slow_peers"]:
                    break
                time.sleep(0.3)
            else:
                import pytest

                pytest.fail(f"slow peer never flagged: {rep}")
            assert rep["slow_peers"]["dn-2"]["reporters"] >= 2

    def test_direct_writes_sample_peer_latency(self):
        """The stock direct pipeline's mirror leg produces organic latency
        samples (downstream write + ack-drain time only), so the detector
        is not blind when no reduced-scheme traffic flows — and healthy
        peers are NOT flagged (no false positives from the absolute rule)."""
        import time

        import numpy as np

        from hdrf_tpu.testing.minicluster import MiniCluster

        rng = np.random.default_rng(72)
        with MiniCluster(n_datanodes=3, replication=2,
                         block_size=1 << 20) as mc:
            with mc.client("sp2") as c:
                for i in range(4):
                    c.write(f"/sp2/f{i}",
                            rng.integers(0, 256, size=200_000,
                                         dtype=np.uint8).tobytes())
            # DN-side: some head DN recorded a sample about its mirror target
            assert any(dn._peer_report() for dn in mc.datanodes), \
                "direct writes produced zero peer-latency samples"
            # ... and it reaches the NN through heartbeat stats
            deadline = time.time() + 6
            while time.time() < deadline:
                rep = mc.namenode.rpc_slow_peers()
                if rep.get("reports"):
                    break
                time.sleep(0.3)
            assert rep.get("reports"), \
                f"no peer reports reached the NN: {rep}"
            assert rep["slow_peers"] == {}, \
                f"healthy peers falsely flagged: {rep}"


class TestLifeline:
    def test_lifeline_keeps_stalled_dn_alive(self):
        """DatanodeLifelineProtocol analog: a DN whose full heartbeats
        stall (busy service actor) keeps sending cheap lifelines, so the
        NN never declares it dead and never mass-re-replicates."""
        from hdrf_tpu.utils import fault_injection

        with MiniCluster(n_datanodes=2, replication=2, heartbeat_s=0.2,
                         dead_node_s=1.2) as mc:
            dn = mc.datanodes[0]

            def stall(**kw):
                if kw.get("dn_id") == dn.dn_id:
                    raise RuntimeError("simulated service-actor stall")

            fault_injection.install("datanode.heartbeat", stall)
            try:
                time.sleep(2.5)   # well past the dead-node interval
                report = mc.namenode.rpc_datanode_report()
                me = next(d for d in report if d["dn_id"] == dn.dn_id)
                assert me["alive"], "lifelines failed to keep the DN alive"
                assert metrics.registry("datanode").snapshot()[
                    "counters"].get("lifelines_sent", 0) > 0
                assert metrics.registry("namenode").snapshot()[
                    "counters"].get("lifelines", 0) > 0
            finally:
                fault_injection.remove("datanode.heartbeat")

    def test_lifeline_idle_when_heartbeats_flow(self):
        with MiniCluster(n_datanodes=1, replication=1,
                         heartbeat_s=0.2) as mc:
            before = metrics.registry("datanode").snapshot()[
                "counters"].get("lifelines_sent", 0)
            time.sleep(1.2)
            after = metrics.registry("datanode").snapshot()[
                "counters"].get("lifelines_sent", 0)
            assert after == before, "lifeline fired while heartbeats flow"
