"""Degraded-mode resilience spine: deadlines, retry budgets, circuit
breakers, worker failover (the fault matrix for utils/retry.py and the
paths rewired onto it — the reference's RetryPolicies.java:153 /
RetryInvocationHandler.java:88 behaviors the fork's reduction path lacked).

Every breaker/deadline state transition here is driven by INJECTED clocks
(the utils/outlier.py convention): no wall-clock sleeps gate an assertion.
The only time-bounded waits are heartbeat-propagation polls, which follow
the MiniCluster wait_for_* idiom.
"""

from __future__ import annotations

import random
import socket
import threading
import time

import numpy as np
import pytest

from hdrf_tpu.config import CdcConfig, NameNodeConfig
from hdrf_tpu.server.namenode import NameNode
from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.utils import fault_injection, metrics, retry

RNG = np.random.default_rng(77)


def _bytes(n):
    return RNG.integers(0, 256, size=n, dtype=np.uint8).tobytes()


class Boom(Exception):
    pass


@pytest.fixture(autouse=True)
def _fresh_breakers():
    retry.reset_breakers()
    yield
    retry.reset_breakers()
    fault_injection.clear()


# --------------------------------------------------------------- unit: budget


class TestDeadline:
    def test_fake_clock_lifecycle(self):
        t = [0.0]
        d = retry.Deadline(5.0, clock=lambda: t[0])
        assert d.remaining() == 5.0 and not d.expired
        t[0] = 4.0
        d.check("op")  # 1 s left: fine
        assert d.timeout() == pytest.approx(1.0)
        assert d.timeout(cap_s=0.25) == 0.25
        d.extend(2.0)  # budget accrual (streamed-MiB shape)
        t[0] = 6.5
        assert not d.expired
        t[0] = 7.0
        assert d.expired and d.remaining() == 0.0 and d.header() == 0.0
        with pytest.raises(retry.DeadlineExceeded):
            d.check("op")

    def test_ambient_bind_and_clamp(self):
        assert retry.current() is None
        assert retry.remaining_header() is None
        assert retry.effective_budget(60.0) == 60.0  # unclamped
        t = [0.0]
        with retry.bind(retry.Deadline(10.0, clock=lambda: t[0])) as d:
            assert retry.current() is d
            # local per-op budget may never outlive the end-to-end budget
            assert retry.effective_budget(60.0) == pytest.approx(10.0)
            assert retry.effective_budget(3.0) == 3.0
            assert retry.remaining_header() == pytest.approx(10.0)
        assert retry.current() is None

    def test_bind_remaining_rebinds_against_local_clock(self):
        t = [1000.0]  # a clock wildly different from the sender's
        with retry.bind_remaining(2.5, clock=lambda: t[0]) as d:
            assert d.remaining() == pytest.approx(2.5)
            t[0] = 1002.0
            assert d.remaining() == pytest.approx(0.5)
        with retry.bind_remaining(None) as d:
            assert d is None and retry.current() is None


class TestBackoffAndRetries:
    def test_full_jitter_bounds(self):
        delays = list(retry.backoff_delays(
            6, base_s=1.0, cap_s=4.0, rng=random.Random(7)))
        assert len(delays) == 6
        for i, d in enumerate(delays):
            assert 0.0 <= d <= min(4.0, 2.0 ** i)

    def test_call_with_retries_recovers(self):
        calls, slept = [], []
        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("nope")
            return "ok"
        out = retry.call_with_retries(flaky, attempts=3,
                                      sleep=slept.append,
                                      rng=random.Random(1))
        assert out == "ok" and len(calls) == 3 and len(slept) == 2

    def test_exhausted_attempts_raise_last(self):
        def always():
            raise ConnectionError("down")
        with pytest.raises(ConnectionError, match="down"):
            retry.call_with_retries(always, attempts=2, sleep=lambda s: None)

    def test_spent_budget_short_circuits(self):
        t = [0.0]
        calls = []
        with retry.bind(retry.Deadline(0.0, clock=lambda: t[0])):
            with pytest.raises(retry.DeadlineExceeded):
                retry.call_with_retries(lambda: calls.append(1), attempts=3,
                                        sleep=lambda s: None)
        assert calls == []  # refused BEFORE running the op


# -------------------------------------------------------- unit: breaker state


class TestCircuitBreaker:
    def test_state_machine_with_injected_clock(self):
        t = [0.0]
        b = retry.CircuitBreaker("edge", failure_threshold=2, reset_s=10.0,
                                 clock=lambda: t[0])
        assert b.state == "closed" and b.allow()
        b.record_failure()
        assert b.state == "closed"  # 1 < threshold
        b.record_failure()
        assert b.state == "open" and not b.allow()
        with pytest.raises(retry.BreakerOpen):
            b.check()
        t[0] = 9.99
        assert b.state == "open"
        t[0] = 10.0
        assert b.state == "half_open"
        assert b.allow()       # THE probe
        assert not b.allow()   # only one probe admitted
        b.record_failure()     # probe failed -> straight back to open
        assert b.state == "open"
        t[0] = 20.0
        assert b.allow()       # half-open again, probe admitted
        b.record_success()
        assert b.state == "closed" and b.allow() and b.allow()

    def test_success_resets_consecutive_failures(self):
        b = retry.CircuitBreaker("edge2", failure_threshold=3)
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"  # streak broken: 2 < 3 consecutive

    def test_registry_is_per_edge_and_first_params_win(self):
        b1 = retry.breaker("dn-x->worker", failure_threshold=5)
        b2 = retry.breaker("dn-x->worker", failure_threshold=9)
        assert b1 is b2 and b1.failure_threshold == 5
        assert "dn-x->worker" in retry.all_breakers()
        m = metrics.registry("resilience").snapshot()["gauges"]
        assert m.get("breaker_state.dn-x->worker") == 0  # exported closed

    def test_transition_counters_exported(self):
        reg = metrics.registry("resilience")
        opened0 = reg.counter("breaker_open_total")
        closed0 = reg.counter("breaker_close_total")
        t = [0.0]
        b = retry.CircuitBreaker("edge3", failure_threshold=1, reset_s=1.0,
                                 clock=lambda: t[0])
        b.record_failure()
        t[0] = 1.0
        assert b.allow()
        b.record_success()
        assert reg.counter("breaker_open_total") == opened0 + 1
        assert reg.counter("breaker_close_total") == closed0 + 1


# ------------------------------------------------------------- rpc deadlines


class TestRpcDeadlines:
    def test_server_refuses_spent_budget_before_dispatch(self, tmp_path):
        from hdrf_tpu.proto.rpc import RpcClient, RpcError

        nn = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "n"))).start()
        try:
            c = RpcClient(nn.addr)
            # the rejection counter lives in the RPC layer's own registry
            # (rpc.py:90 — rpc.{name}), not the service's
            rejected0 = metrics.registry("rpc.namenode").counter(
                "mkdir_deadline_rejected")
            with pytest.raises(RpcError, match="DeadlineExceeded"):
                c.call("mkdir", path="/late", _deadline=0.0)
            assert metrics.registry("rpc.namenode").counter(
                "mkdir_deadline_rejected") == rejected0 + 1
            # the handler never ran
            assert not any(e["name"] == "late"
                           for e in nn.rpc_listing("/"))
            c.call("mkdir", path="/ok", _deadline=30.0)  # sane budget: runs
            assert any(e["name"] == "ok" for e in nn.rpc_listing("/"))
            c.close()
        finally:
            nn.stop()

    def test_client_refuses_spent_ambient_budget(self, tmp_path):
        from hdrf_tpu.proto.rpc import RpcClient

        nn = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "n"))).start()
        try:
            c = RpcClient(nn.addr)
            t = [0.0]
            with retry.bind(retry.Deadline(0.0, clock=lambda: t[0])):
                with pytest.raises(retry.DeadlineExceeded):
                    c.call("mkdir", path="/never")
            assert not any(e["name"] == "never"
                           for e in nn.rpc_listing("/"))
            c.close()
        finally:
            nn.stop()


# ------------------------------------------------- hung worker: deadline caps


class _HangingServer:
    """Accepts connections and never responds (a wedged codec process)."""

    def __init__(self):
        self._srv = socket.socket()
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(16)
        self.addr = self._srv.getsockname()
        self._conns: list[socket.socket] = []
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            try:
                c, _ = self._srv.accept()
            except OSError:
                return
            self._conns.append(c)

    def close(self):
        try:
            self._srv.close()
        except OSError:
            pass
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass


class TestHungWorkerDeadline:
    def test_client_unblocks_within_budget(self):
        """Satellite: the old hard-coded 600 s timeout is gone — a hung
        worker costs at most the configured payload-scaled budget."""
        from hdrf_tpu.server.reduction_worker import WorkerClient, WorkerError

        hang = _HangingServer()
        try:
            c = WorkerClient(hang.addr, deadline_s=0.6,
                             deadline_s_per_mb=0.0)
            t0 = time.monotonic()
            with pytest.raises((WorkerError, retry.DeadlineExceeded)):
                c.reduce(_bytes(200_000), CdcConfig())
            assert time.monotonic() - t0 < 30.0  # not 600 s
            c.close()
        finally:
            hang.close()

    def test_write_path_unblocks_and_degrades(self):
        """A DN pointed at a hung worker: the dedup write must complete via
        in-process passthrough within the deadline budget, not hang."""
        hang = _HangingServer()
        try:
            with MiniCluster(
                    n_datanodes=1, replication=1, block_size=1 << 20,
                    reduction_overrides={
                        "worker_addr": list(hang.addr),
                        "worker_deadline_s": 0.6,
                        "worker_deadline_s_per_mb": 0.0,
                        # keep the breaker out of THIS test's way
                        "worker_breaker_failures": 100}) as mc:
                br = metrics.registry("block_receiver")
                fallbacks0 = br.counter("worker_fallbacks")
                degraded0 = br.counter("degraded_writes")
                data = _bytes(400_000)
                t0 = time.monotonic()
                with mc.client("hung") as c:
                    c.write("/hung/f", data, scheme="dedup_lz4")
                    assert c.read("/hung/f") == data
                assert time.monotonic() - t0 < 60.0
                assert br.counter("worker_fallbacks") > fallbacks0
                assert br.counter("degraded_writes") > degraded0
        finally:
            hang.close()


# ------------------------------------- acceptance: kill -9 / breaker / probe


class TestWorkerFailover:
    def test_kill9_breaker_opens_then_halfopen_recovers(self):
        """The fault matrix end to end: kill -9 the reduction worker
        mid-write -> the write completes via passthrough with zero data
        loss; the breaker opens after the configured failure count and
        subsequent writes make NO worker connect attempts; restarting the
        worker and advancing the breaker's injected clock past reset_s
        re-admits the edge (half-open probe -> closed, reduction back on).
        """
        br = metrics.registry("block_receiver")
        wm = metrics.registry("reduction_worker")
        with MiniCluster(
                n_datanodes=1, replication=1, block_size=1 << 20,
                tpu_worker=True,
                reduction_overrides={
                    "worker_deadline_s": 20.0,
                    "worker_breaker_failures": 2,
                    # effectively never on the wall clock; the test drives
                    # half-open by moving the breaker's injected clock
                    "worker_breaker_reset_s": 3600.0}) as mc:
            dn = mc.datanodes[0]
            breaker = dn._worker_breaker
            assert breaker is not None and breaker.state == "closed"

            # --- healthy baseline: the worker serves the reduce
            reduces0 = br.counter("worker_reduces")
            a = _bytes(400_000)
            with mc.client("fo") as c:
                c.write("/fo/a", a, scheme="dedup_lz4")
                assert c.read("/fo/a") == a
            assert br.counter("worker_reduces") == reduces0 + 1

            # --- kill -9 MID-WRITE: first packet of the next block
            fired = threading.Event()

            def kill_once(**kw):
                if not fired.is_set():
                    fired.set()
                    mc.kill_worker()

            b = _bytes(400_000)
            fallbacks0 = br.counter("worker_fallbacks")
            degraded0 = br.counter("degraded_writes")
            with fault_injection.inject("block_receiver.packet", kill_once):
                with mc.client("fo") as c:
                    c.write("/fo/b", b, scheme="dedup_lz4")
                    assert c.read("/fo/b") == b  # zero data loss
            assert fired.is_set()
            assert br.counter("worker_fallbacks") == fallbacks0 + 1
            assert br.counter("degraded_writes") == degraded0 + 1
            assert breaker.state == "closed"  # 1 failure < threshold 2

            # --- second failure (connect refused): breaker opens
            c2 = _bytes(300_000)
            with mc.client("fo") as c:
                c.write("/fo/c", c2, scheme="dedup_lz4")
                assert c.read("/fo/c") == c2
            assert breaker.state == "open"
            assert dn.reduction_degraded

            # --- open breaker: degraded writes make ZERO connect attempts
            attempts0 = wm.counter("connect_attempts")
            d = _bytes(300_000)
            with mc.client("fo") as c:
                c.write("/fo/d", d, scheme="dedup_lz4")
                assert c.read("/fo/d") == d
            assert wm.counter("connect_attempts") == attempts0
            assert metrics.registry("resilience").snapshot()["gauges"][
                f"breaker_state.{breaker.name}"] == 2  # open, exported

            # --- degradation reaches the NN within a couple of heartbeats
            with mc.client("fo") as c:
                deadline = time.monotonic() + 10.0
                cs = {}
                while time.monotonic() < deadline:
                    cs = c._nn.call("cluster_status")
                    if cs.get("reduction_degraded"):
                        break
                    time.sleep(0.05)
                assert cs.get("reduction_degraded") == 1
                assert cs.get("degraded_nodes") == [dn.dn_id]

            # --- restart the worker; drive half-open by the injected clock
            mc.restart_worker()
            breaker._opened_at = breaker._clock() - breaker.reset_s - 1.0
            assert breaker.state == "half_open"
            reduces1 = br.counter("worker_reduces")
            e = _bytes(300_000)
            with mc.client("fo") as c:
                c.write("/fo/e", e, scheme="dedup_lz4")  # the probe
                assert c.read("/fo/e") == e
            assert breaker.state == "closed"  # probe succeeded: re-closed
            assert br.counter("worker_reduces") == reduces1 + 1
            assert not dn.reduction_degraded

            # earlier degraded files still read back intact
            with mc.client("fo") as c:
                assert c.read("/fo/b") == b
                assert c.read("/fo/c") == c2


# ----------------------------------------- mirror failures reach the NN view


class TestMirrorFailureReporting:
    def test_broken_mirror_flagged_within_two_heartbeats(self):
        """Satellite: a mirror push that breaks outright rides the NEXT
        heartbeat as per-peer ``mirror_failures`` and the NN flags the peer
        in slow_peers with rule=mirror_failure — broken beats slow."""
        with MiniCluster(n_datanodes=2, replication=2,
                         block_size=1 << 20) as mc:
            data = _bytes(300_000)
            # only the mirror leg uses op "write_reduced" (client writes use
            # WRITE_BLOCK), so this breaks exactly the mirror ingest —
            # whichever DN the NN picked as the pipeline head
            with fault_injection.inject(
                    "datanode.op",
                    lambda **kw: ((_ for _ in ()).throw(Boom())
                                  if kw.get("op") == "write_reduced"
                                  else None)):
                with mc.client("mf") as c:
                    c.write("/mf/f", data, scheme="dedup_lz4")
                    assert c.read("/mf/f") == data  # primary replica serves
            flagged = {peer: n for dn in mc.datanodes if dn is not None
                       for peer, n in dn._mirror_fail.items()}
            assert flagged, "primary never attributed the broken mirror"
            with mc.client("mf") as c:
                deadline = time.monotonic() + 10.0
                health = {}
                while time.monotonic() < deadline:
                    health = c._nn.call("slow_nodes_report")
                    if health.get("mirror_failures"):
                        break
                    time.sleep(0.05)
                assert health.get("mirror_failures"), \
                    "mirror failure never reached the NN health report"
                for peer, n in health["mirror_failures"].items():
                    assert peer in flagged and n >= 1
                    assert peer in health["slow_peers"]
                    assert health["slow_peers"][peer][
                        "mirror_failures"] >= 1


# --------------------------------------------------- crash-ordering matrices


def h(i: int) -> bytes:
    return bytes([i]) * 32


class TestIndexCrashOrdering:
    def test_wal_append_crash_leaves_memory_untouched(self, tmp_path):
        """Log-before-apply: a failed WAL append must not mutate memory, and
        the retried commit must land EXACTLY once (refcount == 1)."""
        from hdrf_tpu.index.chunk_index import ChunkIndex

        idx = ChunkIndex(str(tmp_path))
        with fault_injection.inject(
                "index.wal_append",
                lambda **kw: (_ for _ in ()).throw(OSError("disk full"))):
            with pytest.raises(OSError, match="disk full"):
                idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
        assert not idx.has_block(1)
        assert idx.chunk_location(h(1)) is None
        idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})  # retry
        assert idx.chunk_location(h(1)).refcount == 1  # not double-applied
        idx.close()
        idx2 = ChunkIndex(str(tmp_path))  # crash-restart replay agrees
        assert idx2.chunk_location(h(1)).refcount == 1
        idx2.close()

    def test_wal_append_crash_preserves_prior_blocks(self, tmp_path):
        from hdrf_tpu.index.chunk_index import ChunkIndex

        idx = ChunkIndex(str(tmp_path))
        idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
        with fault_injection.inject(
                "index.wal_append",
                lambda **kw: (_ for _ in ()).throw(Boom())):
            with pytest.raises(Boom):
                idx.commit_block(2, 20, [h(2)], {h(2): (0, 10, 20)})
        idx.close()  # simulate death; reopen from WAL
        idx2 = ChunkIndex(str(tmp_path))
        assert idx2.has_block(1) and not idx2.has_block(2)  # no lost chunks
        assert idx2.chunk_location(h(1)).refcount == 1
        idx2.commit_block(2, 20, [h(2)], {h(2): (0, 10, 20)})
        assert idx2.chunk_location(h(2)).refcount == 1
        idx2.close()

    def test_auto_checkpoint_post_crash_no_double_apply(self, tmp_path):
        """Crash at the AUTO-triggered checkpoint's post_checkpoint window
        (publish done, WAL truncation lost): seqno filtering must keep
        replay idempotent — refcounts exact, nothing lost."""
        from hdrf_tpu.index.chunk_index import ChunkIndex

        idx = ChunkIndex(str(tmp_path), checkpoint_every=2)
        idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
        with fault_injection.inject(
                "index.post_checkpoint",
                lambda **kw: (_ for _ in ()).throw(Boom())):
            with pytest.raises(Boom):
                # 2nd commit trips the every-2 checkpoint; the record itself
                # was logged AND applied before the checkpoint crashed
                idx.commit_block(2, 20, [h(2)], {h(2): (0, 10, 20)})
        idx.close()
        idx2 = ChunkIndex(str(tmp_path))
        assert idx2.chunk_location(h(1)).refcount == 1  # not inflated
        assert idx2.chunk_location(h(2)).refcount == 1
        assert idx2.delete_block(1) == [h(1)]
        assert idx2.delete_block(2) == [h(2)]
        idx2.close()

    def test_torn_final_wal_record_dropped_after_checkpoint(self, tmp_path):
        """Checkpoint + intact WAL records + a TORN final record: recovery
        keeps everything up to the tear and drops only the torn tail."""
        from hdrf_tpu.index.chunk_index import ChunkIndex

        idx = ChunkIndex(str(tmp_path))
        idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
        idx.checkpoint()
        idx.commit_block(2, 20, [h(2)], {h(2): (0, 10, 20)})
        idx.commit_block(3, 30, [h(3)], {h(3): (0, 30, 30)})
        idx.close()
        wal = tmp_path / "index.wal"
        wal.write_bytes(wal.read_bytes()[:-3])  # crash mid-append of blk 3
        idx2 = ChunkIndex(str(tmp_path))
        assert idx2.has_block(1) and idx2.has_block(2)
        assert not idx2.has_block(3)  # torn record dropped, not corrupted
        assert idx2.chunk_location(h(1)).refcount == 1
        assert idx2.chunk_location(h(2)).refcount == 1
        idx2.commit_block(3, 30, [h(3)], {h(3): (0, 30, 30)})  # log continues
        assert idx2.has_block(3)
        idx2.close()


class TestDaemonLoopFaults:
    def test_namenode_monitor_survives_injected_fault(self):
        """The supervision loops are themselves resilient: a raising
        monitor tick is accounted (monitor_errors) and the NEXT tick runs —
        dead-node detection keeps working after the fault clears."""
        with MiniCluster(n_datanodes=1, replication=1, heartbeat_s=0.1,
                         dead_node_s=0.6) as mc:
            errors0 = metrics.registry("namenode").counter("monitor_errors")
            ticks = threading.Event()

            def boom(**kw):
                ticks.set()
                raise Boom()

            with fault_injection.inject("namenode.monitor_tick", boom):
                assert ticks.wait(5.0), "monitor never ticked"
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline and metrics.registry(
                        "namenode").counter("monitor_errors") <= errors0:
                    time.sleep(0.02)
            assert metrics.registry("namenode").counter(
                "monitor_errors") > errors0
            mc.kill_datanode(0)  # post-fault: the loop still declares death
            with mc.client("mt") as c:
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    if all(not d["alive"] for d in c.datanode_report()):
                        break
                    time.sleep(0.05)
                assert all(not d["alive"] for d in c.datanode_report())

    def test_one_journalnode_append_fault_quorum_survives(self):
        """A single JN append failure must not fail the edit: 2/3 acks."""
        fired = threading.Event()

        def crash_once(**kw):
            if not fired.is_set():
                fired.set()
                raise OSError("jn disk error")

        with MiniCluster(n_datanodes=1, replication=1,
                         journal_nodes=3) as mc:
            with fault_injection.inject("journalnode.append", crash_once):
                with mc.client("jn") as c:
                    c.mkdir("/jn/survives")
                    assert any(e["name"] == "survives"
                               for e in c.ls("/jn"))
            assert fired.is_set()

    def test_replica_finalize_crash_client_retries(self):
        """Crash in the finalize window (data fsync'd, meta not yet
        written): the pipeline aborts and the client's block-granular
        retry lands the write — zero data loss on read-back."""
        fired = threading.Event()

        def crash_once(**kw):
            if not fired.is_set():
                fired.set()
                raise Boom()

        data = _bytes(200_000)
        with MiniCluster(n_datanodes=2, replication=1) as mc:
            with fault_injection.inject("replica.finalize", crash_once):
                with mc.client("rf") as c:
                    c.write("/rf/f", data, scheme="direct")
                    assert c.read("/rf/f") == data
            assert fired.is_set()


class TestContainerSealCrash:
    def test_seal_crash_loses_no_chunks(self, tmp_path):
        """Crash inside seal (before the sealed file is published): the raw
        container must survive, every chunk stays readable, and a retried
        seal completes."""
        import os

        from hdrf_tpu.storage.container_store import ContainerStore

        store = ContainerStore(str(tmp_path), container_size=1 << 20,
                               lanes=1)
        chunks = [_bytes(3000) for _ in range(5)]
        locs = store.append_chunks(chunks)
        cid = locs[0][0]
        with fault_injection.inject(
                "container.seal",
                lambda **kw: (_ for _ in ()).throw(Boom())):
            with pytest.raises(Boom):
                store.seal(cid)
        assert os.path.exists(store._raw_path(cid))      # raw survived
        assert not os.path.exists(store._sealed_path(cid))
        got = store.read_chunks(locs)
        assert got == chunks                             # no lost chunks
        store.seal(cid)                                  # retry completes
        assert os.path.exists(store._sealed_path(cid))
        assert store.read_chunks(locs) == chunks         # and still serves
