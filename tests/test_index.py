"""ChunkIndex: WAL durability, refcounts, recovery, torn-tail tolerance."""

import os
import struct

import pytest

from hdrf_tpu.index.chunk_index import ChunkIndex


def h(i: int) -> bytes:
    return bytes([i]) * 32


def test_commit_and_lookup(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 100, [h(1), h(2), h(1)],
                     {h(1): (0, 0, 40), h(2): (0, 40, 20)})
    e = idx.get_block(1)
    assert e.logical_len == 100
    assert e.hashes == [h(1), h(2), h(1)]
    locs = idx.lookup_chunks([h(1), h(2), h(3)])
    assert locs[h(1)].refcount == 2  # two references from block 1
    assert locs[h(2)].refcount == 1
    assert locs[h(3)] is None
    idx.close()


def test_cross_block_dedup_refcounts(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 60, [h(1)], {h(1): (0, 0, 60)})
    idx.commit_block(2, 60, [h(1)], {})  # second block reuses the chunk
    assert idx.chunk_location(h(1)).refcount == 2
    assert idx.delete_block(1) == []  # still referenced by block 2
    assert idx.chunk_location(h(1)).refcount == 1
    assert idx.delete_block(2) == [h(1)]  # now dead
    assert idx.chunk_location(h(1)) is None
    idx.close()


def test_commit_validates(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    with pytest.raises(ValueError):
        idx.commit_block(1, 10, [h(9)], {})  # unknown hash, not declared new
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
    idx.close()


def test_concurrent_new_chunk_race_first_commit_wins(tmp_path):
    # Two writers dedup the same never-seen chunk concurrently: both append
    # bytes and declare it new. First commit registers it; second keeps the
    # existing location and is told its copy is an orphan.
    idx = ChunkIndex(str(tmp_path))
    assert idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)}) == []
    losers = idx.commit_block(2, 10, [h(1)], {h(1): (3, 50, 10)})
    assert losers == [h(1)]
    loc = idx.chunk_location(h(1))
    assert (loc.container_id, loc.offset) == (0, 0)  # first commit won
    assert loc.refcount == 2
    idx.close()


def test_checkpoint_crash_before_truncate_is_idempotent(tmp_path):
    # Crash between checkpoint publish and WAL truncation: replay must not
    # double-apply records the checkpoint folded in (refcount inflation).
    from hdrf_tpu.utils import fault_injection

    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})

    class Crash(Exception):
        pass

    with fault_injection.inject("index.post_checkpoint",
                                lambda **kw: (_ for _ in ()).throw(Crash())):
        with pytest.raises(Crash):
            idx.checkpoint()
    idx.close()
    # WAL still holds the blk record AND the checkpoint contains it.
    idx2 = ChunkIndex(str(tmp_path))
    assert idx2.chunk_location(h(1)).refcount == 1  # not inflated to 2
    assert idx2.delete_block(1) == [h(1)]  # chunk correctly dies
    idx2.close()


def test_recovery_from_wal(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 100, [h(1), h(2)], {h(1): (0, 0, 50), h(2): (0, 50, 50)})
    idx.seal_container(0)
    idx.close()

    idx2 = ChunkIndex(str(tmp_path))
    assert idx2.get_block(1).hashes == [h(1), h(2)]
    assert idx2.is_sealed(0)
    assert idx2.chunk_location(h(2)).offset == 50
    idx2.close()


def test_recovery_checkpoint_plus_wal(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
    idx.checkpoint()
    idx.commit_block(2, 20, [h(2)], {h(2): (0, 10, 20)})  # post-ckpt, WAL only
    idx.close()

    idx2 = ChunkIndex(str(tmp_path))
    assert idx2.has_block(1) and idx2.has_block(2)
    idx2.close()


def test_torn_wal_tail_dropped(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
    idx.commit_block(2, 20, [h(2)], {h(2): (0, 10, 20)})
    idx.close()

    wal = tmp_path / "index.wal"
    data = wal.read_bytes()
    wal.write_bytes(data[:-3])  # torn final record

    idx2 = ChunkIndex(str(tmp_path))
    assert idx2.has_block(1)
    assert not idx2.has_block(2)  # torn record dropped, prefix intact
    idx2.close()


def test_append_after_torn_tail_survives_next_recovery(tmp_path):
    # Regression: recovery must truncate the torn tail so post-crash appends
    # don't land behind garbage (and vanish on the NEXT recovery).
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
    idx.commit_block(2, 20, [h(2)], {h(2): (0, 10, 20)})
    idx.close()
    wal = tmp_path / "index.wal"
    wal.write_bytes(wal.read_bytes()[:-3])  # torn final record

    idx2 = ChunkIndex(str(tmp_path))  # restart 1: replays block 1, drops 2
    idx2.commit_block(3, 30, [h(3)], {h(3): (0, 30, 30)})  # post-crash append
    idx2.close()

    idx3 = ChunkIndex(str(tmp_path))  # restart 2: block 3 must survive
    assert idx3.has_block(1) and idx3.has_block(3)
    assert not idx3.has_block(2)
    idx3.close()


def test_corrupt_wal_record_stops_replay(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
    idx.close()
    wal = tmp_path / "index.wal"
    data = bytearray(wal.read_bytes())
    data[12] ^= 0xFF  # flip a payload byte -> CRC mismatch
    wal.write_bytes(bytes(data))
    idx2 = ChunkIndex(str(tmp_path))
    assert not idx2.has_block(1)
    idx2.close()


def test_auto_checkpoint(tmp_path):
    idx = ChunkIndex(str(tmp_path), checkpoint_every=3)
    for i in range(1, 5):
        idx.commit_block(i, 10, [h(i)], {h(i): (0, i * 10, 10)})
    assert os.path.exists(tmp_path / "index.ckpt")
    # WAL was truncated at checkpoint; only post-ckpt records remain.
    assert os.path.getsize(tmp_path / "index.wal") < 200
    idx.close()
    idx2 = ChunkIndex(str(tmp_path))
    assert all(idx2.has_block(i) for i in range(1, 5))
    idx2.close()


def test_record_moves_and_live_bytes(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 30, [h(1), h(2)], {h(1): (0, 0, 10), h(2): (0, 10, 20)})
    assert idx.container_live_bytes() == {0: 30}
    idx.record_moves({h(1): (5, 0, 10), h(2): (5, 10, 20)}, dropped_container=0)
    assert idx.container_live_bytes() == {5: 30}
    assert idx.chunk_location(h(1)).container_id == 5
    idx.close()
    idx2 = ChunkIndex(str(tmp_path))
    assert idx2.chunk_location(h(2)).container_id == 5
    idx2.close()


def _grouped_pair(idx, blk_a, blk_b, timeout=30.0):
    """Drive two commit_block callers into ONE group-commit window,
    deterministically: start A, wait until it is the parked leader, then
    start B (the leader early-outs at group_max=2).  Returns
    {block_id: result-or-exception}."""
    import threading
    import time as _t

    out = {}

    def commit(blk):
        bid = blk[0]
        try:
            out[bid] = idx.commit_block(*blk)
        except BaseException as e:  # noqa: BLE001 — asserted by callers
            out[bid] = e

    ta = threading.Thread(target=commit, args=(blk_a,))
    ta.start()
    deadline = _t.monotonic() + timeout
    while not (idx._gc_leader and len(idx._gc_entries) == 1):
        assert _t.monotonic() < deadline, "leader never parked in window"
        _t.sleep(0.001)
    tb = threading.Thread(target=commit, args=(blk_b,))
    tb.start()
    ta.join(timeout)
    tb.join(timeout)
    assert not ta.is_alive() and not tb.is_alive()
    return out


class TestGroupCommit:
    def test_window_shares_one_fsync(self, tmp_path, monkeypatch):
        # Two concurrent committers inside one window: the whole batch goes
        # through ONE WAL append + ONE fsync (FSEditLog.logSync batching).
        idx = ChunkIndex(str(tmp_path), group_window_s=10.0, group_max=2)
        calls = []
        real = os.fsync
        monkeypatch.setattr(os, "fsync",
                            lambda fd: (calls.append(fd), real(fd))[1])
        out = _grouped_pair(idx,
                            (1, 10, [h(1)], {h(1): (0, 0, 10)}),
                            (2, 10, [h(2)], {h(2): (0, 10, 10)}))
        assert out == {1: [], 2: []}
        assert len(calls) == 1, f"expected one shared fsync, got {len(calls)}"
        assert idx.has_block(1) and idx.has_block(2)
        idx.close()

    def test_group_append_failure_leaves_memory_untouched(self, tmp_path):
        # Log-before-apply holds per window: a failed WAL append raises to
        # EVERY caller of the batch and no block becomes visible.
        from hdrf_tpu.utils import fault_injection

        class Crash(Exception):
            pass

        idx = ChunkIndex(str(tmp_path), group_window_s=10.0, group_max=2)
        with fault_injection.inject(
                "index.wal_append",
                lambda **kw: (_ for _ in ()).throw(Crash())):
            out = _grouped_pair(idx,
                                (1, 10, [h(1)], {h(1): (0, 0, 10)}),
                                (2, 10, [h(2)], {h(2): (0, 10, 10)}))
        assert isinstance(out[1], Crash) and isinstance(out[2], Crash)
        assert not idx.has_block(1) and not idx.has_block(2)
        # the log holds nothing the memory doesn't: a later commit works
        # and recovery sees exactly it
        out = _grouped_pair(idx,
                            (3, 10, [h(3)], {h(3): (0, 20, 10)}),
                            (4, 10, [h(4)], {h(4): (0, 30, 10)}))
        assert out == {3: [], 4: []}
        idx.close()
        idx2 = ChunkIndex(str(tmp_path))
        assert not idx2.has_block(1) and not idx2.has_block(2)
        assert idx2.has_block(3) and idx2.has_block(4)
        idx2.close()

    def test_crash_mid_window_loses_only_unacked_blocks(self, tmp_path):
        # A crash DURING the window's single WAL append (torn tail, the PR-5
        # discipline) drops only the torn record's block; the batch prefix
        # replays — nobody whose record tore was ever acked.
        idx = ChunkIndex(str(tmp_path), group_window_s=10.0, group_max=2)
        out = _grouped_pair(idx,
                            (1, 10, [h(1)], {h(1): (0, 0, 10)}),
                            (2, 10, [h(2)], {h(2): (0, 10, 10)}))
        assert out == {1: [], 2: []}
        idx.close()
        wal = tmp_path / "index.wal"
        wal.write_bytes(wal.read_bytes()[:-3])  # tear the batch's last record
        idx2 = ChunkIndex(str(tmp_path))
        assert idx2.has_block(1)        # durable prefix of the window
        assert not idx2.has_block(2)    # torn (unacked) block only
        idx2.close()

    def test_validation_errors_stay_per_caller(self, tmp_path):
        # One bad block in the window (undeclared hash) raises to ITS caller
        # only; the valid block still commits in the same window.
        idx = ChunkIndex(str(tmp_path), group_window_s=10.0, group_max=2)
        out = _grouped_pair(idx,
                            (1, 10, [h(1)], {h(1): (0, 0, 10)}),
                            (2, 10, [h(9)], {}))  # h(9) neither known nor new
        assert out[1] == []
        assert isinstance(out[2], ValueError)
        assert idx.has_block(1) and not idx.has_block(2)
        idx.close()

    def test_intra_window_dedup_first_entry_wins(self, tmp_path):
        # Both windowed blocks declare the SAME never-seen chunk new: the
        # first entry registers it, the second is told it lost the race
        # (same contract as the serial cross-commit race).
        idx = ChunkIndex(str(tmp_path), group_window_s=10.0, group_max=2)
        out = _grouped_pair(idx,
                            (1, 10, [h(1)], {h(1): (0, 0, 10)}),
                            (2, 10, [h(1)], {h(1): (3, 50, 10)}))
        assert out[1] == [] and out[2] == [h(1)]
        loc = idx.chunk_location(h(1))
        assert (loc.container_id, loc.offset) == (0, 0)
        assert loc.refcount == 2
        idx.close()


def test_stats(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 100, [h(1), h(1)], {h(1): (0, 0, 50)})
    s = idx.stats()
    assert s == {"blocks": 1, "chunks": 1, "sealed_containers": 0,
                 "striped_containers": 0,
                 "logical_bytes": 100, "unique_chunk_bytes": 50}
    idx.close()
