"""ChunkIndex: WAL durability, refcounts, recovery, torn-tail tolerance."""

import os
import struct

import pytest

from hdrf_tpu.index.chunk_index import ChunkIndex


def h(i: int) -> bytes:
    return bytes([i]) * 32


def test_commit_and_lookup(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 100, [h(1), h(2), h(1)],
                     {h(1): (0, 0, 40), h(2): (0, 40, 20)})
    e = idx.get_block(1)
    assert e.logical_len == 100
    assert e.hashes == [h(1), h(2), h(1)]
    locs = idx.lookup_chunks([h(1), h(2), h(3)])
    assert locs[h(1)].refcount == 2  # two references from block 1
    assert locs[h(2)].refcount == 1
    assert locs[h(3)] is None
    idx.close()


def test_cross_block_dedup_refcounts(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 60, [h(1)], {h(1): (0, 0, 60)})
    idx.commit_block(2, 60, [h(1)], {})  # second block reuses the chunk
    assert idx.chunk_location(h(1)).refcount == 2
    assert idx.delete_block(1) == []  # still referenced by block 2
    assert idx.chunk_location(h(1)).refcount == 1
    assert idx.delete_block(2) == [h(1)]  # now dead
    assert idx.chunk_location(h(1)) is None
    idx.close()


def test_commit_validates(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    with pytest.raises(ValueError):
        idx.commit_block(1, 10, [h(9)], {})  # unknown hash, not declared new
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
    idx.close()


def test_concurrent_new_chunk_race_first_commit_wins(tmp_path):
    # Two writers dedup the same never-seen chunk concurrently: both append
    # bytes and declare it new. First commit registers it; second keeps the
    # existing location and is told its copy is an orphan.
    idx = ChunkIndex(str(tmp_path))
    assert idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)}) == []
    losers = idx.commit_block(2, 10, [h(1)], {h(1): (3, 50, 10)})
    assert losers == [h(1)]
    loc = idx.chunk_location(h(1))
    assert (loc.container_id, loc.offset) == (0, 0)  # first commit won
    assert loc.refcount == 2
    idx.close()


def test_checkpoint_crash_before_truncate_is_idempotent(tmp_path):
    # Crash between checkpoint publish and WAL truncation: replay must not
    # double-apply records the checkpoint folded in (refcount inflation).
    from hdrf_tpu.utils import fault_injection

    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})

    class Crash(Exception):
        pass

    with fault_injection.inject("index.post_checkpoint",
                                lambda **kw: (_ for _ in ()).throw(Crash())):
        with pytest.raises(Crash):
            idx.checkpoint()
    idx.close()
    # WAL still holds the blk record AND the checkpoint contains it.
    idx2 = ChunkIndex(str(tmp_path))
    assert idx2.chunk_location(h(1)).refcount == 1  # not inflated to 2
    assert idx2.delete_block(1) == [h(1)]  # chunk correctly dies
    idx2.close()


def test_recovery_from_wal(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 100, [h(1), h(2)], {h(1): (0, 0, 50), h(2): (0, 50, 50)})
    idx.seal_container(0)
    idx.close()

    idx2 = ChunkIndex(str(tmp_path))
    assert idx2.get_block(1).hashes == [h(1), h(2)]
    assert idx2.is_sealed(0)
    assert idx2.chunk_location(h(2)).offset == 50
    idx2.close()


def test_recovery_checkpoint_plus_wal(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
    idx.checkpoint()
    idx.commit_block(2, 20, [h(2)], {h(2): (0, 10, 20)})  # post-ckpt, WAL only
    idx.close()

    idx2 = ChunkIndex(str(tmp_path))
    assert idx2.has_block(1) and idx2.has_block(2)
    idx2.close()


def test_torn_wal_tail_dropped(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
    idx.commit_block(2, 20, [h(2)], {h(2): (0, 10, 20)})
    idx.close()

    wal = tmp_path / "index.wal"
    data = wal.read_bytes()
    wal.write_bytes(data[:-3])  # torn final record

    idx2 = ChunkIndex(str(tmp_path))
    assert idx2.has_block(1)
    assert not idx2.has_block(2)  # torn record dropped, prefix intact
    idx2.close()


def test_append_after_torn_tail_survives_next_recovery(tmp_path):
    # Regression: recovery must truncate the torn tail so post-crash appends
    # don't land behind garbage (and vanish on the NEXT recovery).
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
    idx.commit_block(2, 20, [h(2)], {h(2): (0, 10, 20)})
    idx.close()
    wal = tmp_path / "index.wal"
    wal.write_bytes(wal.read_bytes()[:-3])  # torn final record

    idx2 = ChunkIndex(str(tmp_path))  # restart 1: replays block 1, drops 2
    idx2.commit_block(3, 30, [h(3)], {h(3): (0, 30, 30)})  # post-crash append
    idx2.close()

    idx3 = ChunkIndex(str(tmp_path))  # restart 2: block 3 must survive
    assert idx3.has_block(1) and idx3.has_block(3)
    assert not idx3.has_block(2)
    idx3.close()


def test_corrupt_wal_record_stops_replay(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 10, [h(1)], {h(1): (0, 0, 10)})
    idx.close()
    wal = tmp_path / "index.wal"
    data = bytearray(wal.read_bytes())
    data[12] ^= 0xFF  # flip a payload byte -> CRC mismatch
    wal.write_bytes(bytes(data))
    idx2 = ChunkIndex(str(tmp_path))
    assert not idx2.has_block(1)
    idx2.close()


def test_auto_checkpoint(tmp_path):
    idx = ChunkIndex(str(tmp_path), checkpoint_every=3)
    for i in range(1, 5):
        idx.commit_block(i, 10, [h(i)], {h(i): (0, i * 10, 10)})
    assert os.path.exists(tmp_path / "index.ckpt")
    # WAL was truncated at checkpoint; only post-ckpt records remain.
    assert os.path.getsize(tmp_path / "index.wal") < 200
    idx.close()
    idx2 = ChunkIndex(str(tmp_path))
    assert all(idx2.has_block(i) for i in range(1, 5))
    idx2.close()


def test_record_moves_and_live_bytes(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 30, [h(1), h(2)], {h(1): (0, 0, 10), h(2): (0, 10, 20)})
    assert idx.container_live_bytes() == {0: 30}
    idx.record_moves({h(1): (5, 0, 10), h(2): (5, 10, 20)}, dropped_container=0)
    assert idx.container_live_bytes() == {5: 30}
    assert idx.chunk_location(h(1)).container_id == 5
    idx.close()
    idx2 = ChunkIndex(str(tmp_path))
    assert idx2.chunk_location(h(2)).container_id == 5
    idx2.close()


def test_stats(tmp_path):
    idx = ChunkIndex(str(tmp_path))
    idx.commit_block(1, 100, [h(1), h(1)], {h(1): (0, 0, 50)})
    s = idx.stats()
    assert s == {"blocks": 1, "chunks": 1, "sealed_containers": 0,
                 "logical_bytes": 100, "unique_chunk_bytes": 50}
    idx.close()
