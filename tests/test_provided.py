"""Provided storage / alias map (aliasmap/InMemoryAliasMap.java,
common/FileRegion.java:34): files whose bytes live in an external store,
registered in the namespace, mapped block->byte-range by the DN-side alias
map, reported as PROVIDED replicas, and served through the normal read
path."""

import os
import subprocess
import sys
import time

import pytest

from hdrf_tpu.storage.aliasmap import FileRegion, InMemoryAliasMap
from hdrf_tpu.testing.minicluster import MiniCluster


def test_aliasmap_persistence(tmp_path):
    p = str(tmp_path / "amap")
    m = InMemoryAliasMap(p)
    m.write([FileRegion(7, "file:///x", 0, 100),
             FileRegion(8, "file:///x", 100, 50)])
    m2 = InMemoryAliasMap(p)          # reload from disk
    assert m2.read(7).length == 100 and m2.read(8).offset == 100
    m2.remove([7])
    assert InMemoryAliasMap(p).read(7) is None


def test_aliasmap_range_reads(tmp_path):
    ext = tmp_path / "store.bin"
    data = os.urandom(1000)
    ext.write_bytes(data)
    m = InMemoryAliasMap(str(tmp_path / "amap"))
    m.write([FileRegion(1, f"file://{ext}", 100, 500)])
    assert m.read_bytes(1) == data[100:600]
    assert m.read_bytes(1, offset=10, length=20) == data[110:130]
    assert m.read_bytes(1, offset=499, length=100) == data[599:600]
    assert m.read_bytes(99) is None   # not provided


@pytest.fixture()
def cluster():
    with MiniCluster(n_datanodes=2, replication=1, heartbeat_s=0.1,
                     block_size=256 * 1024) as mc:
        yield mc


def _provide(mc, c, local: str, hpath: str):
    out = c._call("provide_file", path=hpath,
                  uri=f"file://{local}", length=os.path.getsize(local))
    from hdrf_tpu.storage.aliasmap import FileRegion as FR
    for dn in mc.datanodes:
        dn.aliasmap.write([FR.unpack(v) for v in out["regions"]])
        for v in out["regions"]:
            dn.notify_block_received(v[0], v[3], 0)
    return out


def test_provided_file_reads_through_dfs(cluster, tmp_path):
    data = os.urandom(700_000)        # 3 regions at 256 KiB blocks
    ext = tmp_path / "external.bin"
    ext.write_bytes(data)
    with cluster.client() as c:
        out = _provide(cluster, c, str(ext), "/mnt/ext")
        assert len(out["regions"]) == 3
        deadline = time.monotonic() + 10
        while True:                   # wait for IBRs to land locations
            try:
                assert c.read("/mnt/ext") == data
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        # ranged read across a region boundary
        assert c.read("/mnt/ext", offset=250_000, length=20_000) == \
            data[250_000:270_000]
        st = c.stat("/mnt/ext")
        assert st["length"] == len(data) and st["complete"]


def test_provided_survives_restarts(cluster, tmp_path):
    data = os.urandom(100_000)
    ext = tmp_path / "ext2.bin"
    ext.write_bytes(data)
    with cluster.client() as c:
        _provide(cluster, c, str(ext), "/mnt/ext2")
    cluster.restart_namenode()
    cluster.stop_datanode(0)
    cluster.restart_datanode(0)       # aliasmap reloads from disk
    cluster.wait_for_datanodes(2)
    with cluster.client() as c:
        deadline = time.monotonic() + 15
        while True:
            try:
                assert c.read("/mnt/ext2") == data
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)


def test_provide_cli(cluster, tmp_path):
    data = b"provided-by-cli" * 1000
    ext = tmp_path / "cli.bin"
    ext.write_bytes(data)
    addr = f"{cluster.namenode.addr[0]}:{cluster.namenode.addr[1]}"
    out = subprocess.run(
        [sys.executable, "-m", "hdrf_tpu.tools.cli", "dfsadmin",
         "--namenode", addr, "-provide", str(ext), "/mnt/cli"],
        capture_output=True, text=True, cwd="/root/repo")
    assert "provided /mnt/cli" in out.stdout, out.stdout + out.stderr
    with cluster.client() as c:
        deadline = time.monotonic() + 10
        while True:
            try:
                assert c.read("/mnt/cli") == data
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)


def test_provided_delete_cleans_aliasmap(cluster, tmp_path):
    data = os.urandom(50_000)
    ext = tmp_path / "del.bin"
    ext.write_bytes(data)
    with cluster.client() as c:
        out = _provide(cluster, c, str(ext), "/mnt/del")
        bid = out["regions"][0][0]
        deadline = time.monotonic() + 10
        while True:
            try:
                assert c.read("/mnt/del") == data
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        assert c.delete("/mnt/del")
        deadline = time.monotonic() + 10
        while any(dn.aliasmap.read(bid) is not None
                  for dn in cluster.datanodes):
            assert time.monotonic() < deadline, "aliasmap entry not purged"
            time.sleep(0.2)


def test_provided_file_checksum(cluster, tmp_path):
    """getFileChecksum works on provided files: DNs recompute chunk CRCs
    from the external bytes, and the composite equals crc32c(bytes)."""
    from hdrf_tpu import native
    data = os.urandom(300_000)
    ext = tmp_path / "ck.bin"
    ext.write_bytes(data)
    with cluster.client() as c:
        _provide(cluster, c, str(ext), "/mnt/ck")
        deadline = time.monotonic() + 10
        while True:
            try:
                fc = c.get_file_checksum("/mnt/ck")
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        assert fc["crc"] == native.crc32c(data)


def test_alias_add_requires_token_when_secure(tmp_path):
    """With block tokens on, a tokenless alias_add is refused — the DN-side
    gate matching rpc_provide_file's superuser-only NN gate."""
    from hdrf_tpu.tools.cli import _dn_call
    with MiniCluster(n_datanodes=1, replication=1, secure=True) as mc:
        dn = mc.datanodes[0]
        addr = f"{dn.addr[0]}:{dn.addr[1]}"
        with pytest.raises(Exception):
            _dn_call(addr, "alias_add",
                     regions=[[999, "file:///etc/hostname", 0, 10]],
                     tokens=None)
        assert dn.aliasmap.read(999) is None
