"""Provided storage / alias map (aliasmap/InMemoryAliasMap.java,
common/FileRegion.java:34): files whose bytes live in an external store,
registered in the namespace, mapped block->byte-range by the DN-side alias
map, reported as PROVIDED replicas, and served through the normal read
path."""

import os
import subprocess
import sys
import time

import pytest

from hdrf_tpu.storage.aliasmap import FileRegion, InMemoryAliasMap
from hdrf_tpu.testing.minicluster import MiniCluster


def test_aliasmap_persistence(tmp_path):
    p = str(tmp_path / "amap")
    m = InMemoryAliasMap(p)
    m.write([FileRegion(7, "file:///x", 0, 100),
             FileRegion(8, "file:///x", 100, 50)])
    m2 = InMemoryAliasMap(p)          # reload from disk
    assert m2.read(7).length == 100 and m2.read(8).offset == 100
    m2.remove([7])
    assert InMemoryAliasMap(p).read(7) is None


def test_aliasmap_range_reads(tmp_path):
    ext = tmp_path / "store.bin"
    data = os.urandom(1000)
    ext.write_bytes(data)
    m = InMemoryAliasMap(str(tmp_path / "amap"))
    m.write([FileRegion(1, f"file://{ext}", 100, 500)])
    assert m.read_bytes(1) == data[100:600]
    assert m.read_bytes(1, offset=10, length=20) == data[110:130]
    assert m.read_bytes(1, offset=499, length=100) == data[599:600]
    assert m.read_bytes(99) is None   # not provided


@pytest.fixture()
def cluster():
    with MiniCluster(n_datanodes=2, replication=1, heartbeat_s=0.1,
                     block_size=256 * 1024) as mc:
        yield mc


def _provide(mc, c, local: str, hpath: str):
    out = c._call("provide_file", path=hpath,
                  uri=f"file://{local}", length=os.path.getsize(local))
    from hdrf_tpu.storage.aliasmap import FileRegion as FR
    for dn in mc.datanodes:
        dn.aliasmap.write([FR.unpack(v) for v in out["regions"]])
        for v in out["regions"]:
            dn.notify_block_received(v[0], v[3], 0)
    return out


def test_provided_file_reads_through_dfs(cluster, tmp_path):
    data = os.urandom(700_000)        # 3 regions at 256 KiB blocks
    ext = tmp_path / "external.bin"
    ext.write_bytes(data)
    with cluster.client() as c:
        out = _provide(cluster, c, str(ext), "/mnt/ext")
        assert len(out["regions"]) == 3
        deadline = time.monotonic() + 10
        while True:                   # wait for IBRs to land locations
            try:
                assert c.read("/mnt/ext") == data
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        # ranged read across a region boundary
        assert c.read("/mnt/ext", offset=250_000, length=20_000) == \
            data[250_000:270_000]
        st = c.stat("/mnt/ext")
        assert st["length"] == len(data) and st["complete"]


def test_provided_survives_restarts(cluster, tmp_path):
    data = os.urandom(100_000)
    ext = tmp_path / "ext2.bin"
    ext.write_bytes(data)
    with cluster.client() as c:
        _provide(cluster, c, str(ext), "/mnt/ext2")
    cluster.restart_namenode()
    cluster.stop_datanode(0)
    cluster.restart_datanode(0)       # aliasmap reloads from disk
    cluster.wait_for_datanodes(2)
    with cluster.client() as c:
        deadline = time.monotonic() + 15
        while True:
            try:
                assert c.read("/mnt/ext2") == data
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)


def test_provide_cli(cluster, tmp_path):
    data = b"provided-by-cli" * 1000
    ext = tmp_path / "cli.bin"
    ext.write_bytes(data)
    addr = f"{cluster.namenode.addr[0]}:{cluster.namenode.addr[1]}"
    out = subprocess.run(
        [sys.executable, "-m", "hdrf_tpu.tools.cli", "dfsadmin",
         "--namenode", addr, "-provide", str(ext), "/mnt/cli"],
        capture_output=True, text=True, cwd="/root/repo")
    assert "provided /mnt/cli" in out.stdout, out.stdout + out.stderr
    with cluster.client() as c:
        deadline = time.monotonic() + 10
        while True:
            try:
                assert c.read("/mnt/cli") == data
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)


def test_provided_delete_cleans_aliasmap(cluster, tmp_path):
    data = os.urandom(50_000)
    ext = tmp_path / "del.bin"
    ext.write_bytes(data)
    with cluster.client() as c:
        out = _provide(cluster, c, str(ext), "/mnt/del")
        bid = out["regions"][0][0]
        deadline = time.monotonic() + 10
        while True:
            try:
                assert c.read("/mnt/del") == data
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        assert c.delete("/mnt/del")
        deadline = time.monotonic() + 10
        while any(dn.aliasmap.read(bid) is not None
                  for dn in cluster.datanodes):
            assert time.monotonic() < deadline, "aliasmap entry not purged"
            time.sleep(0.2)


def test_provided_file_checksum(cluster, tmp_path):
    """getFileChecksum works on provided files: DNs recompute chunk CRCs
    from the external bytes, and the composite equals crc32c(bytes)."""
    from hdrf_tpu import native
    data = os.urandom(300_000)
    ext = tmp_path / "ck.bin"
    ext.write_bytes(data)
    with cluster.client() as c:
        _provide(cluster, c, str(ext), "/mnt/ck")
        deadline = time.monotonic() + 10
        while True:
            try:
                fc = c.get_file_checksum("/mnt/ck")
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        assert fc["crc"] == native.crc32c(data)


class TestMountRoot:
    """alias_add confinement: block tokens gate WHO may alias, the mount
    root bounds WHAT — a write-token holder must not be able to alias a
    block onto /etc/shadow and read it back through the DFS."""

    def test_inside_root_accepted(self, tmp_path):
        m = InMemoryAliasMap(str(tmp_path / "amap"),
                             mount_root=str(tmp_path))
        m.check_uri(f"file://{tmp_path}/sub/data.bin")
        m.check_uri(f"file://{tmp_path}")  # the root itself

    def test_outside_root_rejected(self, tmp_path):
        m = InMemoryAliasMap(str(tmp_path / "amap"),
                             mount_root=str(tmp_path / "mnt"))
        with pytest.raises(IOError, match="outside mount root"):
            m.check_uri("file:///etc/hostname")
        with pytest.raises(IOError, match="outside mount root"):
            # prefix trick: /mnt-evil shares the string prefix, not the tree
            m.check_uri(f"file://{tmp_path}/mnt-evil/x")
        with pytest.raises(IOError, match="outside mount root"):
            m.check_uri(f"file://{tmp_path}/mnt/../escape")

    def test_symlink_out_of_tree_rejected_at_read(self, tmp_path):
        root = tmp_path / "mnt"
        root.mkdir()
        secret = tmp_path / "secret.bin"
        secret.write_bytes(b"s" * 64)
        link = root / "alias.bin"
        link.symlink_to(secret)
        m = InMemoryAliasMap(str(tmp_path / "amap"), mount_root=str(root))
        m.write([FileRegion(5, f"file://{link}", 0, 64)])
        # check_uri re-resolves at read time: the symlink escapes the tree
        with pytest.raises(IOError, match="outside mount root"):
            m.read_bytes(5)

    def test_disabled_root_refuses_everything(self, tmp_path):
        m = InMemoryAliasMap(str(tmp_path / "amap"), mount_root=None)
        with pytest.raises(IOError, match="provided storage disabled"):
            m.check_uri(f"file://{tmp_path}/x")

    def test_non_file_scheme_rejected(self, tmp_path):
        m = InMemoryAliasMap(str(tmp_path / "amap"))
        with pytest.raises(IOError, match="unsupported"):
            m.check_uri("s3://bucket/key")

    def test_alias_add_rejects_outside_mount_root(self, tmp_path):
        """End to end through the DN op: a region outside the configured
        mount root is refused and never persisted or reported."""
        from hdrf_tpu.tools.cli import _dn_call
        with MiniCluster(n_datanodes=1, replication=1) as mc:
            dn = mc.datanodes[0]
            dn.aliasmap._mount_root = str(tmp_path)  # tighten from "/"
            addr = f"{dn.addr[0]}:{dn.addr[1]}"
            out = _dn_call(addr, "alias_add",
                           regions=[[777, "file:///etc/hostname", 0, 10]],
                           tokens=None)
            assert not out.get("ok") and "outside mount root" in out["error"]
            assert dn.aliasmap.read(777) is None
            inside = tmp_path / "ok.bin"
            inside.write_bytes(b"x" * 10)
            out = _dn_call(addr, "alias_add",
                           regions=[[778, f"file://{inside}", 0, 10]],
                           tokens=None)
            assert out["ok"]
            assert dn.aliasmap.read(778) is not None


class TestProvidedReplication:
    """The replication monitor's shared-storage accounting: N provided
    locations are views of ONE external store — counted once, never pruned
    as excess, never a re-replication source."""

    def _nn(self, tmp_path, replication=1):
        from hdrf_tpu.config import NameNodeConfig
        from hdrf_tpu.server.namenode import NameNode
        cfg = NameNodeConfig(meta_dir=str(tmp_path / "name"),
                             replication=replication, block_size=1024,
                             dead_node_interval_s=60.0)
        return NameNode(cfg)

    def _provide_block(self, nn, n_dns, path="/p"):
        for i in range(n_dns):
            nn.rpc_register_datanode(f"dn-{i}", [f"h{i}", 1000 + i])
        out = nn.rpc_provide_file(path, uri="file:///ext/p.bin", length=512)
        bid = out["regions"][0][0]
        for i in range(n_dns):
            nn.rpc_block_received(f"dn-{i}", bid, 512,
                                  storage_type="PROVIDED")
        return bid

    def test_provided_locations_not_pruned(self, tmp_path):
        nn = self._nn(tmp_path, replication=1)
        try:
            bid = self._provide_block(nn, n_dns=3)
            info = nn._blocks[bid]
            assert len(info.locations) == 3
            nn._check_replication()
            # pre-fix behavior: 3 locations vs want=1 -> two invalidated
            assert len(info.locations) == 3, "provided replicas pruned"
            for i in range(3):
                assert not nn._datanodes[f"dn-{i}"].commands
        finally:
            nn._editlog.close()

    def test_provided_never_sources_re_replication(self, tmp_path):
        nn = self._nn(tmp_path, replication=3)
        try:
            bid = self._provide_block(nn, n_dns=3)
            nn._check_replication()
            # one shared store != 3 replicas, but re-replication onto local
            # disks from a provided view is an operator action, not the
            # monitor's: no replicate commands, not counted under-replicated
            for i in range(3):
                assert not nn._datanodes[f"dn-{i}"].commands
            assert bid not in nn._pending_repl
            assert nn._under_replicated == 0
        finally:
            nn._editlog.close()

    def test_excess_prune_targets_local_never_provided(self, tmp_path):
        # Provided files carry replication=1; an extra LOCAL copy (an
        # explicit provided->local migration racing the monitor) IS excess
        # — but the victim must be the local replica, never a provided
        # view.
        nn = self._nn(tmp_path)
        try:
            bid = self._provide_block(nn, n_dns=3)
            info = nn._blocks[bid]
            info.storage_of["dn-2"] = "DISK"   # dn-2 now a local copy
            nn._check_replication()
            assert info.locations == {"dn-0", "dn-1"}  # provided survive
            inval = [c for c in nn._datanodes["dn-2"].commands
                     if c["cmd"] == "invalidate"]
            assert inval and bid in inval[0]["block_ids"]
            assert not nn._datanodes["dn-0"].commands
            assert not nn._datanodes["dn-1"].commands
        finally:
            nn._editlog.close()


def test_alias_add_requires_token_when_secure(tmp_path):
    """With block tokens on, a tokenless alias_add is refused — the DN-side
    gate matching rpc_provide_file's superuser-only NN gate."""
    from hdrf_tpu.tools.cli import _dn_call
    with MiniCluster(n_datanodes=1, replication=1, secure=True) as mc:
        dn = mc.datanodes[0]
        addr = f"{dn.addr[0]}:{dn.addr[1]}"
        with pytest.raises(Exception):
            _dn_call(addr, "alias_add",
                     regions=[[999, "file:///etc/hostname", 0, 10]],
                     tokens=None)
        assert dn.aliasmap.read(999) is None
