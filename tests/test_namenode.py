"""NameNode unit tests: namespace ops, leases, persistence, block management."""

import pytest

from hdrf_tpu.config import NameNodeConfig
from hdrf_tpu.server.namenode import NameNode


@pytest.fixture
def nn(tmp_path):
    cfg = NameNodeConfig(meta_dir=str(tmp_path / "name"), replication=2,
                         block_size=1024, dead_node_interval_s=60.0)
    n = NameNode(cfg)
    # no .start(): RPC/monitor not needed for direct-call unit tests
    yield n
    n._editlog.close()


def register(nn, n=3):
    for i in range(n):
        nn.rpc_register_datanode(f"dn-{i}", [f"h{i}", 1000 + i])


def complete(nn, path, lengths, client="c1"):
    """Report each block from dn-0 (the async-IBR contract: complete waits
    for minimal replication), then complete."""
    for bid, ln in lengths.items():
        nn.rpc_block_received("dn-0", bid, ln)
    assert nn.rpc_complete(path, client=client, block_lengths=lengths)


class TestNamespace:
    def test_mkdir_listing_stat(self, nn):
        nn.rpc_mkdir("/a/b/c")
        (ent,) = nn.rpc_listing("/a")
        assert (ent["name"], ent["type"], ent["children"]) == ("b", "dir", 1)
        st = nn.rpc_stat("/a/b/c")
        assert (st["name"], st["type"], st["children"]) == ("c", "dir", 0)
        assert st["mode"] == 0o755 and st["owner"]  # inode attributes exist

    def test_create_write_flow(self, nn):
        register(nn)
        info = nn.rpc_create("/f", client="c1", scheme="dedup_lz4")
        assert info["block_size"] == 1024 and info["scheme"] == "dedup_lz4"
        alloc = nn.rpc_add_block("/f", client="c1")
        assert len(alloc["targets"]) == 2  # replication
        assert alloc["scheme"] == "dedup_lz4"
        complete(nn, "/f", {alloc["block_id"]: 500})
        st = nn.rpc_stat("/f")
        assert st["length"] == 500 and st["complete"]

    def test_lease_enforcement(self, nn):
        register(nn)
        nn.rpc_create("/f", client="c1")
        with pytest.raises(PermissionError):
            nn.rpc_add_block("/f", client="c2")
        with pytest.raises(PermissionError):
            nn.rpc_create("/f", client="c2")  # lease held by c1

    def test_lease_expiry_recovers_file(self, nn):
        register(nn)
        nn.rpc_create("/f", client="c1")
        a = nn.rpc_add_block("/f", client="c1")
        bid = a["block_id"]
        # ALL expected pipeline DNs report the same length: the consistent
        # fast path completes without a recovery round trip
        for t in a["targets"]:
            nn.rpc_block_received(t["dn_id"], bid, 42)
        nn._leases.expiry_s = -1  # force expiry
        nn._leases.renew_all("c1")
        nn._recover_leases()
        st = nn.rpc_stat("/f")
        assert st["complete"] and st["length"] == 42  # recovered w/ reported len
        with pytest.raises(FileExistsError):
            nn.rpc_create("/f", client="c2")  # complete files aren't overwritten

    def test_delete_and_rename(self, nn):
        register(nn)
        nn.rpc_create("/d/f", client="c1")
        a = nn.rpc_add_block("/d/f", client="c1")
        complete(nn, "/d/f", {a["block_id"]: 10})
        nn.rpc_rename("/d/f", "/d2/g")
        assert nn.rpc_stat("/d2/g")["length"] == 10
        assert nn._blocks[a["block_id"]].path == "/d2/g"
        assert nn.rpc_delete("/d2/g")
        assert a["block_id"] not in nn._blocks
        assert not nn.rpc_delete("/d2/g")  # already gone

    def test_rename_into_own_subtree_rejected(self, nn):
        nn.rpc_mkdir("/a/b")
        with pytest.raises(ValueError):
            nn.rpc_rename("/a", "/a/b/c")
        with pytest.raises(ValueError):
            nn.rpc_rename("/a", "/a")
        assert nn.rpc_stat("/a/b")["type"] == "dir"  # tree intact

    def test_create_over_incomplete_invalidates_old_blocks(self, nn):
        register(nn)
        nn.rpc_create("/f", client="c1")
        a = nn.rpc_add_block("/f", client="c1")
        old_bid = a["block_id"]
        nn.rpc_block_received(a["targets"][0]["dn_id"], old_bid, 10)
        # c1 abandons; lease expires; c2 recreates the (incomplete) file
        nn._leases.drop("/f")
        nn.rpc_create("/f", client="c2")
        assert old_bid not in nn._blocks  # no leak in the block map
        cmds = nn.rpc_heartbeat(a["targets"][0]["dn_id"])["commands"]
        assert {"cmd": "invalidate", "block_ids": [old_bid]} in cmds

    def test_delete_queues_invalidation(self, nn):
        register(nn)
        nn.rpc_create("/f", client="c1")
        a = nn.rpc_add_block("/f", client="c1")
        bid = a["block_id"]
        complete(nn, "/f", {bid: 10})
        dn0 = a["targets"][0]["dn_id"]
        nn.rpc_block_received(dn0, bid, 10)
        nn.rpc_delete("/f")
        cmds = nn.rpc_heartbeat(dn0)["commands"]
        assert {"cmd": "invalidate", "block_ids": [bid]} in cmds


class TestPersistence:
    def _flow(self, nn):
        register(nn)
        nn.rpc_mkdir("/dir")
        nn.rpc_create("/dir/f", client="c1", scheme="lz4")
        a = nn.rpc_add_block("/dir/f", client="c1")
        complete(nn, "/dir/f", {a["block_id"]: 77})
        return a["block_id"]

    def test_wal_replay(self, nn, tmp_path):
        bid = self._flow(nn)
        nn._editlog.close()
        nn2 = NameNode(nn.config)
        st = nn2.rpc_stat("/dir/f")
        assert st["length"] == 77 and st["scheme"] == "lz4" and st["complete"]
        assert nn2._blocks[bid].length == 77
        assert nn2._next_block_id > bid
        nn2._editlog.close()

    def test_image_plus_wal(self, nn):
        self._flow(nn)
        nn.rpc_save_namespace()  # checkpoint
        register(nn)
        nn.rpc_create("/post", client="c1")
        a2 = nn.rpc_add_block("/post", client="c1")
        complete(nn, "/post", {a2["block_id"]: 5})
        nn._editlog.close()
        nn2 = NameNode(nn.config)
        assert nn2.rpc_stat("/dir/f")["length"] == 77
        assert nn2.rpc_stat("/post")["length"] == 5
        nn2._editlog.close()


class TestBlockManagement:
    def test_block_report_reconciles(self, nn):
        register(nn, 1)
        nn.rpc_create("/f", client="c1")
        a = nn.rpc_add_block("/f", client="c1")
        bid = a["block_id"]
        complete(nn, "/f", {bid: 9})
        nn.rpc_block_report("dn-0", [[bid, a["gen_stamp"], 9]])
        assert "dn-0" in nn._blocks[bid].locations
        # stale replica of a deleted file -> invalidate command
        nn.rpc_block_report("dn-0", [[bid, a["gen_stamp"], 9], [999, 1, 5]])
        cmds = nn.rpc_heartbeat("dn-0")["commands"]
        assert {"cmd": "invalidate", "block_ids": [999]} in cmds
        # replica disappears from next report -> location removed
        nn.rpc_block_report("dn-0", [])
        assert "dn-0" not in nn._blocks[bid].locations

    def test_replication_monitor_schedules(self, nn):
        register(nn, 3)
        nn.rpc_create("/f", client="c1", replication=3)
        a = nn.rpc_add_block("/f", client="c1")
        bid = a["block_id"]
        complete(nn, "/f", {bid: 9})
        nn.rpc_block_received("dn-0", bid, 9)  # only 1 of 3 replicas
        nn._check_replication()
        cmds = nn.rpc_heartbeat("dn-0")["commands"]
        rep = [c for c in cmds if c["cmd"] == "replicate"]
        assert len(rep) == 1 and rep[0]["block_id"] == bid
        assert len(rep[0]["targets"]) == 2
        assert all(t["dn_id"] != "dn-0" for t in rep[0]["targets"])

    def test_dead_node_detection(self, nn):
        register(nn, 2)
        nn.rpc_create("/f", client="c1")
        a = nn.rpc_add_block("/f", client="c1")
        bid = a["block_id"]
        complete(nn, "/f", {bid: 9})
        nn.rpc_block_received("dn-0", bid, 9)
        nn.config.dead_node_interval_s = -1  # everything is dead
        nn._check_dead_nodes()
        assert nn._datanodes == {}
        assert nn._blocks[bid].locations == set()

    def test_heartbeat_unknown_dn_asks_reregister(self, nn):
        assert nn.rpc_heartbeat("ghost")["reregister"]

    def test_add_block_no_datanodes(self, nn):
        nn.rpc_create("/f", client="c1")
        with pytest.raises(IOError):
            nn.rpc_add_block("/f", client="c1")


class TestWalIntegrity:
    def test_rejected_op_does_not_poison_wal(self, nn, tmp_path):
        """mkdir over an existing file must fail *without* leaving a WAL
        record that would crash every future NameNode start (apply-before-
        append in NameNode._log)."""
        register(nn)
        nn.rpc_create("/a", client="c1")
        nn.rpc_complete("/a", client="c1", block_lengths={})
        with pytest.raises(FileExistsError):
            nn.rpc_mkdir("/a/b")
        with pytest.raises(FileExistsError):
            nn.rpc_create("/x", client="c1") and nn.rpc_complete(
                "/x", client="c1", block_lengths={}) and nn.rpc_rename("/x", "/a")
        nn._editlog.close()
        # restart over the same meta dir must succeed and keep the namespace
        nn2 = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "name")))
        assert nn2.rpc_stat("/a")["type"] == "file"
        nn2._editlog.close()

    def test_delete_dir_releases_child_leases(self, nn):
        register(nn)
        nn.rpc_create("/d/f", client="c1")   # lease held, file incomplete
        nn.rpc_delete("/d")
        # the path must be immediately re-creatable by another client
        nn.rpc_create("/d/f", client="c2")

    def test_replication_not_requeued_every_tick(self, nn):
        register(nn, n=3)
        nn.rpc_create("/f", client="c1")
        alloc = nn.rpc_add_block("/f", client="c1")
        bid = alloc["block_id"]
        complete(nn, "/f", {bid: 10})
        # one replica reported on dn-0 only; replication=2 -> deficit 1
        nn.rpc_block_received("dn-0", bid, 10)
        nn._check_replication()
        nn._check_replication()  # second tick while transfer "in flight"
        cmds = [c for d in nn._datanodes.values() for c in d.commands
                if c["cmd"] == "replicate"]
        assert len(cmds) == 1


class TestSafemodeAndDecommission:
    def test_startup_safemode_until_reports(self, tmp_path):
        cfg = NameNodeConfig(meta_dir=str(tmp_path / "name"), replication=1)
        nn = NameNode(cfg)
        register(nn, 1)
        nn.rpc_create("/f", client="c1")
        a = nn.rpc_add_block("/f", client="c1")
        nn.rpc_block_received("dn-0", a["block_id"], 7)
        assert nn.rpc_complete("/f", client="c1",
                               block_lengths={a["block_id"]: 7})
        nn._editlog.close()
        # restart over the same meta dir: non-empty namespace => safemode
        nn2 = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "name"),
                                      replication=1))
        assert nn2.rpc_safemode("get") is True
        with pytest.raises(OSError, match="safe mode"):
            nn2.rpc_mkdir("/blocked")
        # a block report satisfies the threshold and safemode lifts
        nn2.rpc_register_datanode("dn-0", ["h0", 1000])
        nn2.rpc_block_report("dn-0", [[a["block_id"], a["gen_stamp"], 7]])
        assert nn2.rpc_safemode("get") is False
        nn2.rpc_mkdir("/unblocked")
        # manual enter/leave
        nn2.rpc_safemode("enter")
        with pytest.raises(OSError, match="safe mode"):
            nn2.rpc_delete("/unblocked")
        nn2.rpc_safemode("leave")
        assert nn2.rpc_delete("/unblocked")
        nn2._editlog.close()

    def test_decommission_drains_and_completes(self, nn):
        register(nn, 3)
        nn.rpc_create("/f", client="c1")
        a = nn.rpc_add_block("/f", client="c1")
        bid = a["block_id"]
        nn.rpc_block_received("dn-0", bid, 10)
        nn.rpc_block_received("dn-1", bid, 10)
        assert nn.rpc_complete("/f", client="c1", block_lengths={bid: 10})
        assert nn.rpc_decommission("dn-0")
        st = nn.rpc_decommission_status("dn-0")
        assert st["state"] == "decommissioning" and st["remaining"] == 1
        # decommissioning nodes are excluded from new placements
        targets = nn._choose_targets(3, exclude=set())
        assert all(t.dn_id != "dn-0" for t in targets)
        # the monitor schedules a replacement copy (replication=2, one
        # counted replica left on dn-1)
        nn._check_replication()
        cmds = [c for d in nn._datanodes.values() for c in d.commands
                if c["cmd"] == "replicate"]
        assert cmds and cmds[0]["block_id"] == bid
        # replica lands on dn-2 -> dn-0 is safe to stop
        nn.rpc_block_received("dn-2", bid, 10)
        assert nn.rpc_decommission_status("dn-0")["state"] == "decommissioned"

    def test_ec_shard_drain_and_recommission(self, nn, tmp_path):
        register(nn, 5)
        nn.rpc_create("/e", client="c1", ec="rs-3-2-4k")
        alloc = nn.rpc_add_block_group("/e", client="c1")
        gid = alloc["group_id"]
        for i, blk in enumerate(alloc["blocks"]):
            nn.rpc_block_received(f"dn-{i % 5}", blk["block_id"], 4096)
        assert nn.rpc_complete("/e", client="c1", block_lengths={gid: 12288})
        assert nn.rpc_decommission("dn-0")
        # the monitor schedules a plain copy of the EC shard off dn-0
        nn._check_replication()
        cmds = [c for d in nn._datanodes.values() for c in d.commands
                if c["cmd"] == "replicate"]
        shard_on_dn0 = next(b["block_id"] for i, b in
                            enumerate(alloc["blocks"]) if i % 5 == 0)
        assert any(c["block_id"] == shard_on_dn0 for c in cmds)
        # replica lands elsewhere -> drain completes
        nn.rpc_block_received("dn-3", shard_on_dn0, 4096)
        st = nn.rpc_decommission_status("dn-0")
        assert st["state"] == "decommissioned", st
        # recommission returns the node to placement
        assert nn.rpc_recommission("dn-0")
        assert nn.rpc_decommission_status("dn-0")["state"] == "normal"
        # the exclude set survives a restart over the same meta dir
        nn.rpc_decommission("dn-1")
        nn._editlog.close()
        nn2 = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "name")))
        assert "dn-1" in nn2._decommissioning
        nn2._editlog.close()

    def test_recover_lease_rpc(self, nn):
        register(nn)
        nn.rpc_create("/rl", client="c1")
        a = nn.rpc_add_block("/rl", client="c1")
        bid = a["block_id"]
        # only ONE of the expected pipeline DNs has reported: recovery must
        # NOT complete from a partial peer set — it dispatches a length-sync
        # to the primary and waits for commitBlockSynchronization
        nn.rpc_block_received(a["targets"][0]["dn_id"], bid, 42)
        assert nn.rpc_recover_lease("/rl") is False
        all_cmds = [c for d in nn._datanodes.values() for c in d.commands]
        assert any(c["cmd"] == "recover_block" for c in all_cmds)
        # the primary reports the synced min length
        assert nn.rpc_commit_block_sync(
            "/rl", bid, 42, [a["targets"][0]["dn_id"]],
            gen_stamp=nn._blocks[bid].gen_stamp)
        assert nn.rpc_recover_lease("/rl") is True
        st = nn.rpc_stat("/rl")
        assert st["complete"] and st["length"] == 42
        # path is writable by a new client afterwards
        nn.rpc_create("/rl2", client="c2")


def completed_block(nn):
    """Register 3 DNs, create /f, complete one block with replicas on
    dn-0 and dn-1."""
    register(nn)
    nn.rpc_create("/f", client="c1")
    a = nn.rpc_add_block("/f", client="c1")
    bid = a["block_id"]
    nn.rpc_block_received("dn-0", bid, 500)
    nn.rpc_block_received("dn-1", bid, 500)
    assert nn.rpc_complete("/f", client="c1", block_lengths={bid: 500})
    return bid


class TestBalancerMoveSafety:
    """A balancer move must never reduce redundancy: the source replica is
    dropped only after the REQUESTED target reports its copy (not when any
    other replica happens to exist), and a move whose target never arrives
    is abandoned with the source untouched."""

    def test_source_kept_until_target_reports(self, nn):
        bid = completed_block(nn)
        assert nn.rpc_move_block(bid, "dn-0", "dn-2")
        nn._settle_moves()  # dn-1 replica exists, but dn-2 hasn't reported
        assert "dn-0" in nn._blocks[bid].locations
        assert bid in nn._pending_moves
        nn.rpc_block_received("dn-2", bid, 500)
        nn._settle_moves()
        locs = nn._blocks[bid].locations
        assert "dn-2" in locs and "dn-0" not in locs
        assert bid not in nn._pending_moves

    def test_move_abandoned_after_deadline(self, nn):
        bid = completed_block(nn)
        assert nn.rpc_move_block(bid, "dn-0", "dn-2")
        nn._pending_moves[bid]["deadline"] = 0.0  # force expiry
        nn._settle_moves()
        assert bid not in nn._pending_moves
        assert "dn-0" in nn._blocks[bid].locations  # replica untouched


class TestStandbyLeaseHygiene:
    def test_standby_create_leaves_no_lease(self, tmp_path):
        """A create rejected by the role check must not leave a lease behind:
        leases acquired on a standby are never recovered (lease recovery only
        runs on the active) and would block creates after promotion."""
        from hdrf_tpu.server.namenode import StandbyError

        cfg = NameNodeConfig(meta_dir=str(tmp_path / "sb"), role="standby")
        sb = NameNode(cfg)
        try:
            with pytest.raises(StandbyError):
                sb.rpc_create("/f", client="c1")
            assert "/f" not in sb._leases._leases
        finally:
            sb._editlog.close()


class TestExcessReplicas:
    def test_excess_replicas_pruned(self, nn):
        """Over-replication (re-replication racing a node's return, or an
        abandoned move whose target reported late) is pruned back to the
        target count — processExtraRedundancy analog."""
        bid = completed_block(nn)
        nn.rpc_block_received("dn-2", bid, 500)  # third copy, want=2
        assert len(nn._blocks[bid].locations) == 3
        nn._check_replication()
        locs = nn._blocks[bid].locations
        assert len(locs) == 2
        victim = ({"dn-0", "dn-1", "dn-2"} - locs).pop()
        cmds = nn._datanodes[victim].commands
        assert any(c["cmd"] == "invalidate" and bid in c["block_ids"]
                   for c in cmds)

    def test_excess_prune_preserves_rack_diversity(self, nn):
        """chooseReplicaToDelete semantics: never prune the last replica on
        a rack while another rack holds two — one rack failure must not be
        able to take out the block."""
        nn.rpc_register_datanode("dn-0", ["h0", 1000], rack="/rackA")
        nn.rpc_register_datanode("dn-1", ["h1", 1001], rack="/rackA")
        nn.rpc_register_datanode("dn-2", ["h2", 1002], rack="/rackB")
        nn.rpc_create("/f", client="c1")
        a = nn.rpc_add_block("/f", client="c1")
        bid = a["block_id"]
        nn.rpc_block_received("dn-0", bid, 500)
        nn.rpc_block_received("dn-1", bid, 500)
        assert nn.rpc_complete("/f", client="c1", block_lengths={bid: 500})
        nn.rpc_block_received("dn-2", bid, 500)  # 3rd copy, want=2
        # make the rackB node the fullest so naive selection would pick it
        nn._datanodes["dn-2"].blocks.update({991, 992, 993})
        nn._check_replication()
        locs = nn._blocks[bid].locations
        assert len(locs) == 2
        assert "dn-2" in locs  # rackB's only copy survived
