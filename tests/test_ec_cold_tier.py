"""EC cold tier: striped containers, degraded reads, stripe repair.

Covers the demotion pipeline end to end against the striped-layout and
reconstruction semantics of the reference (DFSStripedOutputStream.java:81
striping, StripedBlockUtil.java:77 index math, StripedBlockReconstructor.
java:41 decode-and-writeback, ErasureCodingWorker.java:55 DN repair
executor) re-expressed over sealed containers (storage/stripe_store.py):

- codec bit-identity vs the GF log/antilog host oracle (ops/rs.py:134)
  on the 8-device CPU mesh, including non-multiple-of-k tail padding;
- torn-manifest WAL replay (index/chunk_index.py record_stripe framing);
- cluster demotion: 3x replicas -> (k+m)/k stripes, accounting ratio,
  degraded reads with one and two stripe holders failing mid-read
  (fault points "stripe.read" / "stripe.repair"), background repair.
"""

import io
import itertools
import json
import time
import urllib.request
from contextlib import redirect_stdout

import numpy as np
import pytest

from hdrf_tpu import native
from hdrf_tpu.index.chunk_index import ChunkIndex
from hdrf_tpu.ops import rs
from hdrf_tpu.storage import stripe_store
from hdrf_tpu.tools import cli
from hdrf_tpu.utils import fault_injection, metrics, wal

_EC = metrics.registry("ec")


@pytest.fixture(autouse=True)
def _clear_faults():
    fault_injection.clear()
    yield
    fault_injection.clear()


def run_cli(argv) -> tuple[int, str]:
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(argv)
    return rc, buf.getvalue()


# ----------------------------------------------------------- codec oracle


class TestStripeCodec:
    K, M = 6, 3

    def _payload(self, n: int, seed: int = 0) -> bytes:
        rng = np.random.default_rng(seed)
        return rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()

    def test_encode_parity_matches_gf_oracle(self):
        """Parity stripes are bit-identical to the numpy GF log/antilog
        oracle (rs.encode_ref) — including a tail that pads."""
        for n in (6 * 1024, 6 * 1024 + 1, 6 * 1000 - 5):
            payload = self._payload(n, seed=n)
            stripes, man = stripe_store.encode_container(payload, self.K,
                                                         self.M)
            sl = man["stripe_len"]
            padded = payload + b"\x00" * (self.K * sl - n)
            data = np.frombuffer(padded, dtype=np.uint8).reshape(self.K, sl)
            ref = rs.encode_ref(data, self.M)
            for i in range(self.M):
                assert stripes[self.K + i] == ref[i].tobytes(), \
                    f"parity {i} diverged from GF oracle at n={n}"
            assert man["length"] == n
            for i, s in enumerate(stripes):
                assert native.crc32c(s) == man["crcs"][i]

    def test_reconstruct_from_any_k_survivors(self):
        """Up to m=3 lost stripes (any pattern, data and parity mixed):
        reconstruction is bit-identical to the original sealed bytes."""
        payload = self._payload(6 * 512 + 7, seed=2)
        stripes, man = stripe_store.encode_container(payload, self.K, self.M)
        for lost in itertools.combinations(range(self.K + self.M), 3):
            got = {i: stripes[i] for i in range(self.K + self.M)
                   if i not in lost}
            out = stripe_store.reconstruct_container(got, man)
            assert out == payload, f"erasure pattern {lost} diverged"

    def test_tail_padding_edges(self):
        """Lengths around the k boundary: 0, 1, k-1, k, k+1, and a
        multi-cell tail — the manifest's true length trims the zero pad."""
        k = self.K
        for n in (0, 1, k - 1, k, k + 1, k * 257 - 1, k * 257, k * 257 + 1):
            payload = self._payload(n, seed=100 + n)
            stripes, man = stripe_store.encode_container(payload, k, self.M)
            assert man["stripe_len"] >= 1
            # worst case: drop the first m stripes (all-data erasures)
            got = {i: stripes[i] for i in range(self.M, k + self.M)}
            assert stripe_store.reconstruct_container(got, man) == payload

    def test_corrupt_stripe_is_an_erasure(self):
        """A CRC-failing stripe is treated as an erasure, not input; with
        fewer than k intact stripes reconstruction refuses (StripeCorrupt)."""
        payload = self._payload(6 * 300, seed=3)
        stripes, man = stripe_store.encode_container(payload, self.K, self.M)
        bad = bytearray(stripes[0])
        bad[5] ^= 0xFF
        offered = {i: stripes[i] for i in range(self.K + self.M)}
        offered[0] = bytes(bad)
        errs0 = _EC.counter("stripe_crc_errors")
        assert stripe_store.reconstruct_container(offered, man) == payload
        assert _EC.counter("stripe_crc_errors") > errs0
        # k offered but one corrupt -> only k-1 intact -> refuse
        short = {i: stripes[i] for i in range(self.K)}
        short[0] = bytes(bad)
        with pytest.raises(stripe_store.StripeCorrupt):
            stripe_store.reconstruct_container(short, man)

    def test_degraded_read_counter_semantics(self):
        """Losing only parity is NOT a degraded read (no decode); losing a
        data stripe decodes through parity and counts."""
        payload = self._payload(6 * 64, seed=4)
        stripes, man = stripe_store.encode_container(payload, self.K, self.M)
        before = _EC.counter("degraded_reads")
        got = {i: stripes[i] for i in range(self.K)}  # all data, no parity
        assert stripe_store.reconstruct_container(got, man) == payload
        assert _EC.counter("degraded_reads") == before
        got = {i: stripes[i] for i in range(1, self.K + 1)}  # data 0 lost
        assert stripe_store.reconstruct_container(got, man) == payload
        assert _EC.counter("degraded_reads") == before + 1

    def test_storage_ratio_is_three_halves(self):
        """Acceptance pin: RS(6,3) stripes cost ~1.5x the logical sealed
        bytes (vs the replicated tier's 3x)."""
        payload = self._payload((1 << 16) + 11, seed=5)
        _stripes, man = stripe_store.encode_container(payload, 6, 3)
        ratio = (6 + 3) * man["stripe_len"] / man["length"]
        assert 1.49 <= ratio <= 1.51


# ------------------------------------------------- manifest WAL durability


class TestManifestWal:
    MANIFEST = {"k": 3, "m": 2, "length": 1000, "stripe_len": 334,
                "crcs": [1, 2, 3, 4, 5], "owner": "dn-0", "usize": 4096,
                "holders": [["dn-0", "127.0.0.1", 1], ["dn-1", "127.0.0.1", 2],
                            ["dn-2", "127.0.0.1", 3], ["dn-3", "127.0.0.1", 4],
                            ["dn-4", "127.0.0.1", 5]]}

    def test_torn_manifest_tail_is_dropped_on_replay(self, tmp_path):
        """A crash mid-append of a stripe record must not poison recovery:
        the committed manifest survives, the torn tail is discarded."""
        d = str(tmp_path / "idx")
        idx = ChunkIndex(d)
        idx.record_stripe(7, self.MANIFEST)
        idx.close()
        # simulate a torn second stripe record: valid header, short payload
        torn = wal.frame(b"x" * 512)[:-200]
        with open(tmp_path / "idx" / "index.wal", "ab") as f:
            f.write(torn)
        idx2 = ChunkIndex(d)
        try:
            man = idx2.stripe_manifest(7)
            assert man is not None
            assert man["length"] == 1000 and man["k"] == 3
            assert man["holders"][1][0] == "dn-1"
            assert idx2.stripe_manifest(8) is None
        finally:
            idx2.close()

    def test_unstripe_replays(self, tmp_path):
        d = str(tmp_path / "idx")
        idx = ChunkIndex(d)
        idx.record_stripe(7, self.MANIFEST)
        idx.drop_stripe(7)
        idx.close()
        idx2 = ChunkIndex(d)
        try:
            assert idx2.stripe_manifest(7) is None
            assert idx2.stats()["striped_containers"] == 0
        finally:
            idx2.close()


# --------------------------------------------------------- cluster e2e


@pytest.fixture
def cold_cluster():
    """5 DNs, small containers (roll+seal while the test runs), RS(3,2)
    cold tier armed but demotion disabled until the test flips the knob."""
    from hdrf_tpu.testing.minicluster import MiniCluster

    with MiniCluster(n_datanodes=5, block_size=256 * 1024,
                     container_size=32 * 1024) as mc:
        mc.namenode.config.ec_data_shards = 3
        mc.namenode.config.ec_parity_shards = 2
        mc.namenode.config.ec_demote_after_s = 0.0
        yield mc


def _wait(pred, timeout=20.0, interval=0.1, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _owner_dn(mc):
    for dn in mc.datanodes:
        if dn is not None and dn.index.stats()["striped_containers"] > 0:
            return dn
    return None


class TestColdTierCluster:
    def test_demote_degraded_read_and_repair(self, cold_cluster):
        mc = cold_cluster
        rng = np.random.default_rng(17)
        data = rng.integers(0, 256, size=200_000, dtype=np.uint8).tobytes()
        with mc.client("cold") as c:
            c.write("/cold/a", data, scheme="dedup_lz4")
            assert c.read("/cold/a") == data

            # ---- demotion: 3x replicas -> (k+m)/k stripes --------------
            mc.namenode.config.ec_demote_after_s = 0.3
            time.sleep(0.3)
            _wait(lambda: c._call("ec_status")["demoted_blocks"] >= 1,
                  msg="block demotion")
            # the census aggregates DN heartbeat stats — allow one beat
            _wait(lambda: c._call("ec_status")["striped_containers"] >= 1,
                  msg="striped-container census")
            es = c._call("ec_status")
            assert es["policy"] == "rs-3-2"
            assert es["striped_containers"] >= 1
            assert es["stripe_groups"] >= 1
            # accounting: stripe tier costs ~(3+2)/3 = 1.67x vs 3x before
            assert 1.6 <= es["storage_ratio_striped"] <= 1.75
            assert es["storage_ratio_replicated"] == 3.0
            # the demoted block wants ONE full replica (the stripe owner)
            _wait(lambda: all(
                len(b["locations"]) == 1
                for b in c._call("get_block_locations",
                                 path="/cold/a")["blocks"]),
                  msg="replica invalidation down to the owner")

            owner = _owner_dn(mc)
            assert owner is not None
            manifests = owner.index.stripe_manifests()
            assert manifests
            # sealed files were dropped on the owner; bytes must still read
            assert c.read("/cold/a") == data

            # ---- degraded reads: kill holders mid-read -----------------
            # restart the owner so the container cache is cold and every
            # read goes through sealed-file -> stripe-gather fallback
            oid = int(owner.dn_id.split("-")[1])
            mc.stop_datanode(oid)
            mc.restart_datanode(oid)
            mc.wait_for_datanodes(5)
            owner = mc.datanodes[oid]
            man = next(iter(owner.index.stripe_manifests().values()))
            k = int(man["k"])
            data_holders = [man["holders"][i][0] for i in range(k)]
            victims = [d for d in data_holders if d != owner.dn_id]

            def _boom(lost):
                def handler(dn_id=None, **kw):
                    if dn_id in lost:
                        raise ConnectionError(
                            f"injected stripe holder loss on {dn_id}")
                return handler

            # one data-stripe holder down: decode through parity
            before = _EC.counter("degraded_reads")
            with fault_injection.inject("stripe.read", _boom(victims[:1])):
                assert c.read("/cold/a") == data
            assert _EC.counter("degraded_reads") > before

            # two holders down (the full parity budget of RS(3,2)):
            # still bit-identical
            mc.stop_datanode(oid)
            mc.restart_datanode(oid)
            mc.wait_for_datanodes(5)
            before = _EC.counter("degraded_reads")
            with fault_injection.inject("stripe.read", _boom(victims[:2])):
                assert c.read("/cold/a") == data
            assert _EC.counter("degraded_reads") > before

            # ---- background stripe repair ------------------------------
            repair_fired = []
            fault_injection.install(
                "stripe.repair",
                lambda dn_id=None, **kw: repair_fired.append(dn_id))
            owner = mc.datanodes[int(_owner_dn(mc).dn_id.split("-")[1])]
            man = next(iter(owner.index.stripe_manifests().values()))
            dead = next(h[0] for h in man["holders"] if h[0] != owner.dn_id)
            repaired0 = _EC.counter("stripes_repaired")
            mc.stop_datanode(int(dead.split("-")[1]))
            _wait(lambda: _EC.counter("stripes_repaired") > repaired0,
                  timeout=25.0, msg="stripe repair")
            assert repair_fired and repair_fired[0] == owner.dn_id
            assert _EC.counter("repair_bytes") > 0
            # post-repair: manifest holders no longer reference the dead DN
            _wait(lambda: all(
                h[0] != dead
                for m in owner.index.stripe_manifests().values()
                for h in m["holders"]), msg="holder re-registration")
            assert c.read("/cold/a") == data

    def test_ec_status_cli_and_gateway_rows(self, cold_cluster):
        mc = cold_cluster
        rng = np.random.default_rng(23)
        data = rng.integers(0, 256, size=120_000, dtype=np.uint8).tobytes()
        with mc.client("ops") as c:
            c.write("/cold/b", data, scheme="dedup_lz4")
            mc.namenode.config.ec_demote_after_s = 0.3
            time.sleep(0.3)
            _wait(lambda: c._call("ec_status")["demoted_blocks"] >= 1,
                  msg="block demotion")
            _wait(lambda: c._call("ec_status")["striped_containers"] >= 1,
                  msg="striped-container census")

        nn = f"{mc.namenode.addr[0]}:{mc.namenode.addr[1]}"
        rc, out = run_cli(["dfsadmin", "--namenode", nn, "-ecStatus"])
        assert rc == 0
        assert "EC policy: rs-3-2" in out
        assert "striped=" in out and "ratio=" in out

        from hdrf_tpu.server.http_gateway import HttpGateway
        gw = HttpGateway(mc.namenode.addr).start()
        try:
            base = f"http://{gw.addr[0]}:{gw.addr[1]}"
            with urllib.request.urlopen(base + "/status", timeout=10) as r:
                st = json.loads(r.read())
            assert st["striped_containers"] >= 1
            assert st["ec_demoted_blocks"] >= 1
            assert st["stripe_physical_bytes"] > st["stripe_logical_bytes"]
            with urllib.request.urlopen(base + "/health", timeout=10) as r:
                health = json.loads(r.read())
            assert health["striped_containers"] >= 1
            with urllib.request.urlopen(base + "/prom", timeout=10) as r:
                prom = r.read().decode()
            assert 'hdrf_stripes_encoded_total{registry="ec"}' in prom
        finally:
            gw.stop()


# ------------------------------------------- owner-loss stripe durability


class TestOwnerLossDurability:
    def test_kill_owner_deputizes_survivor_from_journaled_manifest(
            self, cold_cluster):
        """Satellite: the demote-time ``ec_demote`` edit journals each
        group's FULL stripe manifest into the NN editlog/fsimage, so a
        dead owner DN (whose WAL held the only other copy) no longer
        strands its groups: the repair monitor deputizes a surviving
        holder, hands the journaled manifest down with ``stripe_repair``,
        and the repaired stripes keep the dead owner's name."""
        from hdrf_tpu.utils import metrics as _m

        _NN = _m.registry("namenode")
        mc = cold_cluster
        rng = np.random.default_rng(29)
        data = rng.integers(0, 256, size=200_000, dtype=np.uint8).tobytes()
        with mc.client("ol") as c:
            c.write("/cold/ol", data, scheme="dedup_lz4")
            before_journal = _NN.counter("stripe_manifests_journaled")
            mc.namenode.config.ec_demote_after_s = 0.3
            time.sleep(0.3)
            _wait(lambda: c._call("ec_status")["demoted_blocks"] >= 1,
                  msg="block demotion")
            _wait(lambda: _NN.counter("stripe_manifests_journaled")
                  > before_journal, msg="manifest journaling")
        assert mc.namenode._stripe_manifests, \
            "demotion journaled no manifest into the NN"
        owner = _owner_dn(mc)
        assert owner is not None
        owner_id = owner.dn_id
        assert any(o == owner_id for o, _cid in mc.namenode._stripe_manifests)

        # the manifests must survive an NN restart (editlog/fsimage replay
        # of the grown ec_demote record) — the owner's WAL copy is NOT the
        # durable home anymore
        mc.restart_namenode()
        mc.wait_for_datanodes(5)
        assert mc.namenode._stripe_manifests, \
            "journaled manifests lost across NN restart"
        mc.namenode.config.ec_demote_after_s = 0.0
        # the re-registration window right after the restart can fire
        # spurious repairs (holders look dead until their first heartbeat
        # lands); shrink the pending backoff so the REAL repair below is
        # not throttled behind them
        mc.namenode.config.pending_replication_timeout_s = 2.0
        # startup safemode refuses edits — including the deputy's manifest
        # re-journaling — so it must lift (the demoted block's owner
        # replica reported back) BEFORE the kills take that replica away
        # for good
        with mc.client("olsm") as c:
            _wait(lambda: not c._call("cluster_status")["safemode"],
                  msg="post-restart safemode exit")

        # kill -9 the owner (its WAL manifests die with it), then one
        # stripe holder: without the journaled manifest this group would
        # now be stranded — no owner to consult, a stripe gone.  The
        # repair monitor must deputize a SURVIVING holder and hand the
        # NN's manifest copy down with the stripe_repair command.
        repair_agents = []
        fault_injection.install(
            "stripe.repair",
            lambda dn_id=None, **kw: repair_agents.append(dn_id))
        mc.kill_datanode(int(owner_id.split("-")[1]))
        mans = [m for (o, _cid), m in mc.namenode._stripe_manifests.items()
                if o == owner_id]
        victim = next(h[0] for m in mans for h in m["holders"]
                      if h[0] != owner_id)
        n_pre = len(repair_agents)
        before_sched = _NN.counter("owner_loss_repairs_scheduled")
        before_rep = _EC.counter("stripes_repaired")
        mc.kill_datanode(int(victim.split("-")[1]))
        _wait(lambda: _NN.counter("owner_loss_repairs_scheduled")
              > before_sched, timeout=25.0, msg="owner-loss scheduling")
        _wait(lambda: _EC.counter("stripes_repaired") > before_rep,
              timeout=25.0, msg="deputized stripe repair")
        post = repair_agents[n_pre:]
        assert post and all(a != owner_id for a in post), \
            "repair ran on the dead owner instead of a deputy"

        # the re-journaled manifests keep the dead owner's name as the
        # group key while every holder entry points at a LIVE DN again
        def _healed():
            live = {dn.dn_id for dn in mc.datanodes if dn is not None}
            mans = [m for (o, _cid), m in
                    mc.namenode._stripe_manifests.items() if o == owner_id]
            return mans and all(h[0] in live
                                for m in mans for h in m["holders"])
        _wait(_healed, timeout=30.0, msg="manifest holder re-registration")
