"""File-level checksums (FileChecksumHelper.java:56,
BlockChecksumHelper.java:61/:328): composed from per-block chunk CRCs in
COMPOSITE-CRC32C mode, so identical content checksums identically across
replicated and EC-striped layouts — and equals crc32c(bytes), the oracle
every test here leans on."""

import os

import pytest

from hdrf_tpu import native
from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.utils.checksum import crc32c_combine


def test_crc32c_combine_matches_oracle():
    rng = os.urandom
    for la, lb in [(1, 1), (100, 37), (65536, 65536), (1, 1_000_000),
                   (999_999, 3)]:
        a, b = rng(la), rng(lb)
        assert crc32c_combine(native.crc32c(a), native.crc32c(b), lb) \
            == native.crc32c(a + b)


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_datanodes=5, replication=2,
                     block_size=256 * 1024) as mc:
        yield mc


def test_replicated_file_checksum_is_stream_crc(cluster):
    data = os.urandom(700_000)   # 3 blocks, partial tail chunk
    with cluster.client() as c:
        c.write("/ck/plain", data)
        fc = c.get_file_checksum("/ck/plain")
        assert fc["algorithm"] == "COMPOSITE-CRC32C"
        assert fc["length"] == len(data)
        assert fc["crc"] == native.crc32c(data)


def test_reduced_scheme_checksums_logical_bytes(cluster):
    """dedup_lz4 blocks store a reduced form; the checksum still covers
    the LOGICAL bytes (BlockMeta checksums are computed at ingest)."""
    data = (b"pattern-" * 9000) + os.urandom(30_000)
    with cluster.client() as c:
        c.write("/ck/reduced", data, scheme="dedup_lz4")
        assert c.get_file_checksum("/ck/reduced")["crc"] \
            == native.crc32c(data)


def test_striped_matches_replicated_checksum(cluster):
    """The block-group variant: same content, EC layout, same checksum."""
    data = os.urandom(900_000)
    with cluster.client() as c:
        c.write("/ck/rep", data)
        c.write("/ck/ec", data, ec="rs-3-2-64k")
        rep = c.get_file_checksum("/ck/rep")
        ec = c.get_file_checksum("/ck/ec")
        assert rep["crc"] == ec["crc"] == native.crc32c(data)
        assert rep["bytes"] == ec["bytes"]


def test_striped_partial_cell_tail(cluster):
    """Logical length not a multiple of the cell: the zero-padded tail
    cell must not leak into the checksum."""
    data = os.urandom(3 * 65536 + 12345)
    with cluster.client() as c:
        c.write("/ck/ectail", data, ec="rs-3-2-64k")
        assert c.get_file_checksum("/ck/ectail")["crc"] \
            == native.crc32c(data)


def test_copy_verify(cluster):
    """The distcp use case: checksums prove (or disprove) a faithful copy."""
    data = os.urandom(400_000)
    with cluster.client() as c:
        c.write("/ck/src", data)
        c.write("/ck/dst", c.read("/ck/src"))
        assert c.get_file_checksum("/ck/src")["bytes"] \
            == c.get_file_checksum("/ck/dst")["bytes"]
        corrupted = bytearray(data)
        corrupted[123] ^= 0xFF
        c.write("/ck/bad", bytes(corrupted))
        assert c.get_file_checksum("/ck/bad")["bytes"] \
            != c.get_file_checksum("/ck/src")["bytes"]


def test_empty_file_checksum(cluster):
    with cluster.client() as c:
        c.write("/ck/empty", b"")
        fc = c.get_file_checksum("/ck/empty")
        assert fc["length"] == 0 and fc["crc"] == 0
