"""Append, truncate, and replica length recovery.

Re-expresses the reference's append/recovery surface (DFSClient.append,
FSNamesystem.truncate, BlockRecoveryWorker + commitBlockSynchronization,
TestFileAppend / TestLeaseRecovery): block-granular copy-on-append under a
bumped generation stamp, namespace-level truncate, and the primary-DN
length-sync recovery for pipelines that died with divergent replica
lengths (kill-mid-write)."""

from __future__ import annotations

import time

import numpy as np
import pytest

from hdrf_tpu.testing.minicluster import MiniCluster

RNG = np.random.default_rng(21)


def _bytes(n: int) -> bytes:
    return RNG.integers(0, 256, size=n, dtype=np.uint8).tobytes()


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_datanodes=3, replication=2, block_size=1 << 20) as mc:
        yield mc


class TestAppend:
    @pytest.mark.parametrize("scheme", ["direct", "dedup_lz4"])
    def test_append_within_last_block(self, cluster, scheme):
        a, b = _bytes(300_000), _bytes(200_000)
        with cluster.client(f"ap-{scheme}") as c:
            c.write(f"/ap/{scheme}", a, scheme=scheme)
            c.append(f"/ap/{scheme}", b)
            assert c.read(f"/ap/{scheme}") == a + b
            assert c.read(f"/ap/{scheme}", offset=290_000, length=20_000) \
                == (a + b)[290_000:310_000]

    def test_append_crosses_block_boundary(self, cluster):
        a, b = _bytes(900_000), _bytes(1_500_000)  # 1 MiB blocks
        with cluster.client("ap-cross") as c:
            c.write("/ap/cross", a, scheme="direct")
            c.append("/ap/cross", b)
            assert c.read("/ap/cross") == a + b

    def test_append_at_exact_block_multiple(self, cluster):
        a, b = _bytes(1 << 20), _bytes(123_456)
        with cluster.client("ap-exact") as c:
            c.write("/ap/exact", a, scheme="direct")
            c.append("/ap/exact", b)  # no partial last block to rewrite
            assert c.read("/ap/exact") == a + b

    def test_repeated_appends(self, cluster):
        parts = [_bytes(80_000) for _ in range(5)]
        with cluster.client("ap-rep") as c:
            c.write("/ap/rep", parts[0], scheme="dedup_lz4")
            for p in parts[1:]:
                c.append("/ap/rep", p)
            assert c.read("/ap/rep") == b"".join(parts)

    def test_append_requires_closed_file_and_lease(self, cluster):
        from hdrf_tpu.proto.rpc import RpcError

        with cluster.client("ap-l1") as c1, cluster.client("ap-l2") as c2:
            c1.write("/ap/lease", _bytes(10_000), scheme="direct")
            cluster.namenode.rpc_append("/ap/lease", client=c1.name)
            # second appender is refused while the lease is held
            with pytest.raises(RpcError) as ei:
                c2.append("/ap/lease", b"x")
            # either refusal is correct: the file is open (OSError) or the
            # lease is held by c1 (PermissionError) — both name the cause
            assert ei.value.error in ("OSError", "PermissionError")
            assert "lease" in str(ei.value).lower() or \
                "open" in str(ei.value).lower()


class TestTruncate:
    def test_truncate_mid_block_and_whole_blocks(self, cluster):
        data = _bytes(2_500_000)  # ~2.4 blocks at 1 MiB
        with cluster.client("tr") as c:
            c.write("/tr/f", data, scheme="direct")
            assert c.truncate("/tr/f", 1_200_000)
            assert c.read("/tr/f") == data[:1_200_000]
            assert c.stat("/tr/f")["length"] == 1_200_000
            # truncate to a block boundary, then to zero
            assert c.truncate("/tr/f", 1 << 20)
            assert c.read("/tr/f") == data[:1 << 20]
            assert c.truncate("/tr/f", 0)
            assert c.read("/tr/f") == b""

    def test_truncate_grow_rejected(self, cluster):
        with cluster.client("tr2") as c:
            c.write("/tr/g", _bytes(1000), scheme="direct")
            with pytest.raises(Exception):
                c.truncate("/tr/g", 2000)

    def test_append_after_truncate(self, cluster):
        data = _bytes(700_000)
        with cluster.client("tr3") as c:
            c.write("/tr/a", data, scheme="direct")
            c.truncate("/tr/a", 400_000)
            c.append("/tr/a", b"tail" * 1000)
            assert c.read("/tr/a") == data[:400_000] + b"tail" * 1000


class TestLengthRecovery:
    def test_kill_mid_write_syncs_replica_lengths(self):
        """The pipeline dies with DIVERGENT replica lengths (one DN saw 3
        packets, the other 2): lease recovery must sync everyone to the
        minimum CRC-verified prefix and close the file at that length —
        not at zero, and not at the longer replica's length."""
        import socket

        from hdrf_tpu.proto import datatransfer as dt

        with MiniCluster(n_datanodes=2, replication=2,
                         block_size=1 << 20) as mc:
            nn = mc.namenode
            nn.rpc_create("/rec/f", client="w", scheme="direct")
            alloc = nn.rpc_add_block("/rec/f", client="w")
            pkt = _bytes(64 * 1024)
            npkts = {0: 3, 1: 2}
            for i, dn in enumerate(mc.datanodes):
                s = socket.create_connection(dn.addr, timeout=10)
                dt.send_op(s, dt.WRITE_BLOCK, block_id=alloc["block_id"],
                           gen_stamp=alloc["gen_stamp"], scheme="direct",
                           token=alloc.get("token"), targets=[])
                for seq in range(npkts[i]):
                    dt.write_packet(s, seq, pkt)
                    dt.read_ack(s)
                s.close()  # die without the LAST packet
            deadline = time.time() + 15
            while time.time() < deadline:
                if nn.rpc_recover_lease("/rec/f"):
                    break
                time.sleep(0.3)
            else:
                pytest.fail("lease recovery did not close the file")
            st = nn.rpc_stat("/rec/f")
            assert st["length"] == 2 * 64 * 1024  # the min prefix
            with mc.client("r") as c:
                assert c.read("/rec/f") == pkt * 2

    def test_recover_lease_before_any_ibr_waits_for_reports(self):
        """recover_lease racing the DNs' ASYNC IBRs: called while the
        pipeline sockets are still open (so no IBR has fired yet) it must
        NOT conclude "no replica survived" and close the file at length 0 —
        it waits a bounded grace, and once the divergent replicas report it
        converges to the min CRC-verified prefix."""
        import socket

        from hdrf_tpu.proto import datatransfer as dt

        with MiniCluster(n_datanodes=2, replication=2,
                         block_size=1 << 20) as mc:
            nn = mc.namenode
            nn.rpc_create("/rec/early", client="w", scheme="direct")
            alloc = nn.rpc_add_block("/rec/early", client="w")
            pkt = _bytes(64 * 1024)
            socks = []
            for i, dn in enumerate(mc.datanodes):
                s = socket.create_connection(dn.addr, timeout=10)
                dt.send_op(s, dt.WRITE_BLOCK, block_id=alloc["block_id"],
                           gen_stamp=alloc["gen_stamp"], scheme="direct",
                           token=alloc.get("token"), targets=[])
                for seq in range(3 if i == 0 else 2):
                    dt.write_packet(s, seq, pkt)
                    dt.read_ack(s)
                socks.append(s)
            # pipeline still open -> replicas are RBW, no IBR yet: recovery
            # must decline rather than complete the file empty
            assert nn.rpc_recover_lease("/rec/early") is False
            assert nn.rpc_stat("/rec/early")["length"] == 0  # still open
            assert not nn.rpc_stat("/rec/early")["complete"]
            for s in socks:
                s.close()  # now the DNs persist the prefix and IBR
            deadline = time.time() + 15
            while time.time() < deadline:
                if nn.rpc_recover_lease("/rec/early"):
                    break
                time.sleep(0.3)
            else:
                pytest.fail("lease recovery did not close the file")
            assert nn.rpc_stat("/rec/early")["length"] == 2 * 64 * 1024
            with mc.client("r") as c:
                assert c.read("/rec/early") == pkt * 2

    def test_append_crash_preserves_old_generation_replicas(self):
        """The writer reopens for append (bump_block journals a new gen
        stamp) then dies before writing a single new-generation byte.  The
        old-generation replicas are now "stale" — but they are the ONLY
        copies of the data: the NN must not invalidate them, and lease
        recovery must restamp them and close the file at its original
        length (commitBlockSynchronization semantics)."""
        with MiniCluster(n_datanodes=2, replication=2,
                         block_size=1 << 20) as mc:
            data = _bytes(100_000)
            with mc.client("w") as c:
                c.write("/rec/ap", data, scheme="direct")
            nn = mc.namenode
            nn.rpc_append("/rec/ap", client="w2")
            nn.rpc_append_block("/rec/ap", client="w2")  # bumps gen stamp
            # full reports now present the OLD generation: the NN must keep
            # these replicas (they are the block's only copies)
            for dn in mc.datanodes:
                dn._send_block_report()
            deadline = time.time() + 15
            while time.time() < deadline:
                if nn.rpc_recover_lease("/rec/ap"):
                    break
                time.sleep(0.3)
            else:
                pytest.fail("lease recovery did not close the file")
            st = nn.rpc_stat("/rec/ap")
            assert st["length"] == len(data)
            with mc.client("r") as c:
                assert c.read("/rec/ap") == data

    def test_kill_before_any_replica_drops_block(self):
        """No replica ever materialized: recovery closes the file empty
        (the reference drops the last block when no replica survives)."""
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=1 << 20) as mc:
            nn = mc.namenode
            nn.rpc_create("/rec/empty", client="w", scheme="direct")
            nn.rpc_add_block("/rec/empty", client="w")
            deadline = time.time() + 10
            while time.time() < deadline:
                if nn.rpc_recover_lease("/rec/empty"):
                    break
                time.sleep(0.3)
            st = nn.rpc_stat("/rec/empty")
            assert st["length"] == 0
