"""Read-path & per-tenant SLO observability plane.

Covers the serving-path twin of the write profiler: read timelines and
their exclusive-class partition (utils/profiler.py:272-312 read_timeline,
server/block_sender.py:66-108 serve_read), read-amplification accounting
(reduction/accounting.py:96-163), per-tenant attribution
(utils/tenants.py:40-99; the reference counts ops per daemon only,
DataNodeMetrics.java:553-560), the time-series flight recorder and its
``/timeseries`` surfaces (utils/flight_recorder.py:33-98,
server/status_http.py:84-87), the slo_report renderer
(tools/slo_report.py:94-146), the decoded-container LRU on the EC
degraded path (storage/container_store.py:455-515), and the rollwin
quantile extensions (utils/rollwin.py:79-168)."""

import json
import os
import random
import time
import urllib.request

import pytest

from hdrf_tpu.server.http_gateway import HttpGateway
from hdrf_tpu.server.status_http import StatusHttpServer
from hdrf_tpu.storage import container_store
from hdrf_tpu.storage.container_store import ContainerStore
from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.reduction import accounting
from hdrf_tpu.tools import slo_report
from hdrf_tpu.utils import metrics, profiler, rollwin, tenants
from hdrf_tpu.utils.flight_recorder import FlightRecorder
from hdrf_tpu.utils.profiler import BlockTimeline, phase_class


def blob(seed: int, n: int) -> bytes:
    return random.Random(seed).randbytes(n)


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10) as r:
        assert r.status == 200
        return r.read()


def _await(cond, timeout: float = 5.0) -> bool:
    """Poll a cross-thread condition: the serving thread books its tenant
    note a hair after the client has its bytes (serve_read's latency covers
    the full packet run), so counter asserts must tolerate that window."""
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            return cond()
        time.sleep(0.01)
    return True


# ------------------------------------------------------ timeline partition


class TestReadPhasePartition:
    def test_read_phase_classes(self):
        """The read phases join the exclusive-class map: index/cache/decode
        burn the single vCPU, stripe gathers and the packet run are
        transport waits the host could hide."""
        for p in ("index_lookup", "cache_probe", "container_decode"):
            assert phase_class(p) == profiler.HOST
        for p in ("ec_gather", "net_send"):
            assert phase_class(p) == profiler.TRANSPORT
        assert phase_class("device_wait") == profiler.DEVICE

    def test_serial_partition_sums_exactly(self):
        """Injected clocks: a serial read decomposes into host + transport
        with zero idle, attributed_frac == 1.0, and the class partition
        summing exactly to the wall clock."""
        tl = BlockTimeline(1, nbytes=1000, t0=0.0)
        tl.add_span("index_lookup", 0.0, 0.1)
        tl.add_span("cache_probe", 0.1, 0.15)
        tl.add_span("container_decode", 0.15, 0.5)
        tl.add_span("ec_gather", 0.5, 0.8)
        tl.add_span("net_send", 0.8, 1.0)
        tl.finish(t1=1.0)
        prof = tl.profile()
        assert prof["wall_s"] == pytest.approx(1.0)
        assert prof["classes"]["host_busy"] == pytest.approx(0.5)
        assert prof["classes"]["transport_wait"] == pytest.approx(0.5)
        assert prof["classes"]["idle"] == pytest.approx(0.0, abs=1e-12)
        assert sum(prof["classes"].values()) == pytest.approx(prof["wall_s"])
        assert prof["attributed_frac"] == pytest.approx(1.0)
        assert prof["phases"]["index_lookup"] == pytest.approx(0.1)
        assert prof["phases"]["cache_probe"] == pytest.approx(0.05)
        assert prof["phases"]["container_decode"] == pytest.approx(0.35)
        assert prof["phases"]["ec_gather"] == pytest.approx(0.3)
        assert prof["phases"]["net_send"] == pytest.approx(0.2)

    def test_hidden_transport_wait_under_decode(self):
        """A net_send window overlapped by host decode counts host_busy
        (the wait is HIDDEN — the desirable state); overlap_efficiency is
        hidden / hideable."""
        tl = BlockTimeline(2, t0=0.0)
        tl.add_span("container_decode", 0.0, 0.6)
        tl.add_span("net_send", 0.2, 1.0)
        tl.finish(t1=1.0)
        prof = tl.profile()
        assert prof["classes"]["host_busy"] == pytest.approx(0.6)
        assert prof["classes"]["transport_wait"] == pytest.approx(0.4)
        assert prof["hideable_wait_s"] == pytest.approx(0.8)
        assert prof["hidden_wait_s"] == pytest.approx(0.4)
        assert prof["overlap_efficiency"] == pytest.approx(0.5)

    def test_nested_lookup_attributes_innermost(self):
        """index_lookup nested inside a container_decode window attributes
        to the innermost phase (PHASE_ORDER lists it first)."""
        tl = BlockTimeline(3, t0=0.0)
        tl.add_span("container_decode", 0.0, 1.0)
        tl.add_span("index_lookup", 0.2, 0.4)
        tl.finish(t1=1.0)
        prof = tl.profile()
        assert prof["phases"]["index_lookup"] == pytest.approx(0.2)
        assert prof["phases"]["container_decode"] == pytest.approx(0.8)

    def test_read_timeline_observes_read_registry(self):
        """Finished read timelines ring separately from write ones and
        observe into the read_profiler registry."""
        profiler.reset()
        reg = metrics.registry("read_profiler")
        before = reg.counter("reads_profiled")
        with profiler.read_timeline(77, nbytes=4096):
            with profiler.phase("index_lookup"):
                pass
        assert reg.counter("reads_profiled") == before + 1
        snaps = profiler.read_timelines_snapshot()
        assert snaps and snaps[-1]["block_id"] == 77
        assert snaps[-1]["nbytes"] == 4096
        assert "profile" in snaps[-1]
        # the read ring is not the write ring
        assert all(t["block_id"] != 77
                   for t in profiler.timelines_snapshot())
        with reg._lock:
            h = reg._histograms.get("read_wall_us")
        assert h is not None and h.snapshot()["count"] >= 1


# --------------------------------------------------- read amplification


class TestReadAmplification:
    def test_exact_synthetic_corpus(self):
        """Hand-computed corpus: 4096 logical bytes served, 10240 physical
        bytes decoded, 2048 stripe bytes gathered -> amplification 2.5 /
        stripe amplification 0.5, exactly."""
        accounting.record_read_logical("t_ro_synth", 4096)
        with accounting.read_scope("t_ro_synth"):
            accounting.record_container_decode(10240)
            accounting.record_stripe_gather(2048)
        rep = accounting.read_amplification_report()["t_ro_synth"]
        assert rep["logical_bytes"] == 4096
        assert rep["physical_bytes"] == 10240
        assert rep["stripe_bytes"] == 2048
        assert rep["read_amplification"] == pytest.approx(2.5)
        assert rep["stripe_amplification"] == pytest.approx(0.5)
        # the derived ratio also lands as a /prom gauge
        snap = metrics.registry("reduction_accounting").snapshot()
        assert snap["gauges"]["read_amplification__t_ro_synth"] == \
            pytest.approx(2.5)

    def test_decode_outside_scope_books_raw(self):
        """Decodes outside any read scope (compaction, EC repair) book
        under the ``raw`` pseudo-scheme."""
        reg = metrics.registry("reduction_accounting")
        before = reg.counter("read_physical_bytes__raw")
        accounting.record_container_decode(777)
        assert reg.counter("read_physical_bytes__raw") == before + 777

    def test_container_store_decode_attribution(self, tmp_path):
        """A sealed-container decode inside read_scope books its physical
        bytes under the ambient scheme; the LRU hit on the second read
        decodes (and books) nothing — the compounding win."""
        cs = ContainerStore(str(tmp_path), container_size=1 << 20,
                            lanes=1, codec="lz4")
        locs = cs.append_chunks([blob(41, 8 * 1024)])
        cid = locs[0][0]
        cs.flush_open()
        reg = metrics.registry("reduction_accounting")
        before = reg.counter("read_physical_bytes__t_ro_cs")
        with accounting.read_scope("t_ro_cs"):
            data = cs.read_container(cid)
        assert reg.counter("read_physical_bytes__t_ro_cs") - before \
            == len(data)
        with accounting.read_scope("t_ro_cs"):
            assert cs.read_container(cid) == data  # LRU hit
        assert reg.counter("read_physical_bytes__t_ro_cs") - before \
            == len(data), "cache hit must not book decoded bytes"


class TestEcDegradedCacheHit:
    def test_lru_hit_after_stripe_fallback(self, tmp_path):
        """A container demoted to stripes (sealed file gone) decodes via
        the EC fallback ONCE; the decoded image lands in the LRU so the
        second read is a cache hit that never touches the stripes."""
        cs = ContainerStore(str(tmp_path), container_size=1 << 20,
                            lanes=1, codec="lz4")
        locs = cs.append_chunks([blob(42, 16 * 1024)])
        cid = locs[0][0]
        cs.flush_open()
        sealed = cs.sealed_file_bytes(cid)
        assert sealed is not None
        os.remove(os.path.join(str(tmp_path), f"{cid}.sealed"))
        calls = []

        def fallback(c):
            calls.append(c)
            return sealed
        cs._stripe_fallback = fallback
        reg = metrics.registry("container_store")
        hits0 = reg.counter("cache_hit")
        data = cs.read_container(cid)
        assert calls == [cid], "first read must reassemble from stripes"
        assert cs.read_container(cid) == data
        assert calls == [cid], "second read must be served by the LRU"
        assert reg.counter("cache_hit") == hits0 + 1
        assert container_store.cache_hit_ratio() > 0.0
        # the ratio also rides /prom as a gauge
        assert reg.snapshot()["gauges"]["cache_hit_ratio"] == \
            pytest.approx(container_store.cache_hit_ratio())


# ------------------------------------------------------- tenant tracking


class TestTenantTracker:
    def test_counters_and_rolling_gauges(self):
        """Fresh tracker on an injected clock: per-(tenant, op) counters
        are exact, rolling p50/p95/p99 gauges refresh on latency notes,
        and an absent tenant id books under ``anon``."""
        trk = tenants.TenantTracker(window_s=300.0, clock=lambda: 0.0)
        trk.note_op("t-ro-u1", "read", 100, latency_s=0.010, now=1.0)
        trk.note_op("t-ro-u1", "read", 200, latency_s=0.030, now=2.0)
        trk.note_op("t-ro-u2", "read", 50, latency_s=0.020, now=2.0)
        trk.note_op(None, "read", 1, now=2.0)
        assert trk.tenant_count() == 3  # u1, u2, anon
        reg = metrics.registry("tenants")
        assert reg.counter("tenant_ops|tenant=t-ro-u1,op=read") == 2
        assert reg.counter("tenant_bytes|tenant=t-ro-u1,op=read") == 300
        assert reg.counter("tenant_ops|tenant=t-ro-u2,op=read") == 1
        assert reg.counter("tenant_ops|tenant=anon,op=read") >= 1
        s = trk.summaries(now=2.0)
        assert set(s["t-ro-u1/read"]) == {"p50", "p95", "p99"}
        assert s["t-ro-u1/read"]["p95"] == pytest.approx(30.0)  # ms
        g = reg.snapshot()["gauges"]
        assert g["tenant_p95_ms|tenant=t-ro-u1,op=read"] == \
            pytest.approx(30.0)

    def test_reset_isolates_windows_not_counters(self):
        trk = tenants.TenantTracker(clock=lambda: 0.0)
        trk.note_op("t-ro-reset", "read", latency_s=0.001, now=1.0)
        assert trk.tenant_count() == 1
        trk.reset()
        assert trk.tenant_count() == 0
        assert trk.summaries(now=1.0) == {}


# ------------------------------------------------------ rollwin quantiles


class TestRollwinQuantiles:
    def test_quantiles_agree_with_summary_p95(self):
        """quantiles((95,)) equals summary()['p95'] by construction (same
        nearest-rank rule), and summary() keeps its exact key set."""
        w = rollwin.RollingWindow(window_s=100.0, clock=lambda: 0.0)
        for i, v in enumerate([5.0, 1.0, 9.0, 3.0, 7.0]):
            w.add(v, now=float(i))
        s = w.summary(now=5.0)
        assert set(s) == {"median", "mean", "max", "p95", "count"}
        assert w.quantiles((95,), now=5.0) == {"p95": s["p95"]}
        q = w.quantiles(now=5.0)
        assert q == {"p50": 5.0, "p95": 9.0, "p99": 9.0}

    def test_quantiles_decay_deterministically(self):
        w = rollwin.RollingWindow(window_s=10.0, clock=lambda: 0.0)
        w.add(100.0, now=0.0)
        w.add(1.0, now=9.0)
        assert w.quantiles(now=9.0) == {"p50": 1.0, "p95": 100.0,
                                        "p99": 100.0}
        # the old sample ages out; the window survives on the fresh one
        assert w.quantiles(now=11.0) == {"p50": 1.0, "p95": 1.0, "p99": 1.0}
        assert w.quantiles(now=99.0) is None

    def test_p2_exact_below_five_samples(self):
        est = rollwin.P2Quantile(0.5)
        assert est.value() == 0.0
        for v in (9.0, 1.0, 5.0):
            est.add(v)
        assert est.value() == 5.0  # nearest-rank median of {1,5,9}
        assert est.count == 3

    def test_p2_bounded_memory_and_accuracy(self):
        """P² keeps five markers regardless of stream length and lands
        near the true quantile on a deterministic uniform stream."""
        rng = random.Random(0x52)
        est = rollwin.P2Quantile(0.95)
        vals = [rng.uniform(0.0, 1000.0) for _ in range(5000)]
        for v in vals:
            est.add(v)
        assert len(est._h) == 5  # O(1) state, not O(n)
        assert est.count == 5000
        true_p95 = sorted(vals)[int(0.95 * 5000) - 1]
        assert abs(est.value() - true_p95) / true_p95 < 0.05

    def test_p2_rejects_degenerate_quantile(self):
        with pytest.raises(ValueError):
            rollwin.P2Quantile(0.0)
        with pytest.raises(ValueError):
            rollwin.P2Quantile(1.0)


# ------------------------------------------------------- flight recorder


class TestFlightRecorder:
    def test_ring_bounds_and_injected_clocks(self):
        """The ring holds exactly ``capacity`` samples (oldest dropped)
        and stamps the injected clocks — fully deterministic."""
        ticks = iter(range(100))
        n = [0]

        def sample():
            n[0] += 1
            return {"v": float(n[0])}
        fr = FlightRecorder("t-ro", sample, interval_s=1.0, capacity=4,
                            clock=lambda: float(next(ticks)),
                            wall=lambda: 1000.0)
        for _ in range(10):
            fr.sample_once()
        snap = fr.snapshot()
        assert snap["daemon"] == "t-ro"
        assert snap["interval_s"] == 1.0 and snap["capacity"] == 4
        assert len(snap["samples"]) == 4
        assert [s["v"] for s in snap["samples"]] == [7.0, 8.0, 9.0, 10.0]
        assert [s["mono"] for s in snap["samples"]] == [6.0, 7.0, 8.0, 9.0]
        assert all(s["t"] == 1000.0 for s in snap["samples"])
        json.dumps(snap)  # the /timeseries body must be JSON-plain

    def test_sample_errors_counted_never_raised(self):
        reg = metrics.registry("flight_recorder")
        before = reg.counter("sample_errors")

        def bad():
            raise RuntimeError("gauge bug")
        fr = FlightRecorder("t-ro-err", bad, capacity=2,
                            clock=lambda: 0.0, wall=lambda: 0.0)
        s = fr.sample_once()  # must not raise
        assert reg.counter("sample_errors") == before + 1
        assert set(s) == {"t", "mono"}  # clock stamps survive the error
        assert len(fr.snapshot()["samples"]) == 1

    def test_status_http_timeseries_roundtrip(self):
        """/timeseries on a daemon status server serves the recorder's
        ring; a recorder-less daemon serves the empty shell, not a 404."""
        fr = FlightRecorder("t-ro-http", lambda: {"g": 1.0}, capacity=8,
                            clock=lambda: 0.0, wall=lambda: 0.0)
        fr.sample_once()
        srv = StatusHttpServer("t-ro-http", port=0, recorder=fr).start()
        try:
            host, port = srv.addr
            doc = json.loads(_get(f"http://{host}:{port}/timeseries"))
        finally:
            srv.stop()
        assert doc["daemon"] == "t-ro-http"
        assert [s["g"] for s in doc["samples"]] == [1.0]
        bare = StatusHttpServer("t-ro-bare", port=0).start()
        try:
            host, port = bare.addr
            doc = json.loads(_get(f"http://{host}:{port}/timeseries"))
        finally:
            bare.stop()
        assert doc["samples"] == [] and doc["capacity"] == 0


# ----------------------------------------------------------- slo report


class TestSloReport:
    SAMPLES = [
        {"t": 1.0, "mono": 1.0, "read_p95_ms": 10.0, "cache_hit_ratio": 0.8},
        {"t": 2.0, "mono": 2.0, "read_p95_ms": 10.0, "cache_hit_ratio": 0.8},
        {"t": 3.0, "mono": 3.0, "read_p95_ms": 20.0, "cache_hit_ratio": 0.8},
        {"t": 4.0, "mono": 4.0, "read_p95_ms": 20.0, "cache_hit_ratio": 0.8},
    ]

    def test_direction_aware_regression_flags(self):
        agg = slo_report.aggregate(self.SAMPLES, baseline_frac=0.5)
        rows = {r["gauge"]: r for r in agg["gauges"]}
        assert "t" not in rows and "mono" not in rows
        assert rows["read_p95_ms"]["regressed"] is True
        assert rows["read_p95_ms"]["rel_change"] == pytest.approx(1.0)
        assert rows["cache_hit_ratio"]["regressed"] is False
        assert agg["regressions"] == ["read_p95_ms"]
        assert agg["verdict"] == "REGRESSED"

    def test_down_direction_and_unknown_gauges(self):
        samples = [{"cache_hit_ratio": 0.9, "mystery": 1.0},
                   {"cache_hit_ratio": 0.9, "mystery": 1.0},
                   {"cache_hit_ratio": 0.3, "mystery": 100.0},
                   {"cache_hit_ratio": 0.3, "mystery": 100.0}]
        agg = slo_report.aggregate(samples, baseline_frac=0.5)
        rows = {r["gauge"]: r for r in agg["gauges"]}
        assert rows["cache_hit_ratio"]["regressed"] is True  # ratio fell
        assert rows["mystery"]["direction"] == "none"
        assert rows["mystery"]["regressed"] is False  # unknown: never flags
        assert agg["regressions"] == ["cache_hit_ratio"]

    def test_jitter_floor_does_not_flag(self):
        samples = [{"read_p95_ms": 10.0}, {"read_p95_ms": 10.0},
                   {"read_p95_ms": 11.0}, {"read_p95_ms": 11.0}]
        agg = slo_report.aggregate(samples, baseline_frac=0.5)
        assert agg["verdict"] == "OK"  # +10% sits under the 25% floor

    def test_format_table_golden(self):
        agg = slo_report.aggregate(self.SAMPLES, baseline_frac=0.5)
        golden = (
            "slo report: 4 samples, baseline window = first/last 50%\n"
            "verdict: REGRESSED (read_p95_ms)\n"
            "\n"
            "gauge                          baseline    current"
            "    drift  flag\n"
            "cache_hit_ratio                   0.800      0.800"
            "     0.0%     -\n"
            "read_p95_ms                      10.000     20.000"
            "   100.0%  REGR")
        assert slo_report.format_table(agg) == golden

    def test_load_samples_shapes(self):
        assert slo_report._load_samples([{"a": 1}]) == [{"a": 1}]
        assert slo_report._load_samples(
            {"daemon": "dn", "samples": [{"a": 1}]}) == [{"a": 1}]
        assert slo_report._load_samples(
            {"value": 9.0, "read": {"read_p95_ms": 3.0}}) == \
            [{"read_p95_ms": 3.0}]
        assert slo_report._load_samples({"b": 2}) == [{"b": 2}]
        with pytest.raises(ValueError):
            slo_report._load_samples("nope")

    def test_accepts_bench_json_via_input(self, tmp_path, capsys):
        """bench.py's one JSON line feeds straight into slo_report
        --input (the 'read' block becomes a one-sample series)."""
        doc = {"value": 12.5, "unit": "MB/s",
               "read": {"read_amplification": 0.2, "cache_hit_ratio": 0.8,
                        "read_p95_ms": 4.0, "tenant_count": 1}}
        path = tmp_path / "bench.json"
        path.write_text(json.dumps(doc))
        rc = slo_report.main(["--input", str(path), "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["samples"] == 1 and out["verdict"] == "OK"
        gauges = {r["gauge"] for r in out["gauges"]}
        assert {"read_amplification", "cache_hit_ratio",
                "read_p95_ms"} <= gauges


class TestSloTrend:
    # injected step regression in read_p95_ms; cache_hit_ratio flat
    SAMPLES = [{"t": float(i + 1), "mono": float(i + 1),
                "read_p95_ms": 10.0 if i < 4 else 20.0,
                "cache_hit_ratio": 0.8} for i in range(8)]

    def test_trend_flags_injected_regression_deterministically(self):
        tr = slo_report.trend(self.SAMPLES)
        assert tr["regressions"] == ["read_p95_ms"]
        assert tr["verdict"] == "REGRESSED"
        rows = {r["metric"]: r for r in tr["metrics"]}
        r = rows["read_p95_ms"]
        assert r["slope"] == pytest.approx(80.0 / 42.0)
        assert r["changepoint"]["index"] == 4
        assert r["changepoint"]["before"] == pytest.approx(10.0)
        assert r["changepoint"]["after"] == pytest.approx(20.0)
        assert rows["cache_hit_ratio"]["regressed"] is False

    def test_flat_series_never_flags(self):
        flat = [{"read_p95_ms": 10.0, "cache_hit_ratio": 0.8}
                for _ in range(8)]
        tr = slo_report.trend(flat)
        assert tr["regressions"] == [] and tr["verdict"] == "OK"

    def test_slow_ramp_caught_by_slope_not_changepoint(self):
        """A creep with no step still regresses: the fitted total drift
        clears the jitter floor even though no single shift does."""
        ramp = [{"write_p95_ms": 10.0 + i} for i in range(10)]
        tr = slo_report.trend(ramp)
        assert tr["regressions"] == ["write_p95_ms"]

    def test_down_direction_metric(self):
        falling = [{"cache_hit_ratio": 0.8 if i < 4 else 0.2}
                   for i in range(8)]
        tr = slo_report.trend(falling)
        assert tr["regressions"] == ["cache_hit_ratio"]

    def test_format_trend_table_golden(self):
        golden = (
            "slo trend: 8 samples, jitter floor = 25%\n"
            "verdict: REGRESSED (read_p95_ms)\n"
            "\n"
            "metric                            first       last"
            "      slope   cp  flag\n"
            "cache_hit_ratio                   0.800      0.800"
            "     0.0000    4     -\n"
            "read_p95_ms                      10.000     20.000"
            "     1.9048    4  REGR")
        assert slo_report.format_trend_table(
            slo_report.trend(self.SAMPLES)) == golden

    def test_trend_from_archive_directory(self, tmp_path, capsys):
        """Satellite: --input accepts a flight-archive DIRECTORY and the
        trend verdict survives a restart — the samples come back off
        disk, torn tail and all."""
        from hdrf_tpu.utils.flight_archive import FlightArchive
        d = str(tmp_path / "arch")
        arch = FlightArchive(d)
        for s in self.SAMPLES:
            arch.append(s)
        arch.close()
        seg = sorted(os.listdir(d))[-1]
        with open(os.path.join(d, seg), "ab") as f:
            f.write(b'{"torn": ')           # crash mid-append
        rc = slo_report.main(["--input", d, "--trend", "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["samples"] == 8
        assert out["regressions"] == ["read_p95_ms"]
        # flat archived series stays unflagged through the same path
        d2 = str(tmp_path / "flat")
        arch2 = FlightArchive(d2)
        for _ in range(8):
            arch2.append({"read_p95_ms": 10.0})
        arch2.close()
        rc = slo_report.main(["--input", d2, "--trend", "--json"])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["verdict"] == "OK"

    def test_guard_direction_aware_with_blast_radius(self):
        base = [{"read_p95_ms": 10.0, "dedup_ratio": 2.0, "noise": 1.0}
                for _ in range(4)]
        worse = [{"read_p95_ms": 20.0, "dedup_ratio": 2.0, "noise": 9.0}
                 for _ in range(4)]
        g = slo_report.guard(base, worse)
        assert g["regressed"] is True
        rows = {r["metric"]: r for r in g["rows"]}
        assert rows["read_p95_ms"]["regressed"] is True
        assert rows["noise"]["regressed"] is False  # unknown direction
        # narrowing to the change's blast radius vetoes unrelated gauges
        g = slo_report.guard(base, worse, gauges=("dedup_ratio",))
        assert g["regressed"] is False
        assert [r["metric"] for r in g["rows"]] == ["dedup_ratio"]

    def test_guard_improvement_never_rolls_back(self):
        base = [{"read_p95_ms": 20.0} for _ in range(4)]
        better = [{"read_p95_ms": 10.0} for _ in range(4)]
        assert slo_report.guard(base, better)["regressed"] is False


# ------------------------------------------------------------ cluster e2e


@pytest.fixture(scope="class")
def ro_cluster():
    with MiniCluster(n_datanodes=1, replication=1, block_size=256 * 1024,
                     dn_config_overrides={"status_port": 0}) as mc:
        gw = HttpGateway(mc.namenode.addr).start()
        try:
            yield mc, gw
        finally:
            gw.stop()


class TestClusterReadObservability:
    def test_two_tenant_isolation(self, ro_cluster):
        """Two clients reading the same blocks stay apart on the tenants
        registry: ops/bytes/latency gauges key by the _client identity the
        RPC-kwarg and DT-header channels carry."""
        mc, _ = ro_cluster
        data = blob(11, 96 * 1024)
        with mc.client("t-ro-writer") as c:
            c.write("/ro/iso", data, scheme="dedup")
        with mc.client("t-ro-alice") as a, mc.client("t-ro-bob") as b:
            for _ in range(3):
                assert a.read("/ro/iso") == data
            assert b.read("/ro/iso") == data
        reg = metrics.registry("tenants")
        assert _await(lambda:
                      reg.counter("tenant_ops|tenant=t-ro-alice,op=read")
                      == 3
                      and reg.counter("tenant_ops|tenant=t-ro-bob,op=read")
                      == 1)
        assert reg.counter("tenant_bytes|tenant=t-ro-alice,op=read") \
            == 3 * len(data)
        assert reg.counter("tenant_bytes|tenant=t-ro-bob,op=read") \
            == len(data)
        g = reg.snapshot()["gauges"]
        assert "tenant_p95_ms|tenant=t-ro-alice,op=read" in g
        # prom exposition renders the |k=v suffix as real labels
        host, port = mc.datanodes[0]._status.addr
        text = _get(f"http://{host}:{port}/prom").decode()
        assert 'tenant="t-ro-alice"' in text
        assert 'tenant="t-ro-bob"' in text

    def test_short_circuit_read_attributed(self, ro_cluster):
        """The AF_UNIX fd-grant path carries _client too (the client
        stamps it into the JSON request; the DN books read_sc ops)."""
        mc, _ = ro_cluster
        data = blob(12, 64 * 1024)
        with mc.client("t-ro-writer") as c:
            c.write("/ro/sc", data, scheme="direct")
        with mc.client("t-ro-scuser") as c:
            assert c.read("/ro/sc") == data
        reg = metrics.registry("tenants")
        assert _await(lambda: reg.counter(
            "tenant_ops|tenant=t-ro-scuser,op=read_sc") >= 1)

    def test_read_plane_rides_health_report(self, ro_cluster):
        """The DN stats payload (heartbeat /health surface) carries the
        serving-path aggregate: cache hit ratio, per-scheme read
        amplification, tenant summaries."""
        mc, _ = ro_cluster
        data = blob(13, 64 * 1024)
        with mc.client("t-ro-health") as c:
            c.write("/ro/health", data, scheme="dedup")
            assert c.read("/ro/health") == data
        rp = mc.datanodes[0]._stats()["read_plane"]
        assert 0.0 <= rp["container_cache_hit_ratio"] <= 1.0
        assert "dedup" in rp["read_amplification"]
        amp = rp["read_amplification"]["dedup"]
        assert amp["logical_bytes"] > 0
        assert any(k.startswith("t-ro-") for k in rp["tenants"])

    def test_dn_and_gateway_timeseries(self, ro_cluster):
        """/timeseries round-trips on both surfaces: the DN's own status
        server and the gateway (which pulls the NN ring over the
        flight_timeseries RPC)."""
        mc, gw = ro_cluster
        dn = mc.datanodes[0]
        dn.flight.sample_once()
        host, port = dn._status.addr
        doc = json.loads(_get(f"http://{host}:{port}/timeseries"))
        assert doc["daemon"] == dn.dn_id
        assert doc["samples"]
        last = doc["samples"][-1]
        for key in ("storage_ratio", "container_cache_hit_ratio",
                    "read_p95_ms", "write_p95_ms", "tenant_count",
                    "breakers_open", "t", "mono"):
            assert key in last, f"DN flight sample missing {key}"
        mc.namenode.flight.sample_once()
        doc = json.loads(
            _get(f"http://{gw.addr[0]}:{gw.addr[1]}/timeseries"))
        assert doc["daemon"] == "namenode"
        assert doc["samples"]
        last = doc["samples"][-1]
        for key in ("blocks", "datanodes", "datanodes_live",
                    "under_replicated", "safemode", "tenant_count"):
            assert key in last, f"NN flight sample missing {key}"
        assert last["datanodes_live"] >= 1

    def test_timeseries_metric_filter_strictly_smaller(self, ro_cluster):
        """Satellite bar: a ?metric= filtered pull is strictly smaller
        than the unfiltered one, on the DN status server and the gateway
        alike (the filter runs server-side, not in the client)."""
        mc, gw = ro_cluster
        dn = mc.datanodes[0]
        dn.flight.sample_once()
        mc.namenode.flight.sample_once()
        host, port = dn._status.addr
        full = _get(f"http://{host}:{port}/timeseries")
        slim = _get(f"http://{host}:{port}/timeseries"
                    f"?metric=storage_ratio")
        assert len(slim) < len(full)
        doc = json.loads(slim)
        assert doc["samples"]
        assert set(doc["samples"][-1]) == {"t", "mono", "storage_ratio"}
        gfull = _get(f"http://{gw.addr[0]}:{gw.addr[1]}/timeseries")
        gslim = _get(f"http://{gw.addr[0]}:{gw.addr[1]}/timeseries"
                     f"?metric=blocks")
        assert len(gslim) < len(gfull)
        # ?since= far in the future empties the series but keeps the shell
        doc = json.loads(_get(f"http://{host}:{port}/timeseries"
                              f"?since=9e18"))
        assert doc["samples"] == [] and doc["daemon"] == dn.dn_id

    def test_gateway_cluster_scope_merges_all_daemons(self, ro_cluster):
        """?scope=cluster fans out to every live DN over the
        flight_timeseries DT op, merges with the NN series, and a &step=
        rollup bounds the response."""
        mc, gw = ro_cluster
        mc.datanodes[0].flight.sample_once()
        mc.namenode.flight.sample_once()
        doc = json.loads(_get(f"http://{gw.addr[0]}:{gw.addr[1]}"
                              f"/timeseries?scope=cluster"))
        assert doc["scope"] == "cluster"
        assert "namenode" in doc["daemons"]
        assert any(d != "namenode" for d in doc["daemons"])
        assert doc["samples"]
        merged = doc["samples"][-1]
        assert merged["nodes"] >= 1 and "t" in merged
        # DN gauges and NN gauges land in one merged series
        names = set().union(*(set(s) for s in doc["samples"]))
        assert "storage_ratio" in names and "datanodes_live" in names
        rolled = json.loads(_get(f"http://{gw.addr[0]}:{gw.addr[1]}"
                                 f"/timeseries?scope=cluster&step=60"))
        assert rolled["rollup"]
        row = rolled["rollup"][-1]
        assert {"min", "max", "mean", "last"} <= set(
            next(iter(row["gauges"].values())))

    def test_nn_rpc_latency_histogram_and_p99_gauge(self, ro_cluster):
        """Satellite: every NN RPC books nn_rpc_us|method=<name> and the
        NN flight sample carries the rolling p99 gauge."""
        mc, _ = ro_cluster
        with mc.client("t-ro-rpc") as c:
            c.ls("/")
        hists = metrics.registry("rpc.namenode").snapshot()["histograms"]
        assert hists["nn_rpc_us|method=listing"]["count"] >= 1
        sample = mc.namenode.flight.sample_once()
        assert "nn_rpc_p99_ms" in sample
        assert sample["nn_rpc_p99_ms"] > 0.0

    def test_read_smoke_mostly_attributed(self, ro_cluster):
        """Acceptance bar: >= 95% of the read smoke's serve wall clock is
        attributed to named phases (aggregated over the data-bearing read
        timelines, weighted by wall)."""
        mc, _ = ro_cluster
        profiler.reset()
        data = blob(14, 240 * 1024)
        with mc.client("t-ro-smoke") as c:
            c.write("/ro/smoke", data, scheme="dedup")
            for _ in range(5):
                assert c.read("/ro/smoke") == data
        snaps = [t for t in profiler.read_timelines_snapshot()
                 if t["nbytes"] > 0]
        assert snaps, "no data-bearing read timeline recorded"
        wall = sum(t["profile"]["wall_s"] for t in snaps)
        attributed = sum(t["profile"]["wall_s"]
                         * t["profile"]["attributed_frac"] for t in snaps)
        assert wall > 0
        assert attributed / wall >= 0.95, \
            f"only {attributed / wall:.1%} of read wall attributed"
