"""Lazy persist / RAM_DISK (RamDiskReplicaTracker.java:38, LazyWriter):
writes under the lazy_persist storage policy land on a shm-backed RAM
volume, a lazy writer shadows them onto DISK, persisted copies are evicted
under RAM pressure, and the data survives simulated RAM loss because the
disk copy exists."""

import os
import shutil
import time

import pytest

from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.utils.throttler import Throttler


def _ram_vol(dn):
    return next(v for v in dn.volumes.volumes
                if v.storage_type == "RAM_DISK")


def _disk_vol(dn):
    return next(v for v in dn.volumes.volumes
                if v.storage_type != "RAM_DISK")


@pytest.fixture()
def cluster():
    with MiniCluster(n_datanodes=1, replication=1, block_size=1 << 20,
                     volume_types=["RAM_DISK", "DISK"],
                     dn_config_overrides={
                         "lazy_writer_interval_s": 0.2,
                         "ram_disk_capacity": 256 * 1024}) as mc:
        yield mc


def test_lazy_persist_write_lands_in_ram_then_disk(cluster):
    dn = cluster.datanodes[0]
    data = os.urandom(100_000)
    with cluster.client() as c:
        c.mkdir("/hot")
        c.set_storage_policy("/hot", "lazy_persist")
        c.write("/hot/f", data)
        bid = c._call("get_block_locations", path="/hot/f")[
            "blocks"][0]["block_id"]
        # the replica routed to the shm-backed RAM volume
        ram, disk = _ram_vol(dn), _disk_vol(dn)
        assert ram.root.startswith("/dev/shm/")
        assert ram.replicas.get_meta(bid) is not None
        # ... and the lazy writer shadows it onto DISK within the window
        deadline = time.monotonic() + 5
        while disk.replicas.get_meta(bid) is None:
            assert time.monotonic() < deadline, "lazy writer never persisted"
            time.sleep(0.05)
        # reads still come from RAM (ownership unchanged)
        assert dn.volumes._where[bid] == ram.vol_id
        assert c.read("/hot/f") == data


def test_eviction_under_ram_pressure(cluster):
    dn = cluster.datanodes[0]
    with cluster.client() as c:
        c.mkdir("/hot")
        c.set_storage_policy("/hot", "lazy_persist")
        # exceed the 256 KiB RAM budget
        blobs = {f"/hot/f{i}": os.urandom(120_000) for i in range(4)}
        for p, b in blobs.items():
            c.write(p, b)
        ram = _ram_vol(dn)
        deadline = time.monotonic() + 6
        while ram.used_bytes() > dn.config.ram_disk_capacity:
            assert time.monotonic() < deadline, \
                f"no eviction: ram holds {ram.used_bytes()}"
            time.sleep(0.1)
        # every file still reads back (from RAM or evicted-to-disk copies)
        for p, b in blobs.items():
            assert c.read(p) == b


def test_survives_simulated_ram_loss(cluster):
    """Machine reboot analog: wipe the shm dir while the DN is down; the
    lazy-persisted disk copy serves."""
    dn = cluster.datanodes[0]
    data = os.urandom(80_000)
    with cluster.client() as c:
        c.mkdir("/hot")
        c.set_storage_policy("/hot", "lazy_persist")
        c.write("/hot/f", data)
        disk = _disk_vol(dn)
        bid = c._call("get_block_locations", path="/hot/f")[
            "blocks"][0]["block_id"]
        deadline = time.monotonic() + 5
        while disk.replicas.get_meta(bid) is None:
            assert time.monotonic() < deadline
            time.sleep(0.05)
    ram_root = _ram_vol(dn).root
    cluster.stop_datanode(0)
    shutil.rmtree(ram_root)            # RAM contents gone
    cluster.restart_datanode(0)
    cluster.wait_for_datanodes(1)
    with cluster.client() as c:
        deadline = time.monotonic() + 10
        while True:
            try:
                assert c.read("/hot/f") == data
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)


def test_throttler_enforces_floor():
    """DataTransferThrottler.java:28 analog: pushing 1 MiB through a
    2 MiB/s bucket takes >= ~0.4s; an unthrottled path doesn't block."""
    t = Throttler(2 * 1024 * 1024)
    t0 = time.monotonic()
    for _ in range(16):
        t.throttle(64 * 1024)
    elapsed = time.monotonic() - t0
    assert elapsed >= 0.35, f"too fast: {elapsed:.3f}s"
    t2 = Throttler(0)      # disabled
    t0 = time.monotonic()
    for _ in range(16):
        t2.throttle(64 * 1024)
    assert time.monotonic() - t0 < 0.05


def test_rereplication_is_throttled_but_client_io_is_not():
    """Kill a DN holding one replica: the NN-commanded re-replication leg
    rides the balance throttler; client pipeline writes never touch it.
    Asserted via the throttler's byte counter, not wall-clock — timing
    comparisons are meaningless on a loaded 1-vCPU host."""
    with MiniCluster(n_datanodes=3, replication=2, block_size=1 << 20,
                     heartbeat_s=0.1, dead_node_s=0.8,
                     dn_config_overrides={
                         "balancer_bandwidth": 400 * 1024}) as mc:
        data = os.urandom(400_000)
        with mc.client() as c:
            c.write("/t/f", data)
            # a client write gates NOTHING through the balance throttlers
            assert all(dn.balance_throttler.throttled_bytes == 0
                       for dn in mc.datanodes)
            loc = c._call("get_block_locations", path="/t/f")
            holders = {d["dn_id"] for b in loc["blocks"]
                       for d in b["locations"]}
            victim = next(i for i in range(3)
                          if f"dn-{i}" in holders)
            mc.kill_datanode(victim)
            # re-replication completes despite the throttle...
            deadline = time.monotonic() + 25
            while True:
                locs = c._call("get_block_locations", path="/t/f")
                live = {d["dn_id"] for b in locs["blocks"]
                        for d in b["locations"]} - {f"dn-{victim}"}
                if len(live) >= 2:
                    break
                assert time.monotonic() < deadline, "re-replication stalled"
                time.sleep(0.2)
            # ...and the surviving source DN gated its push through the
            # throttler (the dedup path sends unique chunk bytes)
            assert sum(dn.balance_throttler.throttled_bytes
                       for dn in mc.datanodes if dn is not None) > 0


def test_ram_volume_death_fails_over_to_disk_shadow(cluster):
    """Eject the RAM volume after the lazy writer persisted: the block is
    RESCUED by its disk shadow, not declared lost (the scenario the lazy
    writer exists for)."""
    dn = cluster.datanodes[0]
    data = os.urandom(50_000)
    with cluster.client() as c:
        c.mkdir("/hot")
        c.set_storage_policy("/hot", "lazy_persist")
        c.write("/hot/f", data)
        bid = c._call("get_block_locations", path="/hot/f")[
            "blocks"][0]["block_id"]
        ram, disk = _ram_vol(dn), _disk_vol(dn)
        deadline = time.monotonic() + 5
        while disk.replicas.get_meta(bid) is None:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        lost = dn.volumes.eject(ram.vol_id)
        assert bid not in lost                 # rescued by the shadow
        assert dn.volumes._where[bid] == disk.vol_id
        assert c.read("/hot/f") == data
