"""Federation: multiple nameservices over one DataNode set
(BPOfferService.java:57 per namespace; MiniDFSNNTopology-style topology).

Block pools are disjoint block-id ranges ((pool_index << 48) | seq), so a
DN partitions its reports per nameservice with a shift and every NN
pool-guards incoming reports — a replica belonging to ns1 must never be
invalidated by ns0's "replica of a deleted file" rule."""

import time

import numpy as np
import pytest

from hdrf_tpu.testing.minicluster import MiniCluster


def _payload(seed: int, n: int = 250_000) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, n, np.uint8).tobytes()


class TestFederation:
    def test_two_nameservices_share_one_dn_set(self):
        """Two independent namespaces, one DN set serving both: writes in
        each NS are invisible to the other, blocks land in disjoint
        pools, and full block reports to BOTH NNs never cross-invalidate."""
        with MiniCluster(n_datanodes=3, replication=2,
                         nameservices=2, block_size=1 << 20) as mc:
            d0, d1 = _payload(0), _payload(1)
            with mc.client("a", nsi=0) as c0, mc.client("b", nsi=1) as c1:
                c0.write("/shared/f", d0)
                c1.write("/shared/f", d1)      # same path, other namespace
                assert c0.read("/shared/f") == d0
                assert c1.read("/shared/f") == d1
                # namespaces are independent: ns1's tree has only its file
                assert {e["name"] for e in c0.ls("/")} == {"shared"}
                c1.mkdir("/only-ns1")
                with pytest.raises(Exception):
                    c0.stat("/only-ns1")
            # pools are disjoint id ranges
            bids0 = set(mc.ns[0]["active"]._blocks)
            bids1 = set(mc.ns[1]["active"]._blocks)
            assert bids0 and bids1 and not (bids0 & bids1)
            assert all(b >> 48 == 0 for b in bids0)
            assert all(b >> 48 == 1 for b in bids1)
            # survive a full-report cycle: neither NS invalidated the
            # other's replicas (the round-2 hazard of dual reporting)
            for dn in mc.datanodes:
                dn._send_block_report()
            time.sleep(0.8)
            with mc.client("a2", nsi=0) as c0, mc.client("b2", nsi=1) as c1:
                assert c0.read("/shared/f") == d0
                assert c1.read("/shared/f") == d1

    def test_independent_failover(self):
        """VERDICT r3 #6 'done' criterion: one NS fails over; the other
        keeps serving undisturbed; both serve afterwards."""
        with MiniCluster(n_datanodes=2, replication=2, ha=True,
                         nameservices=2, block_size=1 << 20) as mc:
            d0, d1 = _payload(10), _payload(11)
            with mc.client("a", nsi=0) as c0, mc.client("b", nsi=1) as c1:
                c0.write("/f0", d0)
                c1.write("/f1", d1)
                time.sleep(0.8)  # standbys tail the edits
                mc.failover(nsi=1)
                # ns0 untouched mid-failover
                assert c0.read("/f0") == d0
                # ns1 serves through its NEW active (client retries)
                assert c1.read("/f1") == d1
                c1.write("/f2", d1)
                assert c1.read("/f2") == d1
                # and ns0 can still fail over independently afterwards
                mc.failover(nsi=0)
                assert c0.read("/f0") == d0

    def test_dn_re_replication_stays_within_pool(self):
        """A dead DN triggers re-replication in BOTH namespaces, each
        driven by its own NN over the shared DN set."""
        with MiniCluster(n_datanodes=3, replication=2,
                         nameservices=2, block_size=1 << 20) as mc:
            d0, d1 = _payload(20), _payload(21)
            with mc.client("a", nsi=0) as c0, mc.client("b", nsi=1) as c1:
                c0.write("/r0", d0)
                c1.write("/r1", d1)
                mc.kill_datanode(0)
                deadline = time.time() + 15
                def healthy(nn):
                    return all(
                        len({d for d in i.locations
                             if d in nn._datanodes and d != "dn-0"}) >= 2
                        for i in nn._blocks.values())
                while time.time() < deadline:
                    if healthy(mc.ns[0]["active"]) \
                            and healthy(mc.ns[1]["active"]):
                        break
                    time.sleep(0.5)
                assert healthy(mc.ns[0]["active"]), "ns0 never re-replicated"
                assert healthy(mc.ns[1]["active"]), "ns1 never re-replicated"
                assert c0.read("/r0") == d0
                assert c1.read("/r1") == d1
