"""Snapshots + quotas (namenode/snapshot 5.6 kLoC + quota subsystem analog):
point-in-time reads through /.snapshot paths, block retention across deletes,
namespace/space quota enforcement, content summary."""

import numpy as np
import pytest

from hdrf_tpu.proto.rpc import RpcError
from hdrf_tpu.testing.minicluster import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_datanodes=3, replication=2) as mc:
        yield mc


class TestSnapshots:
    def test_snapshot_read_after_delete(self, cluster):
        payload = np.random.default_rng(0).integers(
            0, 256, size=150_000, dtype=np.uint8).tobytes()
        with cluster.client("snap") as c:
            c.write("/snapdir/f", payload, scheme="dedup_lz4")
            c.allow_snapshot("/snapdir")
            c.create_snapshot("/snapdir", "s1")
            assert c.list_snapshots("/snapdir") == ["s1"]
            c.delete("/snapdir/f")
            assert not c.exists("/snapdir/f")
            # the frozen view still reads the full content
            assert c.read("/snapdir/.snapshot/s1/f") == payload
            assert c.stat("/snapdir/.snapshot/s1/f")["length"] == len(payload)
            # dropping the snapshot releases the blocks
            c.delete_snapshot("/snapdir", "s1")
            with pytest.raises(Exception):
                c.read("/snapdir/.snapshot/s1/f")

    def test_snapshot_isolated_from_new_writes(self, cluster):
        with cluster.client("snap2") as c:
            c.write("/sd2/a", b"v1" * 1000)
            c.allow_snapshot("/sd2")
            c.create_snapshot("/sd2", "before")
            c.write("/sd2/b", b"v2" * 1000)
            names = {e["name"] for e in c.ls("/sd2/.snapshot/before")}
            assert names == {"a"}
            assert {e["name"] for e in c.ls("/sd2")} == {"a", "b"}

    def test_create_snapshot_requires_allow(self, cluster):
        with cluster.client("snap3") as c:
            c.mkdir("/sd3")
            with pytest.raises(RpcError, match="not snapshottable"):
                c.create_snapshot("/sd3", "x")

    def test_snapshot_survives_nn_restart(self, cluster):
        with cluster.client("snap4") as c:
            c.write("/sd4/f", b"persist" * 500)
            c.allow_snapshot("/sd4")
            c.create_snapshot("/sd4", "keep")
            c.delete("/sd4/f")
        cluster.restart_namenode()
        cluster.wait_for_datanodes(3)
        with cluster.client("snap4b") as c:
            assert c.read("/sd4/.snapshot/keep/f") == b"persist" * 500


class TestQuotas:
    def test_namespace_quota(self, cluster):
        with cluster.client("q1") as c:
            c.mkdir("/q1")
            c.set_quota("/q1", namespace_quota=2)
            c.write("/q1/a", b"x")
            with pytest.raises(RpcError, match="namespace quota"):
                c.write("/q1/b", b"y")
            c.set_quota("/q1")  # clear
            c.write("/q1/b", b"y")

    def test_space_quota(self, cluster):
        with cluster.client("q2") as c:
            c.mkdir("/q2")
            # block_size is 1 MiB in MiniCluster; one block fits, two don't
            c.set_quota("/q2", space_quota=1 << 20)
            with pytest.raises(RpcError, match="space quota"):
                c.write("/q2/big", b"z" * (2 << 20))

    def test_content_summary(self, cluster):
        with cluster.client("q3") as c:
            c.write("/cs/x/f1", b"a" * 1000)
            c.write("/cs/f2", b"b" * 500)
            s = c.content_summary("/cs")
            assert s["files"] == 2 and s["length"] == 1500
            assert s["dirs"] >= 2
