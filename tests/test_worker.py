"""Co-located reduction worker: a SEPARATE process serving the DN's hot
ops over the streaming protocol (the BASELINE.json north-star deployment:
BlockReceiver streams block packets to the worker; bytes land in HBM).

On the CPU test mesh the worker backend auto-resolves to native — the
plumbing (process boundary, streaming ingest, completion flow, fallback)
is identical; the real-chip variant runs in test_tpu_e2e.py."""

from __future__ import annotations

import numpy as np
import pytest

from hdrf_tpu.config import CdcConfig
from hdrf_tpu.ops.dispatch import gear_mask
from hdrf_tpu.server.reduction_worker import (ReductionWorker, WorkerClient,
                                              spawn_local_worker)
from hdrf_tpu.testing.minicluster import MiniCluster

RNG = np.random.default_rng(51)


def _bytes(n):
    return RNG.integers(0, 256, size=n, dtype=np.uint8).tobytes()


class TestWorkerProtocol:
    @pytest.fixture(scope="class")
    def worker(self):
        w = ReductionWorker(backend="native").start()
        yield w
        w.stop()

    def test_reduce_matches_oracle(self, worker):
        from hdrf_tpu import native

        cdc = CdcConfig()
        data = _bytes(300_000)
        c = WorkerClient(worker.addr)
        cuts, digs = c.reduce(data, cdc)
        wc = native.cdc_chunk(np.frombuffer(data, np.uint8), gear_mask(cdc),
                              cdc.min_chunk, cdc.max_chunk)
        starts = np.concatenate([[0], wc[:-1]]).astype(np.uint64)
        wd = native.sha256_batch(np.frombuffer(data, np.uint8), starts,
                                 (wc - starts).astype(np.uint64))
        np.testing.assert_array_equal(cuts, wc.astype(np.int64))
        np.testing.assert_array_equal(digs, wd)
        c.close()

    def test_streaming_matches_whole(self, worker):
        cdc = CdcConfig()
        data = _bytes(500_000)
        c = WorkerClient(worker.addr)
        whole = c.reduce(data, cdc)
        pkts = [data[i:i + 64 * 1024] for i in range(0, len(data), 64 * 1024)]
        streamed = c.reduce_stream(iter(pkts), cdc)
        np.testing.assert_array_equal(whole[0], streamed[0])
        np.testing.assert_array_equal(whole[1], streamed[1])
        c.close()

    def test_compress_roundtrip(self, worker):
        from hdrf_tpu import native

        data = (b"the quick brown fox " * 5000)[:80_000]
        c = WorkerClient(worker.addr)
        comp = c.compress("lz4", data)
        assert native.lz4_decompress(comp, len(data)) == data
        c.close()

    def test_compress_batch_roundtrip(self, worker):
        from hdrf_tpu import native

        datas = [(b"lorem ipsum dolor " * 4000)[:60_000], _bytes(30_000),
                 b"\x00" * 50_000]
        c = WorkerClient(worker.addr)
        outs = c.compress_batch("lz4", datas)
        assert len(outs) == len(datas)
        for d, comp in zip(datas, outs):
            assert native.lz4_decompress(comp, len(d)) == d
        # batch must equal the per-item op byte for byte
        assert outs == [c.compress("lz4", d) for d in datas]
        c.close()

    def test_ping_and_stats(self, worker):
        c = WorkerClient(worker.addr)
        assert c.ping()["backend"] == "native"
        before = c.stats()["blocks_reduced"]
        c.reduce(_bytes(10_000), CdcConfig())
        assert c.stats()["blocks_reduced"] == before + 1
        c.close()


class TestWorkerProcess:
    def test_spawn_real_process(self):
        proc, addr = spawn_local_worker(backend="native")
        try:
            c = WorkerClient(addr)
            assert c.ping()["ok"]
            cuts, digs = c.reduce(_bytes(100_000), CdcConfig())
            assert int(cuts[-1]) == 100_000 and digs.shape[1] == 32
            c.close()
        finally:
            proc.terminate()
            proc.wait(timeout=5)


class TestClusterWithWorker:
    def test_out_of_process_reduction_e2e(self):
        """The MiniCluster flag the VERDICT asked for: every dedup write
        flows DN -> worker process; the worker's stats prove it served."""
        with MiniCluster(n_datanodes=2, replication=2, block_size=1 << 20,
                         tpu_worker=True) as mc:
            wc = WorkerClient(mc._worker_addr)
            assert wc.ping()["ok"]
            data = _bytes(1_500_000) + _bytes(200_000) * 2
            with mc.client("w") as c:
                c.write("/w/f", data, scheme="dedup_lz4")
                assert c.read("/w/f") == data
                c.write("/w/g", data[:300_000], scheme="dedup_lz4")
                assert c.read("/w/g") == data[:300_000]
            st = wc.stats()
            assert st["blocks_reduced"] >= 3  # every dedup block offloaded
            wc.close()

    def test_worker_death_falls_back_in_process(self):
        """Kill the worker mid-cluster: writes keep succeeding via the
        in-process fallback (availability over offload)."""
        with MiniCluster(n_datanodes=1, replication=1, block_size=1 << 20,
                         tpu_worker=True) as mc:
            data = _bytes(400_000)
            second = data[:100_000] + _bytes(50_000)
            with mc.client("w") as c:
                c.write("/f1", data, scheme="dedup_lz4")
                mc._worker_proc.terminate()
                mc._worker_proc.wait(timeout=5)
                c.write("/f2", second, scheme="dedup_lz4")
                assert c.read("/f2") == second
                assert c.read("/f1") == data
