"""Multi-chip sharding tests on the 8-device virtual CPU mesh (conftest.py).

Mirrors how the driver's dryrun validates multi-chip compilation: real Mesh +
shard_map + collectives (ppermute halo, psum), executed on virtual devices.
Correctness bar: sharded outputs are bit-identical to the single-device JAX
path and to the native C++ oracle.
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hdrf_tpu import native
from hdrf_tpu.config import CdcConfig
from hdrf_tpu.ops import gear
from hdrf_tpu.ops.dispatch import gear_mask
from hdrf_tpu.parallel import (
    gear_candidates_sharded,
    make_mesh,
    reduction_step,
    sha256_lanes_sharded,
)
from hdrf_tpu.parallel.sharded import _segment_sha_pad


def _data(n: int, seed: int = 3) -> np.ndarray:
    rng = np.random.default_rng(seed)
    a = rng.integers(0, 256, size=n, dtype=np.uint8)
    # plant a long zero run + a repeat to exercise degenerate hash regions
    a[n // 3:n // 3 + 4096] = 0
    a[n // 2:n // 2 + 2048] = a[:2048]
    return a


@pytest.mark.parametrize("n_data,n_seq", [(1, 8), (2, 4), (1, 2)])
def test_sharded_candidates_match_native(n_data, n_seq):
    mesh = make_mesh(n_data=n_data, n_seq=n_seq,
                     devices=jax.devices()[:n_data * n_seq])
    mask = gear_mask(CdcConfig(mask_bits=10))
    a = _data(1 << 18)
    got = gear_candidates_sharded(a, mask, mesh)
    want = native.gear_candidates(a, mask)
    np.testing.assert_array_equal(got, want)


def test_sharded_candidates_unaligned_length():
    mesh = make_mesh(n_data=1, n_seq=8)
    mask = gear_mask(CdcConfig(mask_bits=9))
    a = _data(100_001, seed=5)  # forces zero-padding to the shard grid
    got = gear_candidates_sharded(a, mask, mesh)
    want = native.gear_candidates(a, mask)
    np.testing.assert_array_equal(got, want)


def test_sharded_sha_lanes_match_hashlib():
    mesh = make_mesh(n_data=8, n_seq=1)
    fn = sha256_lanes_sharded(mesh)
    L, seg = 1024, 192
    rng = np.random.default_rng(9)
    msgs = rng.integers(0, 256, size=(L, seg), dtype=np.uint8)
    pad = _segment_sha_pad(seg)
    buf = np.concatenate([msgs, np.broadcast_to(pad, (L, 64))], axis=1)
    nblocks = np.full(L, seg // 64 + 1, dtype=np.int32)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P("data"))
    got = np.asarray(fn(jax.device_put(buf, sh), jax.device_put(nblocks, sh)))
    for i in range(0, L, 97):
        assert got[i].tobytes() == hashlib.sha256(msgs[i].tobytes()).digest()


def test_reduction_step_end_to_end():
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(n_data=2, n_seq=4)
    seg, per_shard = 512, 1024
    n_bytes = per_shard * 4
    blocks = _data(4 * n_bytes, seed=13).reshape(4, n_bytes)
    step = reduction_step(mesh, seg=seg)
    sharded = jax.device_put(blocks, NamedSharding(mesh, P("data", "seq")))
    mask = gear_mask(CdcConfig(mask_bits=8))
    out = step(sharded, jnp.uint32(mask))
    # candidate words agree with the native scan per block
    words = np.asarray(out["words"])
    total = 0
    for b in range(4):
        (idx,) = np.nonzero(words[b])
        pos = gear._words_to_positions(idx.astype(np.uint32), words[b][idx],
                                       n_bytes)
        want = native.gear_candidates(blocks[b], mask)
        np.testing.assert_array_equal(pos, want)
        total += want.size
    assert int(out["candidates"]) == total
    # segment digests agree with hashlib
    digs = np.asarray(out["digests"])
    for b, s in [(0, 0), (1, 3), (3, n_bytes // seg - 1)]:
        seg_bytes = blocks[b, s * seg:(s + 1) * seg].tobytes()
        assert digs[b, s].tobytes() == hashlib.sha256(seg_bytes).digest()


class TestRealPipelineSharded:
    """The VERDICT's ask: the ACTUAL variable-chunk pipeline on the mesh,
    digests asserted against the single-device/native oracle."""

    def test_variable_chunks_match_oracle(self):
        import jax

        from hdrf_tpu import native
        from hdrf_tpu.config import CdcConfig
        from hdrf_tpu.ops.dispatch import gear_mask
        from hdrf_tpu.parallel import make_mesh, reduce_sharded

        cdc = CdcConfig()
        mesh = make_mesh(n_data=1, n_seq=len(jax.devices()))
        rng = np.random.default_rng(61)
        data = rng.integers(0, 256, size=1_500_000, dtype=np.uint8)
        data[:400_000] = rng.integers(97, 123, size=400_000, dtype=np.uint8)
        data[500_000:600_000] = 0
        data = np.ascontiguousarray(data)
        cuts, digs = reduce_sharded(data, cdc, mesh)
        wc = native.cdc_chunk(data, gear_mask(cdc), cdc.min_chunk,
                              cdc.max_chunk)
        starts = np.concatenate([[0], wc[:-1]]).astype(np.uint64)
        wd = native.sha256_batch(data, starts,
                                 (wc - starts).astype(np.uint64))
        np.testing.assert_array_equal(np.asarray(cuts), wc)
        np.testing.assert_array_equal(digs, wd)

    def test_dispatch_routes_multichip(self, monkeypatch):
        """chunk_and_fingerprint('tpu') on a multi-device host takes the
        sharded path automatically."""
        from hdrf_tpu.config import CdcConfig
        from hdrf_tpu.ops import dispatch

        called = {}
        import hdrf_tpu.parallel.sharded as sh

        real = sh.reduce_sharded

        def spy(data, cdc, mesh):
            called["mesh"] = mesh
            return real(data, cdc, mesh)

        monkeypatch.setattr(sh, "reduce_sharded", spy)
        rng = np.random.default_rng(62)
        data = rng.integers(0, 256, size=300_000, dtype=np.uint8)
        cuts, digs = dispatch.chunk_and_fingerprint(data, CdcConfig(),
                                                    backend="tpu")
        assert "mesh" in called, "multichip dispatch did not engage"
        wc, wd = dispatch.chunk_and_fingerprint(data, CdcConfig(),
                                                backend="native")
        np.testing.assert_array_equal(np.asarray(cuts), wc)
        np.testing.assert_array_equal(digs, wd)

    def test_empty_and_tiny_inputs(self):
        import jax

        from hdrf_tpu.config import CdcConfig
        from hdrf_tpu.parallel import make_mesh, reduce_sharded

        mesh = make_mesh(n_data=1, n_seq=len(jax.devices()))
        cuts, digs = reduce_sharded(b"", CdcConfig(), mesh)
        assert cuts.size == 0 and digs.shape == (0, 32)
        from hdrf_tpu import native
        from hdrf_tpu.ops.dispatch import gear_mask

        cdc = CdcConfig()
        tiny = np.arange(300, dtype=np.uint8)
        cuts, digs = reduce_sharded(tiny, cdc, mesh)
        wc = native.cdc_chunk(tiny, gear_mask(cdc), cdc.min_chunk,
                              cdc.max_chunk)
        np.testing.assert_array_equal(np.asarray(cuts), wc)


class TestHaloShaEconomics:
    """r3 verdict weak #6: the sharded SHA stage must not all_gather the
    full image when a neighbor halo suffices (ICI bytes: halo x shard vs
    (n_seq-1) x shard per device)."""

    def test_halo_path_engages_and_matches_oracle(self, monkeypatch):
        import jax

        from hdrf_tpu import native
        from hdrf_tpu.config import CdcConfig
        from hdrf_tpu.ops.dispatch import gear_mask
        import hdrf_tpu.parallel.sharded as sh

        used = {}
        real = sh._sha_chunks_halo

        def spy(mesh, bucket, pad_words, halo):
            used["halo"] = halo
            return real(mesh, bucket, pad_words, halo)

        monkeypatch.setattr(sh, "_sha_chunks_halo", spy)
        # data x seq mesh: owners round-robin across the data axis too
        cdc = CdcConfig()
        mesh = sh.make_mesh(n_data=2, n_seq=len(jax.devices()) // 2)
        rng = np.random.default_rng(63)
        data = rng.integers(0, 256, size=2_000_000, dtype=np.uint8)
        data[:600_000] = rng.integers(97, 123, size=600_000, dtype=np.uint8)
        cuts, digs = sh.reduce_sharded(np.ascontiguousarray(data), cdc,
                                       mesh)
        assert "halo" in used, "halo SHA path did not engage"
        assert used["halo"] < mesh.shape["seq"] - 1
        wc = native.cdc_chunk(data, gear_mask(cdc), cdc.min_chunk,
                              cdc.max_chunk)
        starts = np.concatenate([[0], wc[:-1]]).astype(np.uint64)
        wd = native.sha256_batch(data, starts, (wc - starts).astype(np.uint64))
        np.testing.assert_array_equal(np.asarray(cuts), wc)
        np.testing.assert_array_equal(digs, wd)

    def test_tiny_block_falls_back_to_all_gather(self, monkeypatch):
        import jax

        from hdrf_tpu.config import CdcConfig
        import hdrf_tpu.parallel.sharded as sh

        called = {}
        monkeypatch.setattr(
            sh, "_sha_chunks_halo",
            lambda *a: called.setdefault("halo", True) or (_ for _ in ()))
        cdc = CdcConfig()
        mesh = sh.make_mesh(n_data=1, n_seq=len(jax.devices()))
        rng = np.random.default_rng(64)
        data = rng.integers(0, 256, size=30_000, dtype=np.uint8)
        cuts, digs = sh.reduce_sharded(np.ascontiguousarray(data), cdc,
                                       mesh)
        assert "halo" not in called, \
            "tiny shards must use the all_gather path"
        assert int(cuts[-1]) == data.size
