"""Cluster health intelligence: heartbeat telemetry, median+MAD outlier
detection (slow peers / slow volumes), and reduction-effectiveness
accounting.

Covers the re-expressed SlowPeerTracker.java:56 / SlowDiskTracker /
OutlierDetector.java:61-103 stack (utils/rollwin.py, utils/outlier.py,
server/namenode.py's _health_report + slow_nodes_report RPC) and the
reduction accounting registry (reduction/accounting.py,
index/chunk_index.py:319 accounting) riding DN heartbeats — including the
acceptance pins: a delayed DN flags within two heartbeat intervals, the
dfsadmin -report cluster dedup ratio equals the chunk-index recompute
EXACTLY, and none of it adds device dispatches."""

import io
import json
import time
import urllib.request
from contextlib import redirect_stdout

import numpy as np
import pytest

from hdrf_tpu.reduction import accounting
from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.tools import cli
from hdrf_tpu.utils import device_ledger, fault_injection, outlier, rollwin


def run_cli(argv) -> tuple[int, str]:
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(argv)
    return rc, buf.getvalue()


# ------------------------------------------------------------ rolling windows


class TestRollingWindow:
    def test_decay_and_summary(self):
        t = [0.0]
        w = rollwin.RollingWindow(window_s=10.0, clock=lambda: t[0])
        w.add(1.0)
        w.add(3.0)
        t[0] = 5.0
        s = w.summary()
        assert s == {"median": 2.0, "mean": 2.0, "max": 3.0, "p95": 3.0,
                     "count": 2}
        t[0] = 11.0  # both samples older than the window
        assert w.summary() is None

    def test_partial_decay_keeps_fresh_samples(self):
        t = [0.0]
        w = rollwin.RollingWindow(window_s=10.0, clock=lambda: t[0])
        w.add(1.0)
        t[0] = 8.0
        w.add(9.0)
        t[0] = 12.0  # first sample decayed, second still in window
        s = w.summary()
        assert s is not None and s["count"] == 1 and s["median"] == 9.0

    def test_maxlen_bounds_memory(self):
        w = rollwin.RollingWindow(window_s=1e9, maxlen=4, clock=lambda: 0.0)
        for v in range(10):
            w.add(float(v))
        s = w.summary()
        assert s["count"] == 4 and s["max"] == 9.0

    def test_window_map_drops_decayed_keys(self):
        t = [0.0]
        m = rollwin.WindowMap(window_s=10.0, clock=lambda: t[0])
        m.note("a", 1.0)
        t[0] = 5.0
        m.note("b", 2.0)
        t[0] = 12.0  # "a" fully decayed; "b" survives
        s = m.summaries()
        assert set(s) == {"b"} and s["b"]["median"] == 2.0


# ---------------------------------------------------------- outlier detector


class TestOutlierDetector:
    def test_planted_straggler_flags_on_degenerate_window(self):
        """MAD == 0 (every healthy value identical): the threshold
        collapses to median * min_ratio and the straggler still flags."""
        flags = outlier.detect({"a": 1.0, "b": 1.0, "c": 1.0, "d": 9.0})
        assert set(flags) == {"d"}
        assert flags["d"]["rule"] == "mad" and flags["d"]["mad"] == 0.0

    def test_uniform_population_never_flags(self):
        assert outlier.detect({"a": 2.0, "b": 2.0, "c": 2.0, "d": 2.0}) == {}

    def test_min_points_guards_tiny_population(self):
        # two resources cannot support a MAD verdict...
        assert outlier.detect({"a": 1.0, "b": 9.0}) == {}
        # ...but the absolute rule still catches pathological values
        flags = outlier.detect({"a": 1.0, "b": 9.0}, abs_floor=5.0)
        assert set(flags) == {"b"} and flags["b"]["rule"] == "absolute"

    def test_floor_suppresses_subthreshold_outliers(self):
        # 4x the median, but everything is sub-millisecond: not actionable
        vals = {"a": 0.0001, "b": 0.0001, "c": 0.0001, "d": 0.0004}
        assert outlier.detect(vals, floor=0.001) == {}

    def test_mad_spread_tolerated(self):
        # wide but consistent spread: within median + 3 * scaled MAD
        vals = {"a": 10.0, "b": 12.0, "c": 14.0, "d": 16.0, "e": 18.0}
        assert outlier.detect(vals) == {}

    def test_tracker_expires_healed_flags(self):
        t = [0.0]
        tr = outlier.OutlierTracker(expiry_s=100.0, clock=lambda: t[0])
        flagged = tr.observe({"a": 1.0, "b": 1.0, "c": 1.0, "d": 9.0})
        assert set(flagged) == {"d"} and flagged["d"]["since"] == 0.0
        t[0] = 50.0  # healed: subsequent observations are uniform
        assert set(tr.observe({"a": 1.0, "b": 1.0, "c": 1.0,
                               "d": 1.0})) == {"d"}  # not yet expired
        t[0] = 101.0
        assert tr.report() == {}  # flag expired without a re-flag

    def test_tracker_keeps_since_across_reflag(self):
        t = [0.0]
        tr = outlier.OutlierTracker(expiry_s=100.0, clock=lambda: t[0])
        tr.observe({"a": 1.0, "b": 1.0, "c": 1.0, "d": 9.0})
        t[0] = 40.0
        rep = tr.observe({"a": 1.0, "b": 1.0, "c": 1.0, "d": 9.0})
        assert rep["d"]["since"] == 0.0 and rep["d"]["last"] == 40.0


# ------------------------------------------------------- heartbeat telemetry


class TestHeartbeatTelemetry:
    def test_stats_round_trip_to_namenode(self):
        """DN heartbeat stats carry the volume, reduction and stall
        summaries; the NN stores them per DN (DatanodeInfo.stats)."""
        rng = np.random.default_rng(81)
        with MiniCluster(n_datanodes=2, replication=2) as mc:
            with mc.client("ht") as c:
                c.write("/ht/f", rng.integers(0, 256, size=150_000,
                                              dtype=np.uint8).tobytes(),
                        scheme="dedup_lz4")
            deadline = time.time() + 8
            stats = {}
            while time.time() < deadline:
                report = mc.namenode.rpc_datanode_report()
                stats = {d["dn_id"]: d["stats"] for d in report}
                if stats and all(
                        ("volumes" in s and "reduction" in s
                         and "stalls" in s) for s in stats.values()):
                    break
                time.sleep(0.2)
            for dn_id, s in stats.items():
                assert "volumes" in s, f"{dn_id} missing volume telemetry"
                for v in s["volumes"].values():
                    assert {"storage_type", "failed", "used_bytes",
                            "probe_median_s", "probe_count"} <= set(v)
                red = s["reduction"]
                assert {"logical_bytes", "unique_chunk_bytes", "dedup_ratio",
                        "refcount_hist", "container_util_hist",
                        "counters"} <= set(red)
                assert red["dedup_ratio"] >= 1.0
                assert s["stalls"] == 0

    def test_slow_volume_flags_from_probe_latency(self):
        """A volume whose health probes run past the absolute floor is
        flagged by the NN detector (SlowDiskTracker analog) within the
        heartbeat cadence, and surfaces on the /prom gauge."""
        with MiniCluster(n_datanodes=2, replication=2) as mc:
            dn = mc.datanodes[0]
            for _ in range(4):
                dn.note_volume_latency(0, 5.0)  # 5 s probes: sick disk
            deadline = time.time() + 6
            rep = {}
            while time.time() < deadline:
                rep = mc.namenode.rpc_slow_nodes_report()
                if rep["slow_volumes"]:
                    break
                time.sleep(0.1)
            key = f"{dn.dn_id}:vol-0"
            assert key in rep["slow_volumes"], rep
            assert rep["slow_volumes"][key]["rule"] == "absolute"
            from hdrf_tpu.utils import metrics
            gauges = metrics.registry("namenode").snapshot()["gauges"]
            assert gauges.get("slow_volume_count", 0) >= 1


# --------------------------------------------------------- slow-peer e2e


class TestSlowPeerEndToEnd:
    def test_delayed_datanode_flagged_within_two_heartbeats(self):
        """Acceptance pin: one DN's packet path is artificially delayed
        (block_receiver.packet fault point, filtered by dn_id since every
        MiniCluster DN shares the process); its upstream pipeline peers
        observe the slow mirror leg organically, and the NN outlier
        detector flags it — visible through slow_nodes_report, the /prom
        gauge, and dfsadmin -slowPeers — within two heartbeat intervals
        of the telemetry landing."""
        rng = np.random.default_rng(82)
        hb = 0.2
        with MiniCluster(n_datanodes=3, replication=3, heartbeat_s=hb,
                         block_size=1 << 20) as mc:
            victim = mc.datanodes[2]

            def delay(**kw):
                if kw.get("dn_id") == victim.dn_id:
                    time.sleep(0.25)

            def observed() -> bool:
                # some upstream peer sampled the slow mirror leg
                return any(victim.dn_id in dn._peer_report()
                           for dn in mc.datanodes if dn is not victim)

            fault_injection.install("block_receiver.packet", delay)
            try:
                with mc.client("slow") as c:
                    # the victim only registers on peers when it is a
                    # MIRROR (not pipeline head); keep writing until some
                    # peer has sampled it
                    for i in range(8):
                        c.write(f"/slow/f{i}",
                                rng.integers(0, 256, size=150_000,
                                             dtype=np.uint8).tobytes())
                        if i >= 2 and observed():
                            break
            finally:
                fault_injection.remove("block_receiver.packet")
            assert observed(), "no peer recorded latency about the slow DN"
            # ... and the NN must flag it within two heartbeat intervals
            # (plus scheduling slack for a loaded CI host)
            deadline = time.time() + 2 * hb + 3.0
            rep = {}
            while time.time() < deadline:
                rep = mc.namenode.rpc_slow_nodes_report()
                if victim.dn_id in rep["slow_peers"]:
                    break
                time.sleep(hb / 2)
            assert victim.dn_id in rep["slow_peers"], rep
            assert rep["slow_peers"][victim.dn_id]["value"] > 1.0

            # /prom gauge via the gateway exposition
            from hdrf_tpu.server.http_gateway import HttpGateway
            gw = HttpGateway(mc.namenode.addr).start()
            try:
                with urllib.request.urlopen(
                        f"http://{gw.addr[0]}:{gw.addr[1]}/prom",
                        timeout=10) as r:
                    text = r.read().decode()
                line = next(ln for ln in text.splitlines()
                            if ln.startswith("hdrf_slow_peer_count"))
                assert float(line.rsplit(" ", 1)[1]) >= 1
                # /health JSON carries the same verdict
                with urllib.request.urlopen(
                        f"http://{gw.addr[0]}:{gw.addr[1]}/health",
                        timeout=10) as r:
                    health = json.loads(r.read())
                assert health["status"] == "degraded"
                assert victim.dn_id in health["slow_peers"]
            finally:
                gw.stop()

            # operator surface: dfsadmin -slowPeers prints the flag
            nn = f"{mc.namenode.addr[0]}:{mc.namenode.addr[1]}"
            rc, out = run_cli(["dfsadmin", "--namenode", nn, "-slowPeers"])
            assert rc == 0
            assert victim.dn_id in json.loads(out)["slow_peers"]


# ------------------------------------------------- reduction accounting e2e


class TestReductionAccounting:
    def test_report_dedup_ratio_exactly_matches_index(self):
        """Acceptance pin: the cluster dedup ratio printed by dfsadmin
        -report equals the ground truth recomputed from the chunk index
        tables EXACTLY (same ints, same float division — repr round-trip
        through the CLI)."""
        rng = np.random.default_rng(83)
        base = rng.integers(0, 256, size=120_000, dtype=np.uint8).tobytes()
        with MiniCluster(n_datanodes=1, replication=1) as mc:
            with mc.client("acct") as c:
                c.write("/acct/a", base, scheme="dedup_lz4")
                c.write("/acct/b", base, scheme="dedup_lz4")  # full dedup
                c.write("/acct/c", base[:40_000], scheme="dedup_lz4")
            # ground truth from the live chunk index tables
            logical = unique = 0
            for dn in mc.datanodes:
                acc = dn.index.accounting()
                logical += acc["logical_bytes"]
                unique += acc["unique_chunk_bytes"]
            truth = accounting.dedup_ratio(logical, unique)
            assert truth > 1.5  # the corpus really deduped
            nn = f"{mc.namenode.addr[0]}:{mc.namenode.addr[1]}"
            deadline = time.time() + 8
            reported = None
            while time.time() < deadline:
                cs = mc.namenode.rpc_cluster_status()
                if (cs["dedup_logical_bytes"] == logical
                        and cs["dedup_unique_bytes"] == unique):
                    reported = cs["dedup_ratio"]
                    break
                time.sleep(0.2)
            assert reported is not None, "heartbeat stats never converged"
            assert reported == truth  # exact: identical ints, same division
            rc, out = run_cli(["dfsadmin", "--namenode", nn, "-report"])
            assert rc == 0
            line = next(ln for ln in out.splitlines()
                        if "dedup_ratio=" in ln)
            printed = float(line.split("dedup_ratio=")[1].split()[0])
            assert printed == truth  # repr round-trips exactly

    def test_accounting_counters_stamped_on_write_path(self):
        """Per-scheme logical/physical bytes and dedup hit/miss chunks
        land in the reduction_accounting registry from the product write
        path (DataDeduplicator.java:338-367's checkChunk points)."""
        rng = np.random.default_rng(84)
        base = rng.integers(0, 256, size=100_000, dtype=np.uint8).tobytes()
        before = accounting.snapshot()["counters"]
        with MiniCluster(n_datanodes=1, replication=1) as mc:
            with mc.client("ctr") as c:
                c.write("/ctr/a", base, scheme="dedup_lz4")
                c.write("/ctr/b", base, scheme="dedup_lz4")
                c.write("/ctr/z", base, scheme="lz4")

        def delta(key: str) -> int:
            after = accounting.snapshot()["counters"]
            return after.get(key, 0) - before.get(key, 0)

        assert delta("logical_bytes__dedup_lz4") == 2 * len(base)
        assert delta("logical_bytes__lz4") >= len(base)
        assert delta("physical_bytes__lz4") > 0
        # second identical write: all chunks hit, none missed
        assert delta("dedup_chunks_hit") > 0
        assert delta("dedup_chunks_miss") > 0
        # hits == misses here: write 1 misses every chunk, write 2 hits
        # every one of the same chunks
        assert delta("dedup_chunks_hit") == delta("dedup_chunks_miss")

    def test_utilization_hist_buckets(self):
        live = {1: 50, 2: 100, 3: 0}
        sizes = {1: 100, 2: 100, 3: 100, 4: 0}
        h = accounting.utilization_hist(live, sizes)
        # cid1 -> 50% (bucket 5), cid2 -> 100% (bucket 10), cid3+cid4 -> 0
        assert h == {5: 1, 10: 1, 0: 2}

    def test_telemetry_adds_zero_device_dispatches(self):
        """Acceptance pin: assembling heartbeat telemetry and running the
        detector are pure host work — the dispatch ledger must not move."""
        with MiniCluster(n_datanodes=1, replication=1) as mc:
            dn = mc.datanodes[0]
            with mc.client("zd") as c:
                c.write("/zd/f", b"x" * 50_000, scheme="dedup_lz4")
            led0 = device_ledger.stamp()
            for _ in range(3):
                dn._stats()
                mc.namenode.rpc_slow_nodes_report()
                mc.namenode.rpc_cluster_status()
                accounting.snapshot()
            led = device_ledger.delta(led0)
            assert led.get("dispatch_total", 0) == 0, led
            assert led.get("readback_total", 0) == 0, led
