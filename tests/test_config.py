"""Config system tests (replaces reference's hardcoded DataNode.java:412-458 statics)."""

from hdrf_tpu.config import HdrfConfig


def test_defaults():
    cfg = HdrfConfig()
    assert cfg.namenode.replication == 3
    assert cfg.datanode.reduction.cdc.avg_chunk == 8192
    assert cfg.datanode.reduction.container_size == 1 << 25


def test_set_dotted():
    cfg = HdrfConfig()
    cfg.set("namenode.replication", 2)
    cfg.set("datanode.reduction.default_scheme", "zstd")
    cfg.set("datanode.reduction.cdc.mask_bits", 16)
    assert cfg.namenode.replication == 2
    assert cfg.datanode.reduction.default_scheme == "zstd"
    assert cfg.datanode.reduction.cdc.avg_chunk == 65536


def test_env_style_underscore_ambiguity():
    cfg = HdrfConfig.load(env={
        "HDRF_DATANODE_REDUCTION_DEFAULT_SCHEME": "lz4",
        "HDRF_NAMENODE_BLOCK_SIZE": "1048576",
        "HDRF_IGNORED_UNKNOWN_KEY": "x",
    })
    assert cfg.datanode.reduction.default_scheme == "lz4"
    assert cfg.namenode.block_size == 1048576


def test_toml_layer(tmp_path):
    p = tmp_path / "hdrf.toml"
    p.write_text("[namenode]\nreplication = 1\n[datanode.reduction]\ndefault_scheme = 'direct'\n")
    cfg = HdrfConfig.load(path=str(p), env={})
    assert cfg.namenode.replication == 1
    assert cfg.datanode.reduction.default_scheme == "direct"


def test_type_coercion():
    cfg = HdrfConfig()
    cfg.set("namenode.heartbeat_interval_s", "2")
    assert cfg.namenode.heartbeat_interval_s == 2.0


def test_unknown_key():
    cfg = HdrfConfig()
    try:
        cfg.set("nope.nothing", 1)
        assert False
    except KeyError:
        pass
