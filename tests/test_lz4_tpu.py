"""TPU LZ4 stage: device match scan + native emit vs the CPU oracle.

The correctness contract (ops/lz4_tpu.py): whatever the device reports, the
emitted stream must decode bit-exactly via hdrf_lz4_decompress — the same
decoder that checks the serial CPU encoder (native/src/lz4.cpp, the
re-expression of the reference's codec stage, DataDeduplicator.java:770-781 /
BlockReceiver.java:822-866).  Ratio is asserted against the serial encoder
with per-corpus bounds (the sorted matcher differs in documented ways:
stride-aligned starts, per-supertile window, frontier thinning)."""

from __future__ import annotations

import numpy as np
import pytest

from hdrf_tpu import native
from hdrf_tpu.ops import dispatch
from hdrf_tpu.ops.lz4_tpu import _S, TpuLz4

RNG = np.random.default_rng(11)


def _text(n: int) -> np.ndarray:
    vocab = [RNG.integers(97, 123, size=RNG.integers(2, 9),
                          dtype=np.uint8).tobytes() for _ in range(500)]
    out = b" ".join(vocab[i] for i in RNG.integers(0, 500, size=n // 5))
    return np.frombuffer(out[:n], np.uint8)


CORPORA = {
    # name -> (array, max ratio penalty vs serial encoder: tpu_size <= native*k)
    "text": (_text(400_000), 1.12),
    "zeros": (np.zeros(300_000, np.uint8), 1.01),
    "random": (RNG.integers(0, 256, size=300_000, dtype=np.uint8), 1.01),
    "rand_ascii": (RNG.integers(97, 123, size=300_000, dtype=np.uint8), 1.05),
    "repeat997": (np.tile(RNG.integers(0, 256, size=997, dtype=np.uint8),
                          300), 1.50),
    "one_tile": (_text(_S), 1.10),
}


class TestRoundTrip:
    @pytest.mark.parametrize("name", sorted(CORPORA))
    def test_roundtrip_and_ratio(self, name):
        a, bound = CORPORA[name]
        comp = TpuLz4().compress(a)
        assert native.lz4_decompress(comp, a.size) == a.tobytes()
        ref = native.lz4_compress(a.tobytes())
        assert len(comp) <= max(len(ref) * bound, len(ref) + 64), (
            f"{name}: tpu {len(comp)} vs native {len(ref)}")

    def test_small_input_native_fallback(self):
        a = RNG.integers(0, 256, size=1000, dtype=np.uint8)
        c = TpuLz4()
        job = c.submit(a)
        assert job.recs is None  # below min_device -> native path
        comp = c.finish(job)
        assert native.lz4_decompress(comp, a.size) == a.tobytes()

    def test_empty(self):
        assert TpuLz4().compress(b"") == b""

    def test_stride4_roundtrip(self):
        a, _ = CORPORA["text"]
        comp = TpuLz4(stride=4).compress(a)
        assert native.lz4_decompress(comp, a.size) == a.tobytes()

    def test_unpadded_sizes(self):
        # Non-multiple-of-supertile lengths: pad region must not corrupt.
        for n in (2 * _S + 1, 2 * _S + 4097, 3 * _S - 1):
            a = _text(n)
            comp = TpuLz4().compress(a)
            assert native.lz4_decompress(comp, a.size) == a.tobytes()


class TestSliceOverflow:
    def test_overflow_retry_recovers_records(self):
        """Force tiny slice hints: the first scan drops records (total >
        returned), the retry widens until the record set fits, and the
        learned widths stick for the next submit."""
        a, _ = CORPORA["text"]
        c = TpuLz4()
        c._p1, c._p2 = 128, 128  # far below text's record density
        comp = c.compress(a)
        assert native.lz4_decompress(comp, a.size) == a.tobytes()
        assert c._p2 > 128  # widened and sticky
        ref = native.lz4_compress(a.tobytes())
        assert len(comp) <= len(ref) * 1.12

    def test_dropped_records_only_cost_ratio(self):
        """With widening disabled (block released), lost records degrade to
        literals but never break the stream."""
        a, _ = CORPORA["text"]
        c = TpuLz4()
        c._p1, c._p2 = 128, 128
        job = c.submit(a)
        rec_row = np.asarray(job.recs)
        job.block = None  # forbid rescan
        comp = c._assemble(job, rec_row)
        assert native.lz4_decompress(comp, a.size) == a.tobytes()


class TestBatched:
    def test_batch_equals_per_buffer(self):
        blocks = [_text(2 * _S), _text(2 * _S), _text(2 * _S)]
        c = TpuLz4()
        batched = c.compress_many(blocks)
        singles = [TpuLz4().compress(b) for b in blocks]
        assert batched == singles

    def test_mixed_lengths_fall_back(self):
        blocks = [_text(2 * _S), _text(3 * _S)]
        outs = TpuLz4().compress_many(blocks)
        for b, comp in zip(blocks, outs):
            assert native.lz4_decompress(comp, b.size) == b.tobytes()


class TestDispatchWiring:
    def test_block_compress_tpu_is_lz4_format(self):
        a, _ = CORPORA["text"]
        comp = dispatch.block_compress("lz4", a.tobytes(), "tpu")
        assert native.lz4_decompress(comp, a.size) == a.tobytes()

    def test_block_compress_native_unchanged(self):
        a, _ = CORPORA["random"]
        assert dispatch.block_compress("lz4", a.tobytes(), "native") == \
            native.lz4_compress(a.tobytes())

    def test_container_store_compress_fn(self, tmp_path):
        from hdrf_tpu.storage.container_store import ContainerStore

        store = ContainerStore(
            str(tmp_path), container_size=1 << 20, lanes=1, codec="lz4",
            compress_fn=lambda d: dispatch.block_compress("lz4", d, "tpu"))
        chunks = [bytes(_text(300_000)), bytes(_text(200_000)),
                  b"z" * 600_000]
        locs = store.append_chunks(chunks, on_seal=lambda cid: None)
        store.flush_open()
        back = store.read_chunks([(cid, off, ln) for cid, off, ln in locs])
        assert [bytes(b) for b in back] == chunks


class TestStitchedParallelLz4:
    """Segmented host-parallel LZ4 (the flood-fallback/bypass encoder):
    independently compressed segments stitched into ONE spec-valid block
    stream by merging junction sequences (lz4_stitch)."""

    def test_stitch_roundtrips_every_corpus(self):
        from concurrent.futures import ThreadPoolExecutor

        from hdrf_tpu.ops.lz4_tpu import _SEG, lz4_stitch

        pool = ThreadPoolExecutor(2)
        rng = np.random.default_rng(11)
        cases = {
            "text": _text(2 * _SEG + 12345),
            "zeros": np.zeros(_SEG + 1, np.uint8),
            "random": rng.integers(0, 256, 2 * _SEG + 7, np.uint8),
            "exact_two_segs": _text(2 * _SEG),
            "periodic": np.tile(np.arange(100, dtype=np.uint8),
                                (_SEG * 2 + 999) // 100 + 1)[:2 * _SEG + 999],
        }
        for name, a in cases.items():
            parts = [a[o:o + _SEG] for o in range(0, a.size, _SEG)]
            pieces = list(pool.map(native.lz4_compress_tail, parts))
            out = lz4_stitch(pieces)
            assert native.lz4_decompress(out, a.size) == a.tobytes(), name
            # ratio stays within a hair of the single-stream encoder (only
            # junction back-windows are lost)
            one = native.lz4_compress(a)
            assert len(out) <= int(len(one) * 1.01) + 64, name

    def test_compress_tail_reports_final_sequence(self):
        a = _text(300_000)
        stream, toff, tlit = native.lz4_compress_tail(a)
        assert stream == native.lz4_compress(a)
        # the reported tail literals are the stream's last tlit bytes and
        # equal the source's tail
        assert 0 < toff < len(stream)
        if tlit:
            assert stream[-tlit:] == a.tobytes()[-tlit:]


def test_emit_adversarial_low_bytes_roundtrip():
    """Regression for the probe-scan word-scan: low-byte-biased data (runs
    of 0x00/0x01) is where a borrow-corrupted zero-byte mask emitted
    matches whose bytes did NOT match — every emit output must decompress
    back to the exact input."""
    import numpy as np

    from hdrf_tpu import native
    from hdrf_tpu.ops.lz4_tpu import TpuLz4

    rng = np.random.default_rng(99)
    tl = TpuLz4()
    for trial in range(4):
        n = 1 << 20
        a = rng.integers(0, 4, n, dtype=np.uint8)      # dense 0x00-0x03
        a[:: 7] = rng.integers(0, 256, a[::7].size, dtype=np.uint8)
        out = tl.compress(a)
        assert native.lz4_decompress(out, n) == a.tobytes(), \
            f"trial {trial}: corrupt emit stream"
