"""TPU LZ4 stage: device match scan + native emit vs the CPU oracle.

The correctness contract (ops/lz4_tpu.py): whatever the device reports, the
emitted stream must decode bit-exactly via hdrf_lz4_decompress — the same
decoder that checks the serial CPU encoder (native/src/lz4.cpp, the
re-expression of the reference's codec stage, DataDeduplicator.java:770-781 /
BlockReceiver.java:822-866).  Ratio is asserted against the serial encoder
with per-corpus bounds (the sorted matcher differs in documented ways:
stride-aligned starts, per-supertile window, frontier thinning)."""

from __future__ import annotations

import numpy as np
import pytest

from hdrf_tpu import native
from hdrf_tpu.ops import dispatch
from hdrf_tpu.ops.lz4_tpu import _S, TpuLz4

RNG = np.random.default_rng(11)


def _text(n: int) -> np.ndarray:
    vocab = [RNG.integers(97, 123, size=RNG.integers(2, 9),
                          dtype=np.uint8).tobytes() for _ in range(500)]
    out = b" ".join(vocab[i] for i in RNG.integers(0, 500, size=n // 5))
    return np.frombuffer(out[:n], np.uint8)


CORPORA = {
    # name -> (array, max ratio penalty vs serial encoder: tpu_size <= native*k)
    "text": (_text(400_000), 1.12),
    "zeros": (np.zeros(300_000, np.uint8), 1.01),
    "random": (RNG.integers(0, 256, size=300_000, dtype=np.uint8), 1.01),
    "rand_ascii": (RNG.integers(97, 123, size=300_000, dtype=np.uint8), 1.05),
    "repeat997": (np.tile(RNG.integers(0, 256, size=997, dtype=np.uint8),
                          300), 1.50),
    "one_tile": (_text(_S), 1.10),
}


class TestRoundTrip:
    @pytest.mark.parametrize("name", sorted(CORPORA))
    def test_roundtrip_and_ratio(self, name):
        a, bound = CORPORA[name]
        comp = TpuLz4().compress(a)
        assert native.lz4_decompress(comp, a.size) == a.tobytes()
        ref = native.lz4_compress(a.tobytes())
        assert len(comp) <= max(len(ref) * bound, len(ref) + 64), (
            f"{name}: tpu {len(comp)} vs native {len(ref)}")

    def test_small_input_native_fallback(self):
        a = RNG.integers(0, 256, size=1000, dtype=np.uint8)
        c = TpuLz4()
        job = c.submit(a)
        assert job.recs is None  # below min_device -> native path
        comp = c.finish(job)
        assert native.lz4_decompress(comp, a.size) == a.tobytes()

    def test_empty(self):
        assert TpuLz4().compress(b"") == b""

    def test_stride4_roundtrip(self):
        a, _ = CORPORA["text"]
        comp = TpuLz4(stride=4).compress(a)
        assert native.lz4_decompress(comp, a.size) == a.tobytes()

    def test_unpadded_sizes(self):
        # Non-multiple-of-supertile lengths: pad region must not corrupt.
        for n in (2 * _S + 1, 2 * _S + 4097, 3 * _S - 1):
            a = _text(n)
            comp = TpuLz4().compress(a)
            assert native.lz4_decompress(comp, a.size) == a.tobytes()


class TestSliceOverflow:
    def test_overflow_retry_recovers_records(self):
        """Force tiny slice hints: the first scan drops records (total >
        returned), the retry widens until the record set fits, and the
        learned widths stick for the next submit."""
        a, _ = CORPORA["text"]
        c = TpuLz4()
        c._p1, c._p2 = 128, 128  # far below text's record density
        comp = c.compress(a)
        assert native.lz4_decompress(comp, a.size) == a.tobytes()
        assert c._p2 > 128  # widened and sticky
        ref = native.lz4_compress(a.tobytes())
        assert len(comp) <= len(ref) * 1.12

    def test_dropped_records_only_cost_ratio(self):
        """With widening disabled (block released), lost records degrade to
        literals but never break the stream."""
        a, _ = CORPORA["text"]
        c = TpuLz4()
        c._p1, c._p2 = 128, 128
        job = c.submit(a)
        rec_row = np.asarray(job.recs)
        job.block = None  # forbid rescan
        comp = c._assemble(job, rec_row)
        assert native.lz4_decompress(comp, a.size) == a.tobytes()


class TestBatched:
    def test_batch_equals_per_buffer(self):
        blocks = [_text(2 * _S), _text(2 * _S), _text(2 * _S)]
        c = TpuLz4()
        batched = c.compress_many(blocks)
        singles = [TpuLz4().compress(b) for b in blocks]
        assert batched == singles

    def test_mixed_lengths_fall_back(self):
        blocks = [_text(2 * _S), _text(3 * _S)]
        outs = TpuLz4().compress_many(blocks)
        for b, comp in zip(blocks, outs):
            assert native.lz4_decompress(comp, b.size) == b.tobytes()


class TestPackedRecords:
    """Packed/delta-encoded record readback (ops/lz4_tpu.py item 5): the
    packed row must decode to the EXACT record set of the full layout —
    same positions, same delta|len words, same total — on every corpus, so
    the emit stream is byte-identical regardless of readback format."""

    @pytest.mark.parametrize("name", sorted(CORPORA))
    def test_packed_row_decodes_to_full_layout_records(self, name):
        import jax

        from hdrf_tpu.ops.lz4_tpu import _match_scan, _packed_len

        a, _ = CORPORA[name]
        c = TpuLz4()
        block = jax.device_put(c._pad(a))
        p1, p2, p3 = c._shapes(block.shape[0])
        packed = np.asarray(_match_scan(block, c.stride, c.min_len,
                                        p1, p2, p3, packed=True))
        full = np.asarray(_match_scan(block, c.stride, c.min_len,
                                      p1, p2, p3, packed=False))
        assert packed.size == _packed_len(p3) < full.size
        tp, gp, rp, complete = c._unpack_packed(packed, p3)
        tf, gf, rf = c._unpack_full(full, p3)
        assert complete
        assert tp == tf
        np.testing.assert_array_equal(gp, gf)
        np.testing.assert_array_equal(rp, rf)

    def test_packed_row_is_at_least_25pct_smaller(self):
        # The ISSUE acceptance bar, on the corpus with the densest record
        # stream (text): packed D2H words <= 0.75x the full layout.
        from hdrf_tpu.ops.lz4_tpu import _packed_len

        c = TpuLz4()
        a, _ = CORPORA["text"]
        _, _, p3 = c._shapes(c._pad(a).shape[0])
        assert _packed_len(p3) <= 0.75 * (1 + 2 * p3)

    def test_compress_equals_full_layout_stream(self, monkeypatch):
        # End to end: the default (packed) compressor emits byte-identical
        # streams to a compressor forced onto the full-layout readback.
        from hdrf_tpu.ops import lz4_tpu

        a, _ = CORPORA["text"]
        comp = TpuLz4().compress(a)
        c2 = TpuLz4()

        def full_records(self, job, rec_row):
            row = np.asarray(lz4_tpu._match_scan(
                job.block, self.stride, self.min_len, job.p1, job.p2,
                job.p3, packed=False))
            return self._unpack_full(row, job.p3)

        monkeypatch.setattr(TpuLz4, "_records", full_records)
        assert c2.compress(a) == comp
        assert native.lz4_decompress(comp, a.size) == a.tobytes()

    def test_native_unpack_records_escapes(self):
        # Hand-built packed row exercising both escape lanes and the
        # clipped-length sentinel.
        from hdrf_tpu.ops.lz4_tpu import _esc_slots

        stride, p3 = 2, 256
        es = _esc_slots(p3)
        # record i: (pos_u, delta_u, len_u) in entry units, ascending pos
        recs = [(10, 3, 0),           # plain
                (12, 5, 600),         # len escape (>=511)
                (80_000, 7, 2),       # pos-delta escape (>=0xFFFF)
                (80_001, 9, 32766)]   # clipped mlen==65535 sentinel
        A = np.zeros(p3, np.uint32)
        B = np.zeros(p3 // 4, np.uint32)
        E1 = np.zeros(es, np.uint32)
        E2 = np.zeros(es, np.uint32)
        prev = 0
        e1 = e2 = 0
        for i, (pos, dlt, ln) in enumerate(recs):
            dp = pos - prev
            if dp >= 0xFFFF:
                dp16 = 0xFFFF
                E1[e1] = pos
                e1 += 1
            else:
                dp16 = dp
            if ln >= 511:
                l9 = 511
                E2[e2] = ln
                e2 += 1
            else:
                l9 = ln
            A[i] = dlt | (l9 << 15) | ((dp16 >> 8) << 24)
            B[i // 4] |= (dp16 & 0xFF) << ((i % 4) * 8)
            prev = pos
        row = np.concatenate([A, B, E1, E2])
        g, r, nrec = native.lz4_unpack_records(row, p3, len(recs), stride, es)
        assert nrec == len(recs)
        np.testing.assert_array_equal(g, [p * stride for p, _, _ in recs])
        for i, (pos, dlt, ln) in enumerate(recs):
            mlen = 65535 if ln == 32766 else ln * stride + 4
            assert r[i] == ((dlt * stride) << 16 | mlen), i

    def test_native_unpack_rejects_bad_args(self):
        row = np.zeros(16, np.uint32)
        with pytest.raises(ValueError):
            native.lz4_unpack_records(row, 256, 4, 2, 68)  # row too small


class TestDispatchWiring:
    def test_block_compress_tpu_is_lz4_format(self):
        a, _ = CORPORA["text"]
        comp = dispatch.block_compress("lz4", a.tobytes(), "tpu")
        assert native.lz4_decompress(comp, a.size) == a.tobytes()

    def test_block_compress_native_unchanged(self):
        a, _ = CORPORA["random"]
        assert dispatch.block_compress("lz4", a.tobytes(), "native") == \
            native.lz4_compress(a.tobytes())

    def test_container_store_compress_fn(self, tmp_path):
        from hdrf_tpu.storage.container_store import ContainerStore

        store = ContainerStore(
            str(tmp_path), container_size=1 << 20, lanes=1, codec="lz4",
            compress_fn=lambda d: dispatch.block_compress("lz4", d, "tpu"))
        chunks = [bytes(_text(300_000)), bytes(_text(200_000)),
                  b"z" * 600_000]
        locs = store.append_chunks(chunks, on_seal=lambda cid: None)
        store.flush_open()
        back = store.read_chunks([(cid, off, ln) for cid, off, ln in locs])
        assert [bytes(b) for b in back] == chunks

    def test_container_store_batched_flush(self, tmp_path):
        """flush_open with compress_batch_fn: all open lanes sealed through
        ONE batched compress call, containers read back intact."""
        from hdrf_tpu.storage.container_store import ContainerStore

        calls = []

        def batch(datas):
            calls.append(len(datas))
            return dispatch.block_compress_batch("lz4", datas, "native")

        store = ContainerStore(
            str(tmp_path), container_size=1 << 20, lanes=3, codec="lz4",
            compress_batch_fn=batch)
        chunks = [bytes(_text(200_000)) for _ in range(6)]
        locs = []
        for ch in chunks:  # round-robins across the 3 lanes
            locs += store.append_chunks([ch], on_seal=lambda cid: None)
        store.flush_open()
        assert calls == [3], "expected ONE batch over the 3 open lanes"
        back = store.read_chunks([(cid, off, ln) for cid, off, ln in locs])
        assert [bytes(b) for b in back] == chunks

    def test_batched_flush_stream_identical_to_per_lane(self, tmp_path):
        # The batch path must leave byte-identical sealed files.
        import filecmp

        from hdrf_tpu.storage.container_store import ContainerStore

        chunks = [bytes(_text(150_000)) for _ in range(4)]

        def fill(root, **kw):
            store = ContainerStore(str(root), container_size=1 << 20,
                                   lanes=2, codec="lz4", **kw)
            for ch in chunks:
                store.append_chunks([ch], on_seal=lambda cid: None)
            store.flush_open()
            return store

        a, b = tmp_path / "a", tmp_path / "b"
        a.mkdir(), b.mkdir()
        fill(a)
        fill(b, compress_batch_fn=lambda ds: dispatch.block_compress_batch(
            "lz4", ds, "native"))
        names = sorted(p.name for p in a.iterdir())
        assert names == sorted(p.name for p in b.iterdir())
        for n in names:
            assert filecmp.cmp(a / n, b / n, shallow=False), n


class TestStitchedParallelLz4:
    """Segmented host-parallel LZ4 (the flood-fallback/bypass encoder):
    independently compressed segments stitched into ONE spec-valid block
    stream by merging junction sequences (lz4_stitch)."""

    def test_stitch_roundtrips_every_corpus(self):
        from concurrent.futures import ThreadPoolExecutor

        from hdrf_tpu.ops.lz4_tpu import _SEG, lz4_stitch

        pool = ThreadPoolExecutor(2)
        rng = np.random.default_rng(11)
        cases = {
            "text": _text(2 * _SEG + 12345),
            "zeros": np.zeros(_SEG + 1, np.uint8),
            "random": rng.integers(0, 256, 2 * _SEG + 7, np.uint8),
            "exact_two_segs": _text(2 * _SEG),
            "periodic": np.tile(np.arange(100, dtype=np.uint8),
                                (_SEG * 2 + 999) // 100 + 1)[:2 * _SEG + 999],
        }
        for name, a in cases.items():
            parts = [a[o:o + _SEG] for o in range(0, a.size, _SEG)]
            pieces = list(pool.map(native.lz4_compress_tail, parts))
            out = lz4_stitch(pieces)
            assert native.lz4_decompress(out, a.size) == a.tobytes(), name
            # ratio stays within a hair of the single-stream encoder (only
            # junction back-windows are lost)
            one = native.lz4_compress(a)
            assert len(out) <= int(len(one) * 1.01) + 64, name

    def test_compress_tail_reports_final_sequence(self):
        a = _text(300_000)
        stream, toff, tlit = native.lz4_compress_tail(a)
        assert stream == native.lz4_compress(a)
        # the reported tail literals are the stream's last tlit bytes and
        # equal the source's tail
        assert 0 < toff < len(stream)
        if tlit:
            assert stream[-tlit:] == a.tobytes()[-tlit:]


def test_emit_adversarial_low_bytes_roundtrip():
    """Regression for the probe-scan word-scan: low-byte-biased data (runs
    of 0x00/0x01) is where a borrow-corrupted zero-byte mask emitted
    matches whose bytes did NOT match — every emit output must decompress
    back to the exact input."""
    import numpy as np

    from hdrf_tpu import native
    from hdrf_tpu.ops.lz4_tpu import TpuLz4

    rng = np.random.default_rng(99)
    tl = TpuLz4()
    for trial in range(4):
        n = 1 << 20
        a = rng.integers(0, 4, n, dtype=np.uint8)      # dense 0x00-0x03
        a[:: 7] = rng.integers(0, 256, a[::7].size, dtype=np.uint8)
        out = tl.compress(a)
        assert native.lz4_decompress(out, n) == a.tobytes(), \
            f"trial {trial}: corrupt emit stream"
