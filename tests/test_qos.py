"""Overload-safe traffic plane (ISSUE 14 / ARCHITECTURE.md design
decision 14): per-tenant token-bucket admission, weighted-fair dequeue,
deadline-aware load shedding, and k+δ straggler-proof EC stripe reads.

Covers utils/qos.py (TenantBucket deficit math, AdmissionController
bucket/deadline sheds, FairQueue round-robin + close-sentinel contract),
the admission wiring through server/write_pipeline.py and
server/read_plane.py (including the semaphore permit-leak regressions),
the ShedError wire round-trip (proto/datatransfer.py ACK_SHED, error
frames), the noisy-neighbor acceptance matrix on a two-tenant
MiniCluster, and the hedged stripe gather of server/ec_tier.py.
Exercises the fault points "qos.admit", "qos.shed" and
"ec.stripe_hedge".
"""

import threading
import time
from queue import Empty

import numpy as np
import pytest

from hdrf_tpu.config import CdcConfig
from hdrf_tpu.utils import fault_injection, metrics, qos, retry

_QOS = metrics.registry("qos")
_EC = metrics.registry("ec")


@pytest.fixture(autouse=True)
def _clear_faults():
    fault_injection.clear()
    yield
    fault_injection.clear()


def _wait(pred, timeout=20.0, interval=0.1, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


# ------------------------------------------------------ deficit buckets


class TestTenantBucket:
    def test_deficit_and_refill(self):
        clk = _FakeClock()
        b = qos.TenantBucket(rate_bytes_s=100.0, burst_bytes=50.0,
                             clock=clk)
        assert b.try_admit() == 0.0
        # charge AFTER the op may overdraw: 250 bytes against a 50 burst
        b.charge(250)
        assert b.level == pytest.approx(-200.0)
        # retry-after = time for the level to climb back past zero
        assert b.try_admit() == pytest.approx(2.0)
        clk.t += 1.0
        assert b.try_admit() == pytest.approx(1.0)
        clk.t += 1.5
        assert b.try_admit() == 0.0
        # refill clamps at the burst, not unbounded credit
        clk.t += 100.0
        assert b.level == pytest.approx(50.0)

    def test_zero_rate_is_unlimited_until_configured(self):
        ctrl = qos.AdmissionController(rate_mb_s=0.0)
        for _ in range(50):
            ctrl.admit("anyone", "write")
            ctrl.charge("anyone", "write", 1 << 30)


# --------------------------------------------------- weighted-fair queue


class _Item:
    __slots__ = ("tenant", "tag")

    def __init__(self, tenant, tag=0):
        self.tenant = tenant
        self.tag = tag


class TestFairQueue:
    def test_round_robin_interleaves_flood_and_light(self):
        """64 queued items from a flooding tenant must not delay a light
        tenant's 8: round-robin serves one per tenant per cycle, so all
        of B's items land within the first 2*8 dequeues."""
        q = qos.FairQueue()
        for i in range(64):
            q.put(_Item("flood", i))
        for i in range(8):
            q.put(_Item("light", i))
        first = [q.get_nowait() for _ in range(16)]
        assert sum(1 for it in first if it.tenant == "light") == 8
        # and within each lane, FIFO order is preserved
        light_tags = [it.tag for it in first if it.tenant == "light"]
        assert light_tags == sorted(light_tags)

    def test_close_sentinel_served_after_data_drains(self):
        """The pipelines' ``None`` close sentinel parks in the control
        lane: queued work drains first, preserving the close contract."""
        q = qos.FairQueue()
        q.put(_Item("a"))
        q.put(None)
        q.put(_Item("b"))
        got = [q.get_nowait() for _ in range(3)]
        assert got[-1] is None
        assert {it.tenant for it in got[:2]} == {"a", "b"}
        with pytest.raises(Empty):
            q.get_nowait()

    def test_blocking_get_wakes_on_put(self):
        q = qos.FairQueue()
        out = []
        t = threading.Thread(target=lambda: out.append(q.get(timeout=5.0)))
        t.start()
        time.sleep(0.05)
        q.put(_Item("x"))
        t.join(timeout=5.0)
        assert out and out[0].tenant == "x"
        with pytest.raises(Empty):
            q.get(timeout=0.01)

    def test_depth_by_tenant(self):
        q = qos.FairQueue()
        for _ in range(3):
            q.put(_Item("a"))
        q.put(_Item("b"))
        assert q.depth_by_tenant() == {"a": 3, "b": 1}
        assert q.qsize() == 4


# ------------------------------------------------- admission controller


class TestAdmissionController:
    def test_bucket_shed_carries_retry_after_and_isolates_tenants(self):
        clk = _FakeClock()
        ctrl = qos.AdmissionController(rate_mb_s=1.0, burst_mb=1.0,
                                       clock=clk)
        admits, sheds = [], []
        with fault_injection.inject(
                "qos.admit", lambda **kw: admits.append(kw)), \
                fault_injection.inject(
                    "qos.shed", lambda **kw: sheds.append(kw)):
            ctrl.admit("hog", "write")
            ctrl.charge("hog", "write", 5 << 20)  # 5x the burst
            with pytest.raises(qos.ShedError) as ei:
                ctrl.admit("hog", "write")
            # retry-after = the 4 MiB deficit at 1 MiB/s
            assert ei.value.retry_after_s == pytest.approx(4.0)
            assert ei.value.tenant == "hog" and ei.value.op == "write"
            # the light tenant's bucket is untouched by the hog's deficit
            ctrl.admit("light", "write")
            # the bucket refills with time and the hog re-admits
            clk.t += 5.0
            ctrl.admit("hog", "write")
        assert [s["tenant"] for s in sheds] == ["hog"]
        assert sheds[0]["why"] == "rate"
        assert len(admits) == 4  # every admission attempt fires the point
        assert ctrl.report()["tenant_sheds"] == {"hog": 1}
        assert ctrl.sheds_total() == 1

    def test_deadline_shed_requires_warmed_estimator(self):
        """A cold service-time window must never shed; once >=5 samples
        land, a deadline that cannot cover p95 * shed_p95_mult is
        refused at admission with the needed budget as the hint."""
        clk = _FakeClock()
        ctrl = qos.AdmissionController(shed_p95_mult=3.0, clock=clk)
        short = retry.Deadline(0.05)
        # cold estimator: admitted even with a microscopic budget
        ctrl.admit("t", "read", deadline=short)
        for _ in range(6):
            ctrl.note_latency("read", 0.2)
        with pytest.raises(qos.ShedError) as ei:
            ctrl.admit("t", "read", deadline=retry.Deadline(0.05))
        assert ei.value.retry_after_s == pytest.approx(0.6, rel=0.2)
        # a budget that covers the estimate passes
        ctrl.admit("t", "read", deadline=retry.Deadline(5.0))
        # ops are estimated independently: writes have no samples
        ctrl.admit("t", "write", deadline=retry.Deadline(0.05))

    def test_ambient_deadline_is_picked_up(self):
        ctrl = qos.AdmissionController()
        for _ in range(6):
            ctrl.note_latency("write", 0.5)
        with retry.bind(retry.Deadline(0.01)):
            with pytest.raises(qos.ShedError):
                ctrl.admit("t", "write")
        ctrl.admit("t", "write")  # no ambient deadline -> no shed


# ------------------------------------------- permit-leak regressions


class TestPermitLeaks:
    def _shedding_ctrl(self):
        ctrl = qos.AdmissionController(rate_mb_s=1.0, burst_mb=1.0)
        ctrl.admit("hog", "write")
        ctrl.charge("hog", "write", 1 << 40)  # bucket never recovers
        return ctrl

    def test_write_pipeline_sheds_leak_no_permits(self):
        """100 shed admissions must not consume pipeline permits, and an
        admitted tenant must still get through afterward (the flood
        cannot starve the pipeline by leaking its semaphore)."""
        from hdrf_tpu.server.write_pipeline import WritePipeline

        ctrl = self._shedding_ctrl()
        p = WritePipeline(CdcConfig(), "native", max_inflight=4,
                          qos_ctrl=ctrl)
        before = p._sem._value
        data = np.zeros(1 << 12, dtype=np.uint8)
        for _ in range(100):
            with pytest.raises(qos.ShedError):
                p.submit(1, data, tenant="hog")
        assert p._sem._value == before
        # an admitted tenant's submit still succeeds
        fut = p.submit(2, data, tenant="light")
        cuts, _digs = fut.result(timeout=30)[:2]
        assert len(cuts) >= 1
        assert p._sem._value == before

    def test_write_pipeline_queue_failure_releases_permit(self):
        """A raise between permit acquire and enqueue (the audited
        window) must hand the permit back through the future's done
        callback — 100 failures leave the semaphore intact."""
        from hdrf_tpu.server.write_pipeline import WritePipeline

        p = WritePipeline(CdcConfig(), "native", max_inflight=4)
        p._thread = threading.current_thread()  # force the queue path

        class _Boom:
            def put(self, item):
                raise RuntimeError("injected enqueue failure")

        p._q = _Boom()
        before = p._sem._value
        data = np.zeros(1 << 10, dtype=np.uint8)
        for _ in range(100):
            with pytest.raises(RuntimeError):
                p.submit(1, data)
        assert p._sem._value == before

    def test_read_coalescer_sheds_and_failures_leak_no_permits(self):
        from hdrf_tpu.server.read_plane import ReadCoalescer

        class _Containers:
            def read_containers(self, cids, decompress_batch=None):
                raise IOError("injected container read failure")

        ctrl = self._shedding_ctrl()
        rc = ReadCoalescer(_Containers(), max_inflight=4, backend="native",
                           qos_ctrl=ctrl)
        before = rc._sem._value
        for _ in range(100):
            with pytest.raises(qos.ShedError):
                rc.fetch([1], tenant="hog")
        # admitted tenant: the decode failure path releases via finally
        for _ in range(100):
            with pytest.raises(IOError):
                rc.fetch([1], tenant="light")
        assert rc._sem._value == before

    def test_unattributed_traffic_is_never_shed(self):
        """Internal relays (mirror ingest, scrub, EC fan-in) carry no
        tenant and bypass admission — a tenant flood must not starve
        housekeeping into unavailability."""
        from hdrf_tpu.server.write_pipeline import WritePipeline

        ctrl = self._shedding_ctrl()
        ctrl.charge("anon", "write", 1 << 40)  # even the default lane
        p = WritePipeline(CdcConfig(), "native", qos_ctrl=ctrl)
        data = np.zeros(1 << 10, dtype=np.uint8)
        fut = p.submit(3, data, tenant=None)  # internal: no attribution
        assert fut.result(timeout=30) is not None


# --------------------------------------------------- noisy neighbor e2e


class TestNoisyNeighbor:
    def test_flood_sheds_hog_while_light_tenant_reads(self):
        """The acceptance matrix: tenant A floods writes past its rate;
        tenant B keeps reading.  A gets a structured retryable ShedError
        (refused AT ADMISSION — no mid-pipeline timeout), B's ops all
        complete, the per-tenant shed counters show the asymmetry, and
        no circuit breaker opens from shedding alone."""
        from hdrf_tpu.client.filesystem import HdrfClient
        from hdrf_tpu.config import ClientConfig
        from hdrf_tpu.testing.minicluster import MiniCluster
        from hdrf_tpu.utils import prom

        retry.reset_breakers()
        hog_sheds0 = _QOS.counter("tenant_sheds|tenant=hog,op=write")
        light_sheds0 = _QOS.counter("tenant_sheds|tenant=light,op=read")
        # one DN so every block shares one admission gate (with more DNs
        # each write head charges its own bucket and the flood would need
        # to overdraw every head before shedding)
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=1 << 20,
                         reduction_overrides={
                             "qos_tenant_rate_mb_s": 0.05,
                             "qos_tenant_burst_mb": 0.25,
                         }) as mc:
            rng = np.random.default_rng(14)
            small = rng.integers(0, 256, size=64 * 1024,
                                 dtype=np.uint8).tobytes()
            with mc.client("setup") as c:
                c.write("/qos/b", small, scheme="dedup_lz4")

            # ---- tenant A floods: first write rides the burst, the
            # second is refused at admission with a retry-after hint the
            # 3 s budget cannot cover (hint ~10 s at 0.05 MB/s)
            flood = rng.integers(0, 256, size=768 * 1024,
                                 dtype=np.uint8).tobytes()
            hog = HdrfClient(mc.nn_addrs(0)[0], name="hog",
                             config=ClientConfig(op_deadline_s=3.0))
            try:
                hog.write("/qos/flood1", flood, scheme="dedup_lz4")
                t0 = time.monotonic()
                with pytest.raises(qos.ShedError) as ei:
                    hog.write("/qos/flood2", flood, scheme="dedup_lz4")
                shed_latency = time.monotonic() - t0
                assert ei.value.retry_after_s > 0
                # refused at the door, not timed out mid-pipeline: the
                # 3 s deadline was NOT burned waiting
                assert shed_latency < 2.5, \
                    f"shed took {shed_latency:.2f}s — that's a timeout"
            finally:
                hog.close()

            # ---- tenant B's reads complete under the flood
            with mc.client("light") as c:
                for _ in range(3):
                    assert c.read("/qos/b") == small

            # ---- per-tenant asymmetry on the qos registry (and /prom
            # via the same snapshots render)
            assert _QOS.counter("tenant_sheds|tenant=hog,op=write") \
                > hog_sheds0
            assert _QOS.counter("tenant_sheds|tenant=light,op=read") \
                == light_sheds0
            text = prom.render(metrics.all_snapshots())
            assert 'hdrf_tenant_sheds_total{' in text
            assert 'tenant="hog"' in text

            # ---- sheds surface on /health without degrading the verdict
            # (the NN aggregates DN heartbeat stats — allow one beat)
            with mc.client("probe") as c:
                _wait(lambda: c._call("cluster_status")
                      ["qos_sheds_total"] >= 1,
                      msg="qos_sheds_total heartbeat aggregation")

            # ---- shedding alone never opens a breaker
            open_edges = [n for n, b in retry.all_breakers().items()
                          if b.state == "open"]
            assert not open_edges, f"breakers opened: {open_edges}"

    def test_shed_ack_round_trip_honors_hint_then_admits(self):
        """Wire contract: the DN refuses a streamed block with ACK_SHED
        acks carrying the retry-after hint (ms in the seqno field); a
        client WITHOUT a deadline honors the hint — sleeps it out — and
        the retried block is then admitted, so the write succeeds on the
        second attempt instead of erroring or hot-looping."""
        from hdrf_tpu.testing.minicluster import MiniCluster

        seen0 = metrics.registry("client").counter("write_sheds_seen")
        recv0 = metrics.registry("block_receiver").counter("write_sheds")
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=1 << 20,
                         reduction_overrides={
                             "qos_tenant_rate_mb_s": 0.1,
                             "qos_tenant_burst_mb": 0.1,
                         }) as mc:
            rng = np.random.default_rng(7)
            data = rng.integers(0, 256, size=256 * 1024,
                                dtype=np.uint8).tobytes()
            data2 = rng.integers(0, 256, size=64 * 1024,
                                 dtype=np.uint8).tobytes()
            with mc.client("wirehog") as c:
                c.write("/wire/a", data, scheme="dedup_lz4")
                # bucket ~150 KiB in deficit: attempt 1 sheds with a
                # ~1.5 s hint, the client waits it out, attempt 2 admits
                t0 = time.monotonic()
                c.write("/wire/b", data2, scheme="dedup_lz4")
                elapsed = time.monotonic() - t0
            # verify under a fresh tenant: wirehog's own bucket is still
            # paying off the overdraft and would shed the read as well
            with mc.client("wireverify") as c:
                assert c.read("/wire/b") == data2
        assert metrics.registry("block_receiver").counter(
            "write_sheds") > recv0, "the DN never shed on the wire"
        assert metrics.registry("client").counter(
            "write_sheds_seen") > seen0, "the client never saw ACK_SHED"
        # the hint was honored: no hot-loop (>=1 s of the ~1.5 s hint),
        # no pathological wait either
        assert 0.9 < elapsed < 20.0


# ------------------------------------------------- k+δ hedged EC reads


class TestEcStripeHedge:
    def test_stalled_stripe_holder_does_not_stall_degraded_read(self):
        """The straggler acceptance: demote a block to RS(2,1) stripes,
        stall ONE stripe holder via the "ec.stripe_hedge" fault point,
        and the degraded read must complete from the other k legs (the
        hedge fires at the p95 floor) without waiting out the stall."""
        from hdrf_tpu.testing.minicluster import MiniCluster

        retry.reset_breakers()
        with MiniCluster(n_datanodes=4, block_size=256 * 1024,
                         container_size=32 * 1024) as mc:
            mc.namenode.config.ec_data_shards = 2
            mc.namenode.config.ec_parity_shards = 1
            rng = np.random.default_rng(41)
            data = rng.integers(0, 256, size=150_000,
                                dtype=np.uint8).tobytes()
            with mc.client("hedge") as c:
                c.write("/hedge/a", data, scheme="dedup_lz4")
                assert c.read("/hedge/a") == data
                mc.namenode.config.ec_demote_after_s = 0.3
                time.sleep(0.3)
                _wait(lambda: c._call("ec_status")["demoted_blocks"] >= 1,
                      msg="block demotion")

                owner = next(dn for dn in mc.datanodes
                             if dn is not None and dn.index.stats()
                             ["striped_containers"] > 0)
                # cold-restart the owner: the container cache must miss so
                # the read goes sealed-file -> stripe gather
                oid = int(owner.dn_id.split("-")[1])
                mc.stop_datanode(oid)
                mc.restart_datanode(oid)
                mc.wait_for_datanodes(4)
                owner = mc.datanodes[oid]
                man = next(iter(owner.index.stripe_manifests().values()))
                k = int(man["k"])
                victim = next(man["holders"][i][0] for i in range(k)
                              if man["holders"][i][0] != owner.dn_id)

                stalled = []

                def _stall(holder=None, **kw):
                    if holder == victim:
                        stalled.append(holder)
                        time.sleep(6.0)

                fired0 = _EC.counter("ec_hedges_fired")
                wins0 = _EC.counter("ec_hedge_wins")
                with fault_injection.inject("ec.stripe_hedge", _stall):
                    t0 = time.monotonic()
                    assert c.read("/hedge/a") == data
                    elapsed = time.monotonic() - t0
                assert stalled, "fault point never saw the victim leg"
                assert elapsed < 5.0, \
                    f"read waited out the straggler ({elapsed:.1f}s)"
                assert _EC.counter("ec_hedges_fired") > fired0
                assert _EC.counter("ec_hedge_wins") > wins0

    def test_delta_zero_restores_serial_gather(self):
        """ec_read_hedge_delta=0 must take the pre-hedging serial path
        (no hedge counters move) and still reconstruct bit-identically."""
        from hdrf_tpu.testing.minicluster import MiniCluster

        with MiniCluster(n_datanodes=4, block_size=256 * 1024,
                         container_size=32 * 1024,
                         reduction_overrides={
                             "ec_read_hedge_delta": 0,
                         }) as mc:
            mc.namenode.config.ec_data_shards = 2
            mc.namenode.config.ec_parity_shards = 1
            rng = np.random.default_rng(43)
            data = rng.integers(0, 256, size=120_000,
                                dtype=np.uint8).tobytes()
            with mc.client("serial") as c:
                c.write("/serial/a", data, scheme="dedup_lz4")
                mc.namenode.config.ec_demote_after_s = 0.3
                time.sleep(0.3)
                _wait(lambda: c._call("ec_status")["demoted_blocks"] >= 1,
                      msg="block demotion")
                oid = next(i for i, dn in enumerate(mc.datanodes)
                           if dn is not None and dn.index.stats()
                           ["striped_containers"] > 0)
                mc.stop_datanode(oid)
                mc.restart_datanode(oid)
                mc.wait_for_datanodes(4)
                fired0 = _EC.counter("ec_hedges_fired")
                assert c.read("/serial/a") == data
                assert _EC.counter("ec_hedges_fired") == fired0
