"""Storage layout versioning + upgrade/rollback (Storage.java analog,
storage/version.py): VERSION files, layout checks, the flat->volumes
DataNode migration, byte-exact rollback, and online finalization."""

import hashlib
import os
import time

import numpy as np
import pytest

from hdrf_tpu.storage import version as sv
from hdrf_tpu.testing.minicluster import MiniCluster


def _tree_digest(directory: str, skip=("previous", "previous.tmp")) -> dict:
    """path -> sha256 of every file (the byte-exactness oracle)."""
    out = {}
    for root, dirs, files in os.walk(directory):
        rel = os.path.relpath(root, directory)
        if rel.split(os.sep)[0] in skip:
            dirs[:] = []
            continue
        for name in files:
            p = os.path.join(root, name)
            with open(p, "rb") as f:
                out[os.path.relpath(p, directory)] = hashlib.sha256(
                    f.read()).hexdigest()
    return out


def _devolve_to_v1(data_dir: str) -> None:
    """Rewrite a current-layout DN dir as the OLD flat layout (what a
    pre-upgrade deployment left on disk): volumes/vol-0/* at the root,
    VERSION saying layout 1."""
    vol0 = os.path.join(data_dir, "volumes", "vol-0")
    for sub in ("replicas", "containers"):
        src = os.path.join(vol0, sub)
        if os.path.isdir(src):
            os.replace(src, os.path.join(data_dir, sub))
    os.rmdir(vol0)
    os.rmdir(os.path.join(data_dir, "volumes"))
    sv.write_version(data_dir, "datanode", 1)


class TestVersionFile:
    def test_fresh_dir_gets_current_layout(self, tmp_path):
        d = str(tmp_path / "s")
        assert sv.ensure_layout(d, "datanode", sv.DN_UPGRADERS) == 2
        v = sv.read_version(d)
        assert v["layoutVersion"] == 2 and v["storageType"] == "datanode"

    def test_future_layout_refuses_to_load(self, tmp_path):
        d = str(tmp_path / "s")
        os.makedirs(d)
        sv.write_version(d, "datanode", 99)
        with pytest.raises(sv.LayoutError, match="NEWER"):
            sv.ensure_layout(d, "datanode", sv.DN_UPGRADERS)

    def test_wrong_storage_type_refuses(self, tmp_path):
        d = str(tmp_path / "s")
        os.makedirs(d)
        sv.write_version(d, "journal", 1)
        with pytest.raises(sv.LayoutError, match="storageType"):
            sv.ensure_layout(d, "namenode", sv.NN_UPGRADERS)

    def test_unversioned_nonempty_dir_upgrades_from_zero(self, tmp_path):
        d = str(tmp_path / "s")
        os.makedirs(os.path.join(d, "replicas", "finalized"))
        with open(os.path.join(d, "replicas", "finalized", "blk_7"),
                  "wb") as f:
            f.write(b"x" * 100)
        assert sv.ensure_layout(d, "datanode", sv.DN_UPGRADERS) == 2
        assert os.path.exists(os.path.join(
            d, "volumes", "vol-0", "replicas", "finalized", "blk_7"))
        assert sv.has_previous(d)


class TestDataNodeUpgrade:
    def test_old_layout_dn_upgrades_serves_and_rolls_back(self):
        """The VERDICT r3 'done' criterion: an old-layout store loads via
        upgrade (data served afterwards), and rollback restores the old
        layout byte-exactly."""
        rng = np.random.default_rng(3)
        data = rng.integers(0, 64, 500_000, np.uint8).tobytes()
        with MiniCluster(n_datanodes=2, replication=2,
                         block_size=1 << 20) as mc:
            with mc.client("up") as c:
                c.write("/up/f", data, scheme="dedup_lz4")
            ddir = mc.datanodes[0].config.data_dir
            mc.stop_datanode(0)
            _devolve_to_v1(ddir)
            pre_upgrade = _tree_digest(ddir)

            mc.restart_datanode(0)           # upgrade runs at startup
            assert sv.read_version(ddir)["layoutVersion"] == 2
            assert sv.has_previous(ddir)
            with mc.client("up2") as c:
                assert c.read("/up/f") == data   # data survived the move
            mc.stop_datanode(0)

            sv.rollback(ddir)
            assert _tree_digest(ddir) == pre_upgrade  # byte-exact
            assert sv.read_version(ddir)["layoutVersion"] == 1

            # ... and the rolled-back store upgrades cleanly again
            mc.restart_datanode(0)
            with mc.client("up3") as c:
                assert c.read("/up/f") == data

    def test_online_finalize_drops_snapshots(self):
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=1 << 20) as mc:
            with mc.client("fin") as c:
                c.write("/fin/f", b"z" * 200_000)
            ddir = mc.datanodes[0].config.data_dir
            mc.stop_datanode(0)
            _devolve_to_v1(ddir)
            mc.restart_datanode(0)
            assert sv.has_previous(ddir)
            r = mc.namenode.rpc_finalize_upgrade()
            assert r["datanodes_queued"] == 1
            deadline = time.time() + 8
            while time.time() < deadline and sv.has_previous(ddir):
                time.sleep(0.3)   # finalize rides the next heartbeat
            assert not sv.has_previous(ddir)

    def test_rollback_without_snapshot_raises(self, tmp_path):
        d = str(tmp_path / "s")
        sv.ensure_layout(d, "datanode", sv.DN_UPGRADERS)
        with pytest.raises(sv.LayoutError, match="previous"):
            sv.rollback(d)

    def test_crash_mid_upgrade_rolls_back_and_retries(self, tmp_path):
        """Post-snapshot crash: upgrade flag + previous/ present, current
        tree half-migrated.  The next load must restore the intact
        pre-upgrade image from previous/ and re-run the upgrade — not
        boot-loop, and not re-snapshot the mangled tree."""
        d = str(tmp_path / "s")
        # intact v1 image preserved in previous/
        os.makedirs(os.path.join(d, sv.PREVIOUS, "replicas", "finalized"))
        with open(os.path.join(d, sv.PREVIOUS, "replicas", "finalized",
                               "blk_9"), "wb") as f:
            f.write(b"payload")
        with open(os.path.join(d, sv.PREVIOUS, sv.VERSION_FILE), "w") as f:
            f.write("layoutVersion=1\nstorageType=datanode\n")
        # current tree: half-migrated mess + in-progress flag
        os.makedirs(os.path.join(d, "volumes", "vol-0", "replicas"))
        sv.write_version(d, "datanode", 1)
        with open(os.path.join(d, sv.UPGRADE_FLAG), "w") as f:
            f.write("1->2\n")
        assert sv.ensure_layout(d, "datanode", sv.DN_UPGRADERS) == 2
        # the retried upgrade migrated the RESTORED tree
        with open(os.path.join(d, "volumes", "vol-0", "replicas",
                               "finalized", "blk_9"), "rb") as f:
            assert f.read() == b"payload"
        assert not os.path.exists(os.path.join(d, sv.UPGRADE_FLAG))

    def test_unfinalized_previous_blocks_new_upgrade(self, tmp_path):
        """previous/ without the in-progress flag = a completed upgrade
        awaiting finalization; a NEW upgrade must refuse rather than
        overwrite the operator's rollback image."""
        d = str(tmp_path / "s")
        os.makedirs(os.path.join(d, sv.PREVIOUS))
        os.makedirs(os.path.join(d, "replicas"))
        sv.write_version(d, "datanode", 1)
        with pytest.raises(sv.LayoutError, match="finalize"):
            sv.ensure_layout(d, "datanode", sv.DN_UPGRADERS)
        # finalizing clears the way
        sv.finalize_upgrade(d)
        assert sv.ensure_layout(d, "datanode", sv.DN_UPGRADERS) == 2

    def test_torn_snapshot_is_discarded_and_upgrade_reruns(self, tmp_path):
        d = str(tmp_path / "s")
        os.makedirs(os.path.join(d, "replicas", "finalized"))
        os.makedirs(os.path.join(d, sv.PREVIOUS_TMP))  # crash artifact
        with open(os.path.join(d, sv.PREVIOUS_TMP, "junk"), "wb") as f:
            f.write(b"torn")
        sv.write_version(d, "datanode", 1)
        assert sv.ensure_layout(d, "datanode", sv.DN_UPGRADERS) == 2
        assert not os.path.exists(os.path.join(d, sv.PREVIOUS_TMP))
        assert sv.has_previous(d)


class TestNnJnVersioning:
    def test_nn_and_jn_dirs_get_version_files(self):
        with MiniCluster(n_datanodes=1, replication=1, ha=True,
                         journal_nodes=3) as mc:
            v = sv.read_version(mc.nn_config.meta_dir)
            assert v and v["storageType"] == "namenode"
            jdirs = [jn._dir for jn in mc.journalnodes if jn is not None]
            assert jdirs
            for jd in jdirs:
                jv = sv.read_version(jd)
                assert jv and jv["storageType"] == "journal"

    def test_nn_future_layout_refuses_boot(self, tmp_path):
        import dataclasses

        from hdrf_tpu.config import NameNodeConfig
        from hdrf_tpu.server.namenode import NameNode

        meta = str(tmp_path / "meta")
        os.makedirs(meta)
        sv.write_version(meta, "namenode", 42)
        cfg = NameNodeConfig(meta_dir=meta, port=0)
        with pytest.raises(sv.LayoutError, match="NEWER"):
            NameNode(cfg)
