"""ContainerStore + ReplicaStore behavior."""

import os

import pytest

from hdrf_tpu.storage.container_store import ContainerStore
from hdrf_tpu.storage.replica_store import ReplicaStore


class TestContainerStore:
    def test_append_and_read(self, tmp_path):
        cs = ContainerStore(str(tmp_path), container_size=1 << 20, lanes=1)
        chunks = [b"a" * 100, b"b" * 200, b"c" * 300]
        locs = cs.append_chunks(chunks)
        assert [ln for _, _, ln in locs] == [100, 200, 300]
        assert cs.read_chunks(locs) == chunks

    def test_rollover_seals_with_compression(self, tmp_path):
        sealed = []
        cs = ContainerStore(str(tmp_path), container_size=1000, lanes=1, codec="lz4")
        locs1 = cs.append_chunks([b"x" * 600], on_seal=sealed.append)
        locs2 = cs.append_chunks([b"y" * 600], on_seal=sealed.append)  # rollover
        assert sealed == [locs1[0][0]]
        assert locs2[0][0] != locs1[0][0]
        # sealed container readable (decompress path), open one raw
        assert cs.read_chunks(locs1) == [b"x" * 600]
        assert cs.read_chunks(locs2) == [b"y" * 600]
        assert os.path.exists(tmp_path / f"{locs1[0][0]}.sealed")
        assert os.path.exists(tmp_path / f"{locs2[0][0]}.raw")

    def test_incompressible_stored_raw_frame(self, tmp_path):
        cs = ContainerStore(str(tmp_path), container_size=100, lanes=1, codec="lz4")
        data = os.urandom(90)
        locs = cs.append_chunks([data])
        cs.flush_open()
        assert cs.read_chunks(locs) == [data]

    def test_lanes_are_independent_containers(self, tmp_path):
        cs = ContainerStore(str(tmp_path), container_size=1 << 20, lanes=2)
        l1 = cs.append_chunks([b"a" * 10])
        l2 = cs.append_chunks([b"b" * 10])
        assert l1[0][0] != l2[0][0]  # round-robin to distinct lanes
        assert cs.read_chunks(l1 + l2) == [b"a" * 10, b"b" * 10]

    def test_id_allocation_survives_restart(self, tmp_path):
        cs = ContainerStore(str(tmp_path), lanes=1)
        locs = cs.append_chunks([b"z" * 10])
        cs.flush_open()
        cs2 = ContainerStore(str(tmp_path), lanes=1)
        locs2 = cs2.append_chunks([b"w" * 10])
        assert locs2[0][0] > locs[0][0]
        assert cs2.read_chunks(locs) == [b"z" * 10]

    def test_compaction_protocol(self, tmp_path):
        cs = ContainerStore(str(tmp_path), container_size=1 << 20, lanes=1)
        locs = cs.append_chunks([b"a" * 100, b"dead" * 25, b"b" * 50])
        cs.flush_open()
        cid = locs[0][0]
        live = {b"h1" * 16: (locs[0][1], locs[0][2]),
                b"h2" * 16: (locs[2][1], locs[2][2])}
        moves = cs.copy_live(cid, live)
        assert set(moves) == set(live)
        # Old container still present until the index commit lands...
        assert os.path.exists(tmp_path / f"{cid}.sealed")
        cs.delete_container(cid)  # ...then dropped (after record_moves)
        assert not os.path.exists(tmp_path / f"{cid}.sealed")
        new_locs = [moves[b"h1" * 16], moves[b"h2" * 16]]
        assert cs.read_chunks(new_locs) == [b"a" * 100, b"b" * 50]

    def test_zstd_codec(self, tmp_path):
        pytest.importorskip("zstandard",
                            reason="zstandard module not installed")
        cs = ContainerStore(str(tmp_path), container_size=100, lanes=1, codec="zstd")
        locs = cs.append_chunks([b"q" * 90])
        cs.flush_open()
        assert cs.read_chunks(locs) == [b"q" * 90]


class TestReplicaStore:
    def test_rbw_to_finalized(self, tmp_path):
        rs = ReplicaStore(str(tmp_path))
        w = rs.create_rbw(42, gen_stamp=7)
        w.write(b"hello")
        w.write(b"world")
        meta = w.finalize(logical_len=10, scheme="direct", checksums=[123])
        assert meta.physical_len == 10 and meta.logical_len == 10
        assert rs.length(42) == 10
        assert rs.read_data(42) == b"helloworld"
        assert rs.block_report() == [(42, 7, 10)]

    def test_reduced_block_zero_physical_is_consistent(self, tmp_path):
        rs = ReplicaStore(str(tmp_path))
        w = rs.create_rbw(1)
        meta = w.finalize(logical_len=128 * 1024, scheme="dedup_lz4")
        assert meta.physical_len == 0
        assert rs.length(1) == 128 * 1024  # logical, from metadata
        assert rs.scan() == []  # NOT flagged corrupt (vs DirectoryScanner.java:437)

    def test_scan_detects_real_problems(self, tmp_path):
        rs = ReplicaStore(str(tmp_path))
        w = rs.create_rbw(5)
        w.write(b"x" * 100)
        w.finalize(logical_len=100, scheme="direct")
        # Truncate the data file behind the store's back.
        with open(rs.data_path(5), "wb") as f:
            f.write(b"x" * 40)
        problems = rs.scan()
        assert len(problems) == 1 and "physical length 40" in problems[0]

    def test_recovery_drops_orphan_rbw(self, tmp_path):
        rs = ReplicaStore(str(tmp_path))
        w = rs.create_rbw(9)
        w.write(b"partial")  # crash: no finalize
        rs2 = ReplicaStore(str(tmp_path))
        assert rs2.get_meta(9) is None
        assert not os.path.exists(tmp_path / "rbw" / "blk_9")

    def test_recovery_loads_finalized(self, tmp_path):
        rs = ReplicaStore(str(tmp_path))
        w = rs.create_rbw(3)
        w.write(b"abc")
        w.finalize(logical_len=3, scheme="lz4", checksums=[1, 2])
        rs2 = ReplicaStore(str(tmp_path))
        m = rs2.get_meta(3)
        assert m.scheme == "lz4" and m.checksums == [1, 2]

    def test_duplicate_create_rejected(self, tmp_path):
        rs = ReplicaStore(str(tmp_path))
        rs.create_rbw(1).finalize(logical_len=0, scheme="direct")
        with pytest.raises(FileExistsError):
            rs.create_rbw(1)

    def test_delete(self, tmp_path):
        rs = ReplicaStore(str(tmp_path))
        w = rs.create_rbw(8)
        w.write(b"data")
        w.finalize(logical_len=4, scheme="direct")
        rs.delete(8)
        assert rs.get_meta(8) is None
        assert rs.block_ids() == []
        assert rs.scan() == []
