"""Encryption zones (EncryptionZoneManager.java:71 / FSDirEncryptionZoneOp
analog): zone keys in the NN's owned key provider, per-file DEKs wrapped by
the zone key (EDEK as a raw.* xattr), transparent client-side ChaCha20
encryption — ciphertext on the DNs, plaintext never leaves the client."""

from __future__ import annotations

import getpass

import numpy as np
import pytest

from hdrf_tpu.client.filesystem import HdrfClient
from hdrf_tpu.proto.rpc import RpcError
from hdrf_tpu.testing.minicluster import MiniCluster

RNG = np.random.default_rng(81)
SUPER = getpass.getuser()


def _bytes(n):
    return RNG.integers(0, 256, size=n, dtype=np.uint8).tobytes()


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_datanodes=2, replication=1, block_size=1 << 20) as mc:
        mc.namenode.rpc_create_encryption_key("zk1")
        mc.namenode.rpc_mkdir("/secure")
        mc.namenode.rpc_create_encryption_zone("/secure", "zk1")
        yield mc


class TestEncryptionZones:
    def test_transparent_roundtrip(self, cluster):
        data = _bytes(1_500_000)
        with cluster.client("w") as c:
            c.write("/secure/f", data, scheme="direct")
            assert c.read("/secure/f") == data

    def test_ranged_reads_decrypt_correctly(self, cluster):
        data = _bytes(300_000)
        with cluster.client("r") as c:
            c.write("/secure/r", data)
            for off, ln in [(0, 100), (64, 64), (63, 130), (100_001, 7777),
                            (299_990, 10), (1, 299_999)]:
                assert c.read("/secure/r", offset=off, length=ln) == \
                    data[off:off + ln], (off, ln)

    def test_ciphertext_on_datanodes(self, cluster):
        """The DN-side replica must NOT contain the plaintext."""
        marker = b"TOP-SECRET-MARKER" * 100
        data = marker + _bytes(50_000)
        with cluster.client("ct") as c:
            c.write("/secure/ct", data, scheme="direct")
            loc = c._call("get_block_locations", path="/secure/ct")
            assert loc["encrypted"]
            bid = loc["blocks"][0]["block_id"]
        for dn in cluster.datanodes:
            meta = dn.replicas.get_meta(bid)
            if meta is not None:
                stored = dn.replicas.read_data(bid)
                assert marker not in stored
                break
        else:
            pytest.fail("no DN holds the block")

    def test_dedup_scheme_in_zone(self, cluster):
        """Reduction operates on ciphertext (dedup yields little across
        files — the privacy/reduction trade encrypted storage always has —
        but the round trip must hold)."""
        data = _bytes(400_000)
        with cluster.client("dz") as c:
            c.write("/secure/dz", data, scheme="dedup_lz4")
            assert c.read("/secure/dz") == data

    def test_decrypt_edek_requires_read_permission(self, cluster):
        with cluster.client("own") as su:
            su.write("/secure/priv", _bytes(10_000))
            su.chmod("/secure/priv", 0o600)
            su.chmod("/secure", 0o755)
        mal = HdrfClient(cluster.namenode.addr, user="mallory")
        try:
            with pytest.raises(RpcError) as ei:
                mal._call("decrypt_edek", path="/secure/priv")
            assert ei.value.error == "PermissionError"
        finally:
            mal.close()

    def test_zone_constraints(self, cluster):
        nn = cluster.namenode
        with pytest.raises(IOError):
            nn.rpc_create_encryption_zone("/secure", "zk1")  # nested/self
        nn.rpc_mkdir("/notempty/x")
        with pytest.raises(IOError):
            nn.rpc_create_encryption_zone("/notempty", "zk1")
        nn.rpc_mkdir("/ez2")
        with pytest.raises(KeyError):
            nn.rpc_create_encryption_zone("/ez2", "nokey")
        assert nn.rpc_get_ez("/secure/deep/er")["zone"] == "/secure"
        assert nn.rpc_get_ez("/elsewhere")["zone"] is None
        assert "/secure" in nn.rpc_list_encryption_zones()

    def test_append_to_encrypted_rejected(self, cluster):
        with cluster.client("ap") as c:
            c.write("/secure/ap", _bytes(1000))
            with pytest.raises(RpcError):
                c.append("/secure/ap", b"more")

    def test_zone_survives_restart(self, tmp_path):
        from hdrf_tpu.config import NameNodeConfig
        from hdrf_tpu.server.namenode import NameNode

        nn = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "nn")))
        nn.rpc_create_encryption_key("zkr")
        nn.rpc_mkdir("/z")
        nn.rpc_create_encryption_zone("/z", "zkr")
        key_before = bytes(nn._ezkeys["zkr"])
        nn._editlog.close()
        nn2 = NameNode(NameNodeConfig(meta_dir=str(tmp_path / "nn")))
        assert nn2.rpc_list_encryption_zones() == {"/z": "zkr"}
        assert bytes(nn2._ezkeys["zkr"]) == key_before
        nn2._editlog.close()
