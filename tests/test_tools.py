"""CLI tools (hdrf_tpu/tools/cli.py): dfs ops, dfsadmin, oiv/oev offline
viewers, and the balancer — the reference's bin/hdfs + DFSAdmin + OIV/OEV +
Balancer surface."""

import io
import json
import os
import time
from contextlib import redirect_stdout

import numpy as np
import pytest

from hdrf_tpu.testing.minicluster import MiniCluster
from hdrf_tpu.tools import cli


@pytest.fixture(scope="module")
def cluster():
    with MiniCluster(n_datanodes=3, replication=2) as mc:
        yield mc


def run(argv) -> tuple[int, str]:
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(argv)
    return rc, buf.getvalue()


def nn_arg(mc) -> str:
    return f"{mc.namenode.addr[0]}:{mc.namenode.addr[1]}"


class TestDfsCli:
    def test_put_ls_cat_stat_rm(self, cluster, tmp_path):
        payload = np.random.default_rng(0).integers(
            0, 256, size=100_000, dtype=np.uint8).tobytes()
        local = tmp_path / "in.bin"
        local.write_bytes(payload)
        nn = nn_arg(cluster)
        assert run(["dfs", "--namenode", nn, "-mkdir", "/t"])[0] == 0
        assert run(["dfs", "--namenode", nn, "--scheme", "dedup_lz4",
                    "-put", str(local), "/t/f"])[0] == 0
        rc, out = run(["dfs", "--namenode", nn, "-ls", "/t"])
        assert rc == 0 and "f" in out
        rc, out = run(["dfs", "--namenode", nn, "-stat", "/t/f"])
        assert rc == 0 and json.loads(out)["length"] == len(payload)
        out_file = tmp_path / "out.bin"
        assert run(["dfs", "--namenode", nn, "-get", "/t/f",
                    str(out_file)])[0] == 0
        assert out_file.read_bytes() == payload
        rc, out = run(["dfs", "--namenode", nn, "-du", "/t"])
        assert rc == 0 and int(out.strip()) == len(payload)
        assert run(["dfs", "--namenode", nn, "-mv", "/t/f", "/t/g"])[0] == 0
        assert run(["dfs", "--namenode", nn, "-rm", "/t/g"])[0] == 0
        assert run(["dfs", "--namenode", nn, "-rm", "/t/g"])[0] == 1

    def test_dfsadmin_report_and_metrics(self, cluster):
        nn = nn_arg(cluster)
        rc, out = run(["dfsadmin", "--namenode", nn, "-report"])
        assert rc == 0 and out.count("live") == 3
        # enriched -report: cluster summary header + reduction accounting
        # + health intelligence lines precede the per-DN lines
        assert "Cluster: up=3 down=0" in out
        assert "dedup_ratio=" in out and "slow_peers=" in out
        assert "stalls=" in out and "failed_volumes=" in out
        assert "reduction_degraded=0" in out  # healthy cluster: none
        rc, out = run(["dfsadmin", "--namenode", nn, "-metrics"])
        assert rc == 0 and "namenode" in json.loads(out)
        assert run(["dfsadmin", "--namenode", nn, "-savenamespace"])[0] == 0

    def test_dfsadmin_slow_peers_json(self, cluster):
        nn = nn_arg(cluster)
        rc, out = run(["dfsadmin", "--namenode", nn, "-slowPeers"])
        assert rc == 0
        doc = json.loads(out)
        for key in ("slow_peers", "slow_volumes", "peer_medians_s_per_mb",
                    "volume_probe_medians_s", "reporters"):
            assert key in doc, f"-slowPeers missing {key}"


class TestParityCitations:
    def test_every_module_cites_references(self):
        """tools/check_parity.py as a tier-1 gate: every hdrf_tpu module
        docstring carries at least one file:line reference citation (the
        CLAUDE.md parity convention)."""
        import hdrf_tpu
        from hdrf_tpu.tools import check_parity

        root = os.path.dirname(os.path.abspath(hdrf_tpu.__file__))
        problems = check_parity.check(root)
        assert not problems, "\n".join(problems)

    def test_every_fault_point_is_exercised(self):
        """Fault-point lint as a tier-1 gate: every
        ``fault_injection.point(...)`` name declared in main code must be
        referenced by at least one test — an unexercised crash window is a
        crash window nobody has proven survivable."""
        import hdrf_tpu
        from hdrf_tpu.tools import check_parity

        root = os.path.dirname(os.path.abspath(hdrf_tpu.__file__))
        points = check_parity.declared_fault_points(root)
        assert "block_receiver.packet" in points  # the matrix's anchor
        problems = check_parity.check_fault_points(root)
        assert not problems, "\n".join(problems)

    def test_every_metric_is_documented(self):
        """Prom-metric lint as a tier-1 gate: every metric name declared
        with a plain string literal must have a backticked row in
        ARCHITECTURE.md's metrics reference — an undocumented gauge is a
        dashboard nobody can interpret."""
        import hdrf_tpu
        from hdrf_tpu.tools import check_parity

        root = os.path.dirname(os.path.abspath(hdrf_tpu.__file__))
        names = check_parity.declared_metrics(root)
        # anchors across the spine: the new profiler family + the ledger
        assert "blocks_profiled" in names and "wait_us" in names
        problems = check_parity.check_prom_metrics(root)
        assert not problems, "\n".join(problems)
        # dynamic (f-string) families are exempt from the regex by
        # construction but must still be documented — pin the ones the
        # profiler/ledger emit today
        arch = open(os.path.join(os.path.dirname(root),
                                 "ARCHITECTURE.md")).read()
        for fam in ("phase_us", "wait_us", "inflight_blocks",
                    "outstanding_dispatches", "wal_queue_depth"):
            assert f"`{fam}`" in arch, f"{fam} missing from metrics table"

    def test_bench_multichip_block_in_both_json_branches(self):
        """Bench-contract lint as a tier-1 gate: bench.py prints its one
        JSON line from two branches (native fallback and the TPU path), so
        the multichip service-rate block must be a literal key in BOTH —
        a block added to one branch silently vanishes on the other
        backend."""
        import hdrf_tpu
        from hdrf_tpu.tools import check_parity

        root = os.path.dirname(os.path.abspath(hdrf_tpu.__file__))
        problems = check_parity.check_bench_contract(root)
        assert not problems, "\n".join(problems)

    def test_bench_mirror_block_in_both_json_branches(self):
        """Same contract for the coded mirror plane's summary block: the
        hedge/ack numbers (server/mirror_plane.py) must ride BOTH
        json.dumps branches of bench.py or the driver loses them on one
        backend — and the output must stay exactly one JSON line."""
        import hdrf_tpu
        from hdrf_tpu.tools import check_parity

        root = os.path.dirname(os.path.abspath(hdrf_tpu.__file__))
        problems = check_parity.check_bench_contract(root, key="mirror")
        assert not problems, "\n".join(problems)

    def test_bench_read_keys_ride_both_json_branches(self):
        """Dotted bench-contract lint for the read-plane serving-engine
        keys: chunk_cache_hit_ratio / read_batches /
        containers_decoded_per_read must be literal keys of the "read"
        block's summary helper (the ``return {...}`` of _read_summary),
        reachable from BOTH json.dumps branches — a key dropped from the
        helper would silently vanish from the stamp on every backend."""
        import hdrf_tpu
        from hdrf_tpu.tools import check_parity

        root = os.path.dirname(os.path.abspath(hdrf_tpu.__file__))
        for key in ("read.chunk_cache_hit_ratio", "read.read_batches",
                    "read.containers_decoded_per_read"):
            problems = check_parity.check_bench_contract(root, key=key)
            assert not problems, "\n".join(problems)
        # the lint actually bites: a key nobody returns must fail
        assert check_parity.check_bench_contract(
            root, key="read.no_such_key_ever")

    def test_bench_scrub_block_in_both_json_branches(self):
        """Same contract for the integrity-scrub summary block: the
        bytes_verified / corrupt_total / garbage_bytes numbers
        (server/scrubber.py) must be a literal key in BOTH json.dumps
        branches of bench.py — and the output must stay exactly one JSON
        line."""
        import hdrf_tpu
        from hdrf_tpu.tools import check_parity

        root = os.path.dirname(os.path.abspath(hdrf_tpu.__file__))
        problems = check_parity.check_bench_contract(root, key="scrub")
        assert not problems, "\n".join(problems)

    def test_bench_qos_block_in_both_json_branches(self):
        """Overload-plane bench contract (ISSUE 14): the "qos" block —
        sheds / shed_retry_after_p50_ms / tenant_fairness_ratio /
        ec_hedges_fired / ec_hedge_wins from _qos_summary — must be a
        literal key in BOTH json.dumps branches of bench.py, and the
        summary keys must be literal keys of the helper's return dict."""
        import hdrf_tpu
        from hdrf_tpu.tools import check_parity

        root = os.path.dirname(os.path.abspath(hdrf_tpu.__file__))
        for key in ("qos", "qos.sheds", "qos.shed_retry_after_p50_ms",
                    "qos.tenant_fairness_ratio", "qos.ec_hedges_fired",
                    "qos.ec_hedge_wins"):
            problems = check_parity.check_bench_contract(root, key=key)
            assert not problems, "\n".join(problems)

    def test_bench_cdc_adaptive_block_in_both_json_branches(self):
        """Adaptive-chunking bench contract (ISSUE 15): the "cdc_adaptive"
        block — skip_ahead / scan_slab_survivors / mask_bits_effective /
        retunes from _cdc_adaptive_summary — must be a literal key in
        BOTH json.dumps branches of bench.py, and the summary keys must
        be literal keys of the helper's return dict."""
        import hdrf_tpu
        from hdrf_tpu.tools import check_parity

        root = os.path.dirname(os.path.abspath(hdrf_tpu.__file__))
        for key in ("cdc_adaptive", "cdc_adaptive.skip_ahead",
                    "cdc_adaptive.scan_slab_survivors",
                    "cdc_adaptive.mask_bits_effective",
                    "cdc_adaptive.retunes"):
            problems = check_parity.check_bench_contract(root, key=key)
            assert not problems, "\n".join(problems)

    def test_bench_longhorizon_block_in_both_json_branches(self):
        """Long-horizon flight-plane bench contract (ISSUE 17): the
        "longhorizon" block — and its storage_ratio_slope churn-curve
        key from _longhorizon_summary — must be a literal key in BOTH
        json.dumps branches of bench.py."""
        import hdrf_tpu
        from hdrf_tpu.tools import check_parity

        root = os.path.dirname(os.path.abspath(hdrf_tpu.__file__))
        for key in ("longhorizon", "longhorizon.storage_ratio_slope"):
            problems = check_parity.check_bench_contract(root, key=key)
            assert not problems, "\n".join(problems)


class TestChurnHarness:
    def test_churn_one_json_line_with_curves(self):
        """`benchmarks churn` contract (ISSUE 17): EXACTLY one JSON line
        carrying per-round flight samples, the four SLO curves with
        first/last/slope, and the trend verdict.  Tiny run — deletes
        against sealed containers must push the final storage_ratio
        ABOVE the first round's (the physical bytes stay, the logical
        shrink: that's the regression the curve exists to show)."""
        from hdrf_tpu import benchmarks

        buf = io.StringIO()
        with redirect_stdout(buf):
            assert benchmarks.main(
                ["churn", "--rounds", "3", "--files", "3", "--kb", "8",
                 "--delete-frac", "0.5"]) == 0
        lines = buf.getvalue().splitlines()
        assert len(lines) == 1
        o = json.loads(lines[0])
        assert o["op"].startswith("churn")
        assert o["rounds"] == 3 and o["samples"] == 3
        for name in ("storage_ratio", "garbage_bytes",
                     "chunk_cache_hit_ratio", "read_p95_ms"):
            curve = o["curves"][name]
            assert {"first", "last", "slope", "series"} <= set(curve)
            assert len(curve["series"]) == 3
        sr = o["curves"]["storage_ratio"]
        assert sr["last"] > sr["first"]  # deletes inflate the ratio
        assert "storage_ratio" in o["regressions"]
        assert o["verdict"] == "REGRESSED"


class TestObserverAbHarness:
    def test_observer_ab_one_json_line(self):
        """`benchmarks nn --observer-ab` contract (ISSUE 20): EXACTLY one
        JSON line with paired a/b legs and the observer-plane keys; the
        observer leg must actually route reads off the active."""
        from hdrf_tpu import benchmarks

        buf = io.StringIO()
        with redirect_stdout(buf):
            assert benchmarks.main(
                ["nn", "--observer-ab", "--ops", "40", "--clients", "2",
                 "--meta-per-op", "2", "--rounds", "1"]) == 0
        lines = buf.getvalue().splitlines()
        assert len(lines) == 1
        o = json.loads(lines[0])
        assert o["bench"] == "nn_observer_ab"
        for leg in ("a", "b"):
            assert {"read_p99_ms", "active_read_lock_share",
                    "ops_per_s"} <= set(o[leg])
        for key in ("observer_reads", "observer_share", "msync_p99_ms",
                    "observer_lag_txids"):
            assert key in o
        assert o["errors"] == 0
        assert o["observer_reads"] > 0
        # the tentpole's acceptance bar: observers drain the active's
        # read-method lock share
        assert o["b"]["active_read_lock_share"] \
            <= o["a"]["active_read_lock_share"]


class TestOfflineViewers:
    def test_oiv_oev(self, cluster, tmp_path):
        nn = nn_arg(cluster)
        with cluster.client("viewer") as c:
            c.write("/viewer/f", b"x" * 1000)
        run(["dfsadmin", "--namenode", nn, "-savenamespace"])
        meta = cluster.nn_config.meta_dir
        rc, out = run(["oiv", meta])
        assert rc == 0
        lines = [json.loads(line) for line in out.splitlines()]
        assert any(e.get("path") == "/viewer/f" for e in lines)
        with cluster.client("viewer2") as c:
            c.mkdir("/viewer/after-image")
        rc, out = run(["oev", meta])
        assert rc == 0
        recs = [json.loads(line) for line in out.splitlines()]
        assert any(r["op"] == "mkdir" and r["args"][0] == "/viewer/after-image"
                   for r in recs)


class TestBalancer:
    def test_balancer_moves_blocks(self):
        with MiniCluster(n_datanodes=2, replication=1,
                         block_size=16 * 1024) as mc:
            nn = nn_arg(mc)
            rng = np.random.default_rng(7)
            with mc.client("bal") as c:
                for i in range(6):
                    c.write(f"/bal/f{i}",
                            rng.integers(0, 256, 40_000, dtype=np.uint8)
                            .tobytes())
                # boot a new empty DN; everything sits on dn-0/dn-1
                mc.datanodes.append(mc._make_dn(2).start())
                mc.wait_for_datanodes(3)
                rc, out = run(["balancer", "--namenode", nn,
                               "--threshold", "1", "--batch", "4",
                               "--wait-s", "1", "--iterations", "6"])
                assert rc == 0
                deadline = time.monotonic() + 15
                while time.monotonic() < deadline:
                    rep = {d["dn_id"]: d["blocks"]
                           for d in c.datanode_report() if d["alive"]}
                    if rep.get("dn-2", 0) > 0:
                        break
                    time.sleep(0.3)
                assert rep.get("dn-2", 0) > 0, rep
                # data still readable after moves settle
                for i in range(6):
                    assert len(c.read(f"/bal/f{i}")) == 40_000


class TestLiveReconfiguration:
    """ReconfigurationProtocol / TestDataNodeReconfiguration analog: a
    whitelist of DataNode keys changes without a restart."""

    def test_reconfigure_over_the_wire_and_cli(self, capsys):
        import json as _json
        import socket

        from hdrf_tpu.proto import datatransfer as dt
        from hdrf_tpu.proto.rpc import recv_frame
        from hdrf_tpu.testing.minicluster import MiniCluster
        from hdrf_tpu.tools import cli

        with MiniCluster(n_datanodes=1, replication=1) as mc:
            dn = mc.datanodes[0]
            addr = f"{dn.addr[0]}:{dn.addr[1]}"
            with socket.create_connection(dn.addr, timeout=10) as s:
                dt.send_op(s, "get_reconfigurable")
                keys = recv_frame(s)["keys"]
            assert "cache_capacity" in keys and "scan_interval_s" in keys
            # apply via the dfsadmin CLI path
            rc = cli.main(["dfsadmin", "--namenode",
                           f"{mc.namenode.addr[0]}:{mc.namenode.addr[1]}",
                           "-reconfig", addr, "cache_capacity", "12345"])
            assert rc in (0, None)
            out = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
            assert out["ok"] and out["new"] == 12345
            assert dn.config.cache_capacity == 12345
            assert dn.cache._capacity == 12345
            # non-whitelisted keys refuse
            with socket.create_connection(dn.addr, timeout=10) as s:
                dt.send_op(s, "reconfigure", key="data_dir", value="/x")
                r = recv_frame(s)
            assert not r["ok"] and "not reconfigurable" in r["error"]

    def test_interval_guards(self):
        """0/negative intervals would busy-spin the loops; a loop disabled
        at startup was never spawned and must not pretend to change."""
        import dataclasses
        import socket

        from hdrf_tpu.proto import datatransfer as dt
        from hdrf_tpu.proto.rpc import recv_frame
        from hdrf_tpu.testing.minicluster import MiniCluster

        with MiniCluster(n_datanodes=1, replication=1) as mc:
            dn = mc.datanodes[0]

            def reconf(key, value):
                with socket.create_connection(dn.addr, timeout=10) as s:
                    dt.send_op(s, "reconfigure", key=key, value=value)
                    return recv_frame(s)

            r = reconf("scan_interval_s", 0)
            assert not r["ok"] and "restart" in r["error"]
            r = reconf("volume_check_interval_s", -1)
            assert not r["ok"]
            # the volume-check loop is disabled in MiniCluster DNs
            # (simulated probe friction): a new interval must refuse,
            # not silently no-op
            if not any(t.name.endswith("-volcheck") and t.is_alive()
                       for t in dn._threads):
                r = reconf("volume_check_interval_s", 5)
                assert not r["ok"] and "not running" in r["error"]
            r = reconf("scan_interval_s", 7)
            assert r["ok"] and dn.config.scan_interval_s == 7
