#!/usr/bin/env python
"""Headline benchmark: DataNode write-path reduction throughput.

Two measurements, one JSON line:

- ``value``/``vs_baseline`` — the block-reduction service rate (CDC + SHA-256
  fingerprinting, ops/resident.py), the hot device pipeline of
  DedupScheme.reduce, re-expressing the reference's
  DataDeduplicator.java:264-307 chunk scan + utilities.java:98-137 JNI
  hashing.  Comparable across rounds.
- ``e2e_*`` keys — the FULL dedup_lz4 write path per block: device CDC+SHA,
  host dedup lookup, real ChunkIndex WAL commit (fsync), real ContainerStore
  append (disk), and the container-seal entropy stage with TPU match
  discovery (ops/lz4_tpu.py) + native emit, with the resulting reduction
  ratio.  The CPU baseline runs the identical path single-threaded with the
  native C++ ops (the reference's execution model: dedup ingest concurrency
  nWrite=1, DataNode.java:499-510).

Metric framing: sustained service rate over HBM-resident inputs with the
overlapped submit/finish pattern — the TPU worker's steady state in the
co-located deployment (BASELINE.json north star), where block bytes arrive
in HBM via the DataNode's streaming path and container payloads are staged
during reduction.  The dev-environment tunnel moves bulk bytes at ~25 MB/s
each way (PERF_NOTES.md), which would measure the WAN link, not the
framework; device inputs are therefore staged untimed, while every dispatch,
record/digest readback, host bookkeeping, WAL fsync, container write, and
emit IS timed.  Container payloads produced by the timed pass are asserted
byte-identical to the staged images, so the device never computes on stale
bytes.

Prints ONE JSON line:
  {"metric": ..., "value": <MB/s>, "unit": "MB/s", "vs_baseline": <x>,
   "e2e_value": <MB/s>, "e2e_vs_baseline": <x>,
   "e2e_ratio_tpu": <r>, "e2e_ratio_cpu": <r>,
   "tg_value": <MB/s>, "tg_vs_baseline": <x>,
   "tg_ratio_tpu": <r>, "tg_ratio_cpu": <r>,   # TeraGen-row corpus
   "phase_profile": {"wall_s", "classes", "phases",
                     "overlap_efficiency", "attributed_frac"},
                                               # write-path critical-path
                                               # profiler window over the
                                               # e2e passes (utils/profiler)
   "ec": {"stripes_encoded", "degraded_reads", "repair_bytes",
          "storage_ratio"},                    # EC cold-tier stamp
                                               # (storage/stripe_store.py)
   "read": {"read_amplification", "cache_hit_ratio", "read_p95_ms",
            "tenant_count",
            "chunk_cache_hit_ratio", "read_batches",
            "containers_decoded_per_read"}}    # read-plane stamp over the
                                               # product reconstruct path +
                                               # serving engine
                                               # (server/read_plane.py);
                                               # HDRF_BENCH_READ_MOSTLY=1
                                               # scales the replay rounds
                                               # and interleaves writes
                                               # (mixed read/write profile)
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

BLOCK_MB = 64
N_BLOCKS = 16
SUB_BATCHES = 4
CPU_MB = 32
E2E_BLOCKS = 8          # full-path pass size (HBM also holds container images)
TG_BLOCKS = 8           # TeraGen-corpus pass size (long enough steady state
                        # to amortize the fixed dispatch/readback overheads)

if os.environ.get("HDRF_BENCH_SMOKE") == "1":
    # Tiny-corpus mode for the tier-1 one-line guard test: same code path
    # and JSON contract, seconds instead of minutes (runs under XLA:CPU).
    BLOCK_MB, N_BLOCKS, SUB_BATCHES, CPU_MB = 1, 2, 2, 1
    E2E_BLOCKS = TG_BLOCKS = 2

READ_MOSTLY = os.environ.get("HDRF_BENCH_READ_MOSTLY") == "1"
READ_ROUNDS = 3
if READ_MOSTLY:
    # Read-mostly profile (same pattern as HDRF_BENCH_SMOKE): the read
    # stamp replays its corpus many more times — and interleaves fresh
    # dedup commits between replay rounds (a mixed read/write scenario) —
    # so the cache-hit ratio and read-amplification numbers reflect a
    # serving-heavy DataNode instead of a write-dominated one.
    READ_ROUNDS = 16


def _make_block(mb: int, seed: int) -> np.ndarray:
    """Realistic-entropy block: compressible text-like spans + binary spans +
    planted duplicate regions (so CDC/dedup has real work, not pure noise)."""
    rng = np.random.default_rng(seed)
    n = mb << 20
    a = rng.integers(0, 256, size=n, dtype=np.uint8)
    a[: n // 4] = rng.integers(97, 123, size=n // 4, dtype=np.uint8)
    span = min(8 << 20, n // 4)
    a[n // 2 : n // 2 + span] = a[:span]
    return a


def _salt(block: np.ndarray, i: int) -> np.ndarray:
    b = block.copy()
    b[:4096] ^= np.uint8((i * 37 + 1) % 251)
    return b


def _teragen_blocks(n_blocks: int, mb: int, seed: int = 13) -> list[np.ndarray]:
    """TeraGen-row corpus (the north-star benchmark's own data,
    BASELINE.json): 100-byte records — 10 random key bytes, 10 ASCII row-id
    digits, 78 filler bytes of per-row shifting 10-letter blocks, CRLF.
    Vectorized; row ids run continuously across blocks."""
    rng = np.random.default_rng(seed)
    rows_per_block = (mb << 20) // 100
    out = []
    base_id = 0
    for _ in range(n_blocks):
        n = rows_per_block
        rec = np.empty((n, 100), dtype=np.uint8)
        rec[:, :10] = rng.integers(0, 256, size=(n, 10), dtype=np.uint8)
        ids = base_id + np.arange(n, dtype=np.int64)
        for d in range(10):  # ASCII row id, most significant digit first
            rec[:, 10 + d] = (ids // 10 ** (9 - d) % 10 + 48).astype(np.uint8)
        blocks_j = (np.arange(78) // 10)[None, :]          # filler block idx
        rec[:, 20:98] = (65 + (ids[:, None] + blocks_j) % 26).astype(np.uint8)
        rec[:, 98] = 13
        rec[:, 99] = 10
        base_id += n
        flat = rec.reshape(-1)
        pad = (mb << 20) - flat.size
        out.append(np.concatenate([flat,
                                   np.zeros(pad, np.uint8)]) if pad else flat)
    return out


def _cpu_run(blocks: list[np.ndarray], cdc) -> float:
    from hdrf_tpu import native
    from hdrf_tpu.ops.dispatch import gear_mask

    mask = gear_mask(cdc)
    t0 = time.perf_counter()
    total = 0
    for buf in blocks:
        cuts = native.cdc_chunk(buf, mask, cdc.min_chunk, cdc.max_chunk)
        starts = np.concatenate([[0], cuts[:-1]]).astype(np.uint64)
        native.sha256_batch(buf, starts, (cuts - starts).astype(np.uint64))
        total += buf.size
    return total / (time.perf_counter() - t0) / (1 << 20)


# --------------------------------------------------------- full write path


def _dedup_bookkeeping(block_id, data, cuts, digests, index, containers,
                       on_seal=None):
    """The host half of the write pipeline — the SAME function
    DedupScheme.reduce runs (reduction/dedup.py:dedup_commit), so the timed
    path is the product path."""
    from hdrf_tpu.reduction.dedup import dedup_commit

    dedup_commit(block_id, data, cuts, digests, index, containers,
                 on_seal=on_seal)


def _chain_seal(index, containers):
    """Index seal record + drop the transient container file: the bench
    writes the final sealed output itself (sealed.<cid>, mirroring the
    product's compress-and-replace), so the store's copy is the raw
    intermediate the product unlinks — keeping it would double-count
    container I/O vs the product path."""
    def on_seal(cid):
        index.seal_container(cid)
        containers.delete_container(cid)
    return on_seal


def _fresh_stores(tmp: str, tag: str, on_roll=None):
    from hdrf_tpu.index.chunk_index import ChunkIndex
    from hdrf_tpu.storage.container_store import ContainerStore

    d = os.path.join(tmp, tag)
    os.makedirs(d)
    # codec "none": the rollover entropy stage runs as an explicit timed
    # stage below (TPU match scan / native LZ4), mirroring the reference's
    # async storer-thread compression (DataDeduplicator.java:770-781).
    containers = ContainerStore(os.path.join(d, "containers"),
                                codec="none", lanes=2, on_roll=on_roll)
    index = ChunkIndex(os.path.join(d, "index"))
    return index, containers


def _cpu_full(blocks: list[np.ndarray], cdc, tmp: str, tag: str):
    """Single-thread native full path; returns (MB/s, reduction_ratio,
    dedup_ratio) — the last recomputed from the chunk index tables before
    close, the same ground truth dfsadmin -report aggregates.  The entropy
    stage runs on each container payload as it rolls over (the on_roll
    hook — same code path the TPU pass uses)."""
    from hdrf_tpu import native
    from hdrf_tpu.ops.dispatch import gear_mask
    from hdrf_tpu.utils import profiler

    mask = gear_mask(cdc)
    state = {"stored": 0}

    def seal_now(cid, payload):
        with profiler.phase("reduce_compute"):
            comp = native.lz4_compress(payload)
        out = comp if len(comp) < len(payload) else payload
        with profiler.phase("container_io"):
            with open(os.path.join(tmp, tag, f"sealed.{cid}"), "wb") as f:
                f.write(out)
        state["stored"] += len(out)

    index, containers = _fresh_stores(tmp, tag, on_roll=seal_now)
    on_seal = _chain_seal(index, containers)
    t0 = time.perf_counter()
    total = 0
    for bid, buf in enumerate(blocks):
        # direct native calls bypass ops/dispatch.py, so the pass phases
        # its own CDC+SHA stage (the rest — dedup_lookup, wal_commit,
        # container_io — is phased inside the product code it calls)
        with profiler.phase("reduce_compute"):
            cuts = native.cdc_chunk(buf, mask, cdc.min_chunk, cdc.max_chunk)
            starts = np.concatenate([[0], cuts[:-1]]).astype(np.uint64)
            digs = native.sha256_batch(buf, starts,
                                       (cuts - starts).astype(np.uint64))
        _dedup_bookkeeping(bid, buf, cuts, digs, index, containers,
                           on_seal=on_seal)
        total += buf.size
    containers.flush_open(on_seal=on_seal)
    dt = time.perf_counter() - t0
    ist = index.stats()
    index.close()
    from hdrf_tpu.reduction import accounting

    return (total / dt / (1 << 20), total / max(state["stored"], 1),
            accounting.dedup_ratio(ist["logical_bytes"],
                                   ist["unique_chunk_bytes"]))


def _cdc_fused_summary() -> dict:
    """Fused-CDC ledger sub-dict for the JSON line: how the run's CDC front
    end actually dispatched.  ``candidate_d2h_events`` counts XLA-prep
    completions (each one IS a packed-candidate readback) — zero in fused
    steady state; a nonzero value alongside fused dispatches means the
    overflow fallback fired (tests/test_cdc_pallas.py pins both)."""
    from hdrf_tpu.ops.cdc_pallas import cdc_pallas_mode
    from hdrf_tpu.utils import device_ledger

    evs = device_ledger.events_snapshot()
    prep_ops = {"resident.prep", "resident.prep_batch",
                "resident.prep_retry"}
    return {
        "mode": cdc_pallas_mode(),
        "fused_dispatches": sum(1 for e in evs if e["kind"] == "dispatch"
                                and e["op"] == "resident.cdc_fused"),
        "xla_prep_dispatches": sum(1 for e in evs
                                   if e["kind"] == "dispatch"
                                   and e["op"] in prep_ops),
        "candidate_d2h_events": sum(1 for e in evs
                                    if e["kind"] == "dispatch"
                                    and e["op"] in prep_ops),
    }


def _cdc_adaptive_summary() -> dict:
    """Adaptive-chunking sub-dict for the JSON line (ISSUE 15): which scan
    variant the run used, the skip-ahead kernel's slab-survivor/candidate
    telemetry, the effective geometry the accounting plane last stamped,
    and how many live retunes the DataNode controller drove.  All zeros
    under ``HDRF_CDC_SKIP_AHEAD=0`` or with ``cdc_adaptive`` off — the
    keys stay present so tools/check_parity.py's bench contract holds on
    every path."""
    from hdrf_tpu.ops.cdc_pallas import cdc_skip_ahead
    from hdrf_tpu.reduction import accounting

    snap = accounting.snapshot()
    ctr, gauges = snap["counters"], snap["gauges"]
    return {
        "skip_ahead": cdc_skip_ahead(),
        "scan_slab_survivors": int(ctr.get("cdc_scan_slab_survivors", 0)),
        "mask_bits_effective": int(gauges.get("cdc_mask_bits_effective", 0)),
        "retunes": int(ctr.get("cdc_retunes", 0)),
    }


def _slow_peer_count() -> int:
    """Slow peers flagged by the cluster outlier detector — the bench runs
    no cluster, so this is the detector's verdict over an empty report set
    (0), keeping the JSON schema identical to the NN's /prom gauge."""
    from hdrf_tpu.utils import outlier

    return len(outlier.detect({}))


def _resilience_summary() -> dict:
    """Degraded-mode health of the run, read from the same process-wide
    registries the daemons export (utils/retry.py breakers, block_receiver
    fallback accounting).  The bench drives the reduction pipeline directly
    (no DN worker edge), so both are 0 on a healthy run — a nonzero
    ``breaker_open_total`` or ``degraded_writes`` means a dependency edge
    tripped open or a write fell back to the in-process path mid-bench,
    which taints the throughput verdict and must be visible in the line."""
    from hdrf_tpu.utils import metrics

    return {
        "breaker_open_total":
            metrics.registry("resilience").counter("breaker_open_total"),
        "degraded_writes":
            metrics.registry("block_receiver").counter("degraded_writes"),
    }


def _ec_summary() -> dict:
    """EC cold-tier stamp for the JSON line: a small in-process
    demote-shaped exercise through storage/stripe_store.py — encode one
    container at RS(6,3), drop m stripes INCLUDING data indices (the
    worst degraded case), reconstruct, assert bit-identity — then the
    process-wide ``ec`` registry counters (this exercise plus any product
    EC activity in the run).  ``storage_ratio`` is the tier's
    physical/logical expansion, (k+m)*stripe_len / length ≈ 1.5."""
    from hdrf_tpu.storage import stripe_store
    from hdrf_tpu.utils import metrics

    k, m = 6, 3
    rng = np.random.default_rng(11)
    payload = rng.integers(0, 256, size=(1 << 20) + 3,
                           dtype=np.uint8).tobytes()
    stripes, manifest = stripe_store.encode_container(payload, k, m)
    survivors = {i: stripes[i] for i in range(m, k + m)}
    assert stripe_store.reconstruct_container(survivors, manifest) \
        == payload, "EC degraded read diverged from the encoded container"
    ec = metrics.registry("ec")
    return {
        "stripes_encoded": ec.counter("stripes_encoded"),
        "degraded_reads": ec.counter("degraded_reads"),
        "repair_bytes": ec.counter("repair_bytes"),
        "storage_ratio": round(
            (k + m) * manifest["stripe_len"] / manifest["length"], 4),
    }


def _coded_exchange_summary() -> dict:
    """Coded-exchange stamp for the JSON line: a small in-process
    partial-sum repair through ops/rs.py — encode one container at
    RS(6,3), rebuild a lost data stripe by XOR-folding per-holder
    ``partial_sums`` contributions, assert bit-identity against the
    full-gather ``reconstruct_container`` oracle — booked through the
    SAME ``book_repair_wire`` ledger the live repair path stamps
    (server/coded_exchange.py), so ``repair_wire_ratio`` here is the
    process-wide gauge (this exercise plus any product repair activity:
    a full-gather fallback in the run pulls it back up toward k).  A
    pack/unpack round trip of a compressible payload exercises the
    smaller-of LZ4 negotiation; pack_saved_frac is bytes saved across
    every negotiation this process ran."""
    from hdrf_tpu.ops import rs
    from hdrf_tpu.server import coded_exchange
    from hdrf_tpu.storage import stripe_store
    from hdrf_tpu.utils import metrics

    k, m = 6, 3
    rng = np.random.default_rng(23)
    payload = rng.integers(0, 256, size=(1 << 20) + 5,
                           dtype=np.uint8).tobytes()
    stripes, manifest = stripe_store.encode_container(payload, k, m)
    stripe_len = int(manifest["stripe_len"])
    missing = [0]
    shards = {i: np.frombuffer(s, dtype=np.uint8)
              for i, s in enumerate(stripes) if i not in missing}
    have = sorted(shards)[:k]
    rows = rs.repair_rows(k, m, tuple(have), tuple(missing))
    col = {s: j for j, s in enumerate(have)}
    holders = [have[0::3], have[1::3], have[2::3]]  # 3 simulated DNs
    parts = [rs.partial_sums(np.stack([shards[s] for s in g]),
                             rows[:, [col[s] for s in g]])
             for g in holders if g]
    fold = rs.xor_fold(parts)
    oracle = stripe_store.reconstruct_container(
        {i: s for i, s in enumerate(stripes) if i not in missing},
        manifest, want=missing)
    assert fold[0].tobytes() == oracle[0], \
        "coded partial-sum repair diverged from the full-gather oracle"
    # owner ingress: one (|missing|, stripe_len) fold from the remote
    # chain (2 of the 3 simulated holders are remote)
    coded_exchange.book_repair_wire(len(missing) * stripe_len,
                                    len(missing) * stripe_len)
    blob, enc = coded_exchange.pack(b"coded exchange negotiation " * 512)
    assert coded_exchange.unpack(
        blob, enc, 27 * 512) == b"coded exchange negotiation " * 512
    ec = metrics.registry("ec")
    ce = metrics.registry("coded_exchange")
    raw = ce.counter("pack_raw_bytes")
    with ec._lock:
        ratio = ec._gauges.get("repair_wire_ratio", 0.0)
    return {
        "repair_wire_ratio": round(float(ratio), 4),
        "repair_wire_bytes": ec.counter("repair_wire_bytes"),
        "repair_rebuilt_bytes": ec.counter("repair_rebuilt_bytes"),
        "coded_repairs": ec.counter("coded_repairs"),
        "coded_repair_fallbacks": ec.counter("coded_repair_fallbacks"),
        "packed_intermediates": ce.counter("packed_intermediates"),
        "pack_saved_frac": round(
            ce.counter("pack_saved_bytes") / raw, 4) if raw else 0.0,
    }


def _mirror_summary() -> dict:
    """Coded-mirror-plane stamp for the JSON line: a small in-process
    k-of-n exercise through server/mirror_plane.py's segment codec —
    encode one payload at k=2/m=1, drop a DATA segment (the case that
    forces an RS decode), reassemble, assert bit-identity — timed into
    the ``ack_us`` histogram so the quantiles are never empty, then the
    process-wide ``mirror`` registry counters (this exercise plus any
    product mirror activity in the run: hedges fired, parity bytes paid,
    reconciliations of partial replicas)."""
    import time as _time

    from hdrf_tpu.server import mirror_plane
    from hdrf_tpu.utils import metrics

    k, m = 2, 1
    rng = np.random.default_rng(17)
    payload = rng.integers(0, 256, size=(1 << 20) + 7,
                           dtype=np.uint8).tobytes()
    t0 = _time.perf_counter()
    segments, _seg_len = mirror_plane.encode_segments(payload, k, m)
    survivors = {i: s for i, s in enumerate(segments) if i != 0}
    assert mirror_plane.assemble_payload(survivors, k, m, len(payload)) \
        == payload, "coded mirror assembly diverged from the payload"
    reg = metrics.registry("mirror")
    reg.observe("ack_us", (_time.perf_counter() - t0) * 1e6)
    with reg._lock:
        ack = reg._histograms.get("ack_us")
        p50 = ack.quantile(0.50) if ack else 0.0
        p95 = ack.quantile(0.95) if ack else 0.0
    return {
        "ack_p50_us": round(float(p50), 1),
        "ack_p95_us": round(float(p95), 1),
        "hedges_fired": reg.counter("hedges_fired"),
        "parity_bytes": reg.counter("parity_bytes"),
        "reconciliations": reg.counter("reconciliations"),
    }


def _read_summary(tmp: str) -> dict:
    """Read-plane stamp for the JSON line: a small in-process exercise of
    the PRODUCT read path — dedup-commit a tiny two-block corpus (one
    block half-duplicating the other), seal it, then reconstruct every
    block ``READ_ROUNDS`` times through DedupScheme.reconstruct under a
    read timeline (utils/profiler.py read_timeline), so the same
    index_lookup / container_decode phases, decoded-container LRU, and
    read-amplification counters the DataNode serves /prom from are what
    this stamp reports.  Reads route through the chunk-granular serving
    engine (server/read_plane.py — decoded-chunk cache + grouped decode
    dispatch), exactly as a DataNode wires it.  ``HDRF_BENCH_READ_MOSTLY=1``
    raises the replay count AND interleaves fresh dedup commits between
    rounds (mixed read/write profile).  Keys: read_amplification (physical
    decoded / logical served for the exercised scheme), cache_hit_ratio
    (decoded-container LRU), read_p95_ms (read_wall_us histogram),
    tenant_count (utils/tenants.py — the bench reads as its own tenant),
    chunk_cache_hit_ratio (decoded-CHUNK cache, this run's probes),
    read_batches (grouped decode dispatches: coalesced batches + inline
    groups), containers_decoded_per_read (mean decode fan-out per plan —
    the read-amplification acceptance gauge)."""
    import time as _time

    from hdrf_tpu import native
    from hdrf_tpu.config import CdcConfig, ReductionConfig
    from hdrf_tpu.index.chunk_index import ChunkIndex
    from hdrf_tpu.ops.dispatch import gear_mask
    from hdrf_tpu.reduction import accounting
    from hdrf_tpu.reduction import scheme as schemes
    from hdrf_tpu.reduction.dedup import dedup_commit
    from hdrf_tpu.server import read_plane
    from hdrf_tpu.storage import container_store
    from hdrf_tpu.storage.container_store import ContainerStore
    from hdrf_tpu.utils import metrics, profiler, tenants

    d = os.path.join(tmp, "readpath")
    containers = ContainerStore(os.path.join(d, "containers"), codec="lz4")
    index = ChunkIndex(os.path.join(d, "index"))
    cdc = CdcConfig()
    mask = gear_mask(cdc)
    blocks = []
    b0 = _make_block(1, seed=900)
    blocks.append(b0.tobytes())
    b1 = b0.copy()
    b1[: b1.size // 2] = _make_block(1, seed=901)[: b1.size // 2]
    blocks.append(b1.tobytes())
    for bid, data in enumerate(blocks):
        buf = np.frombuffer(data, np.uint8)
        cuts = native.cdc_chunk(buf, mask, cdc.min_chunk, cdc.max_chunk)
        starts = np.concatenate([[0], cuts[:-1]]).astype(np.uint64)
        digs = native.sha256_batch(buf, starts,
                                   (cuts - starts).astype(np.uint64))
        dedup_commit(bid, data, cuts, digs, index, containers,
                     on_seal=index.seal_container)
    containers.flush_open(on_seal=index.seal_container)
    scheme = schemes.get("dedup_lz4")
    rp = read_plane.ReadPlane(containers, window_ms=0, backend="native")
    rp.attach_store(containers)
    ctx = schemes.ReductionContext(config=ReductionConfig(),
                                   containers=containers, index=index,
                                   read_plane=rp)
    rpm = metrics.registry("read_plane")
    base = {k: rpm.counter(k) for k in
            ("chunk_cache_hit", "chunk_cache_miss", "read_batches",
             "inline_decodes", "containers_fetched", "plans_served")}
    for rnd in range(READ_ROUNDS):
        if READ_MOSTLY and rnd % 4 == 3:
            # mixed read/write: a fresh half-duplicate block lands between
            # replay rounds, churning the open lane and the chunk cache
            nb = _make_block(1, seed=910 + rnd)
            nb[: nb.size // 2] = np.frombuffer(blocks[0],
                                               np.uint8)[: nb.size // 2]
            data = nb.tobytes()
            buf = np.frombuffer(data, np.uint8)
            cuts = native.cdc_chunk(buf, mask, cdc.min_chunk, cdc.max_chunk)
            starts = np.concatenate([[0], cuts[:-1]]).astype(np.uint64)
            digs = native.sha256_batch(buf, starts,
                                       (cuts - starts).astype(np.uint64))
            dedup_commit(len(blocks), data, cuts, digs, index, containers,
                         on_seal=index.seal_container)
            blocks.append(data)
        for bid, data in enumerate(blocks):
            t0 = _time.perf_counter()
            with profiler.read_timeline(bid, nbytes=len(data)):
                out = scheme.reconstruct(bid, b"", len(data), ctx)
            assert out == data, "read-path stamp diverged from the corpus"
            tenants.note_op("bench-reader", "read", len(data),
                            latency_s=_time.perf_counter() - t0)
    rp.close()
    index.close()
    d_ = {k: rpm.counter(k) - v for k, v in base.items()}
    probes = d_["chunk_cache_hit"] + d_["chunk_cache_miss"]
    amp = accounting.read_amplification_report().get(scheme.name, {})
    reg = metrics.registry("read_profiler")
    with reg._lock:
        h = reg._histograms.get("read_wall_us")
        p95 = h.quantile(0.95) if h else 0.0
    return {
        "read_amplification": round(amp.get("read_amplification", 0.0), 4),
        "cache_hit_ratio": round(container_store.cache_hit_ratio(), 4),
        "read_p95_ms": round(float(p95) / 1e3, 3),
        "tenant_count": tenants.tenant_count(),
        "chunk_cache_hit_ratio": round(
            d_["chunk_cache_hit"] / probes if probes else 0.0, 4),
        "read_batches": d_["read_batches"] + d_["inline_decodes"],
        "containers_decoded_per_read": round(
            d_["containers_fetched"] / d_["plans_served"]
            if d_["plans_served"] else 0.0, 4),
    }


def _scrub_summary(tmp: str) -> dict:
    """Integrity-scrub stamp for the JSON line: a small in-process
    exercise of the scrub plane's verification math (server/scrubber.py)
    — dedup-commit a tiny corpus, seal it, re-verify every live chunk
    digest against the chunk index (the exact oracle the DN scrubber
    samples), plant one aged ``.tmp`` orphan and census+reclaim it — then
    the process-wide ``scrub`` registry counters (this exercise plus any
    product scrub activity in the run).  Keys match the scrub prom
    family: bytes_verified, corrupt_total (labelled scrub_corrupt sum),
    garbage_bytes (last census), repairs_triggered."""
    import hashlib

    from hdrf_tpu import native
    from hdrf_tpu.config import CdcConfig
    from hdrf_tpu.index.chunk_index import ChunkIndex
    from hdrf_tpu.ops.dispatch import gear_mask
    from hdrf_tpu.reduction.dedup import dedup_commit
    from hdrf_tpu.server.scrubber import Scrubber
    from hdrf_tpu.storage.container_store import ContainerStore
    from hdrf_tpu.utils import metrics

    d = os.path.join(tmp, "scrubpath")
    containers = ContainerStore(os.path.join(d, "containers"), codec="lz4")
    index = ChunkIndex(os.path.join(d, "index"))
    cdc = CdcConfig()
    mask = gear_mask(cdc)
    data = _make_block(1, seed=950).tobytes()
    buf = np.frombuffer(data, np.uint8)
    cuts = native.cdc_chunk(buf, mask, cdc.min_chunk, cdc.max_chunk)
    starts = np.concatenate([[0], cuts[:-1]]).astype(np.uint64)
    digs = native.sha256_batch(buf, starts,
                               (cuts - starts).astype(np.uint64))
    dedup_commit(0, data, cuts, digs, index, containers,
                 on_seal=index.seal_container)
    containers.flush_open(on_seal=index.seal_container)
    reg = metrics.registry("scrub")
    verified = 0
    for cid in index.container_live_bytes():
        blob = containers.read_container(cid)
        for h, (off, ln) in index.live_chunks_in(cid).items():
            assert hashlib.sha256(blob[off:off + ln]).digest() == h, \
                "scrub stamp: live chunk digest diverged from the index"
            verified += ln
    reg.incr("scrub_bytes_verified", verified)
    # one aged tmp orphan through the census's reclaim math
    orphan = os.path.join(d, "containers", "999.sealed.tmp")
    with open(orphan, "wb") as f:
        f.write(b"\0" * 4096)
    garbage = os.path.getsize(orphan)
    os.unlink(orphan)
    reg.incr("scrub_tmp_reclaimed")
    index.close()
    return {
        "bytes_verified": reg.counter("scrub_bytes_verified"),
        "corrupt_total": Scrubber.corrupt_total(),
        "garbage_bytes": garbage,
        "repairs_triggered": reg.counter("scrub_repairs_triggered"),
        "tmp_reclaimed": reg.counter("scrub_tmp_reclaimed"),
    }


def _qos_summary() -> dict:
    """Overload-plane stamp for the JSON line: a small in-process exercise
    of the admission/shed/hedge machinery (utils/qos.py, utils/retry.py)
    under an injected clock so the numbers are deterministic.  A hog
    tenant burns 8x its burst and must shed with a retry-after hint; a
    light tenant must still admit; a FairQueue flooded by the hog must
    interleave the light tenant's items (ratio 1.0 = perfect round-robin,
    ~0 = FIFO starvation); one stalled primary + one fast hedge through
    ``hedged_quorum`` must land the hedge win.  Keys match the qos/ec
    prom families so the bench line cross-checks /prom."""
    from hdrf_tpu.utils import metrics, qos, retry

    now = [0.0]
    ctrl = qos.AdmissionController(rate_mb_s=1.0, burst_mb=1.0,
                                   clock=lambda: now[0])
    ctrl.admit("hog", "write")
    ctrl.charge("hog", "write", 8 << 20)        # 8x the burst: deficit
    sheds = 0
    for _ in range(4):
        try:
            ctrl.admit("hog", "write")
        except qos.ShedError:
            sheds += 1
    ctrl.admit("light", "write")                # light tenant unaffected

    class _It:  # FairQueue routes on .tenant
        __slots__ = ("tenant",)

        def __init__(self, tenant):
            self.tenant = tenant

    q = qos.FairQueue()
    n_light = 8
    for _ in range(64):
        q.put(_It("hog"))
    for _ in range(n_light):
        q.put(_It("light"))
    served_light = sum(1 for _ in range(2 * n_light)
                       if q.get_nowait().tenant == "light")

    ec_reg = metrics.registry("ec")

    def _stalled():
        time.sleep(0.2)
        return "slow"

    wins, _errs, _hedged = retry.hedged_quorum(
        [_stalled], [lambda: "fast"], k=1, hedge_after_s=0.01,
        on_hedge=lambda: ec_reg.incr("ec_hedges_fired"))
    for leg_i, _payload in wins:
        if leg_i >= 1:
            ec_reg.incr("ec_hedge_wins")
    return {
        "sheds": sheds,
        "shed_retry_after_p50_ms": round(ctrl.shed_retry_after_p50_ms(), 3),
        "tenant_fairness_ratio": round(served_light / n_light, 4),
        "ec_hedges_fired": ec_reg.counter("ec_hedges_fired"),
        "ec_hedge_wins": ec_reg.counter("ec_hedge_wins"),
    }


def _multichip_summary() -> dict:
    """Mesh-plane service-rate stamp for the JSON line: the `benchmarks
    multichip` sub-harness (1/2/4/8-device curve, native-oracle pinned,
    one-dispatch-per-step ledger check) run in a CHILD process on the
    8-virtual-device emulated mesh — the parent may hold the real chip,
    whose backend cannot re-initialize with a different device count
    in-process.  The child's single JSON line is lifted verbatim minus
    the op banner; any failure degrades to ``{"ok": False, ...}`` so a
    mesh regression can never take down the bench line itself."""
    import subprocess

    from hdrf_tpu.utils.cleanenv import clean_cpu_env

    smoke = os.environ.get("HDRF_BENCH_SMOKE") == "1"
    argv = [sys.executable, "-m", "hdrf_tpu.benchmarks", "multichip"]
    if smoke:
        argv += ["--blocks", "16", "--repeats", "1"]
    try:
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=600,
            env=clean_cpu_env(8), cwd=os.path.dirname(os.path.abspath(__file__)))
        line = proc.stdout.strip().splitlines()[-1]
        out = json.loads(line)
    except Exception as e:          # noqa: BLE001 — stamp must never raise
        return {"ok": False, "error": repr(e)[:200]}
    if proc.returncode != 0:
        return {"ok": False, "error": proc.stderr.strip()[-200:]}
    out.pop("op", None)
    out["ok"] = bool(out.get("oracle_ok") and out.get("one_dispatch_per_step"))
    return out


def _longhorizon_summary() -> dict:
    """Long-horizon churn stamp for the JSON line: the `benchmarks churn`
    sub-harness (delete/rewrite lifecycle over a MiniCluster; the
    storage_ratio / garbage / cache / read-p95 curves over time that
    ROADMAP item 1 calls the honest production number) run in a CHILD
    process on the clean CPU env — churn drives a whole MiniCluster and
    must not share the parent's possibly-TPU-held backend.  The child's
    single JSON line is folded into a flat first/last/slope stamp; any
    failure degrades to ``{"ok": False, ...}`` so a churn regression can
    never take down the bench line itself."""
    import subprocess

    from hdrf_tpu.utils.cleanenv import clean_cpu_env

    smoke = os.environ.get("HDRF_BENCH_SMOKE") == "1"
    argv = [sys.executable, "-m", "hdrf_tpu.benchmarks", "churn"]
    if smoke:
        argv += ["--rounds", "3", "--files", "3", "--kb", "8"]
    try:
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=600,
            env=clean_cpu_env(8), cwd=os.path.dirname(os.path.abspath(__file__)))
        line = proc.stdout.strip().splitlines()[-1]
        out = json.loads(line)
    except Exception as e:          # noqa: BLE001 — stamp must never raise
        return {"ok": False, "error": repr(e)[:200],
                "storage_ratio_slope": 0.0}
    if proc.returncode != 0:
        return {"ok": False, "error": proc.stderr.strip()[-200:],
                "storage_ratio_slope": 0.0}
    curves = out.get("curves", {})

    def _c(metric, field):
        return round(float(curves.get(metric, {}).get(field, 0.0)), 4)

    return {
        "rounds": out.get("rounds", 0),
        "samples": out.get("samples", 0),
        "storage_ratio_first": _c("storage_ratio", "first"),
        "storage_ratio_last": _c("storage_ratio", "last"),
        "storage_ratio_slope": _c("storage_ratio", "slope"),
        "garbage_bytes_last": _c("garbage_bytes", "last"),
        "chunk_cache_hit_ratio_last": _c("chunk_cache_hit_ratio", "last"),
        "read_p95_ms_slope": _c("read_p95_ms", "slope"),
        "regressions": out.get("regressions", []),
        "verdict": out.get("verdict", ""),
        # churn MUST show the ratio decaying: deletes leave dead chunks in
        # sealed containers, so a flat curve means the census lies
        "ok": bool(out.get("verdict") == "REGRESSED"
                   and "storage_ratio" in (out.get("regressions") or [])),
    }


def _nn_summary() -> dict:
    """Control-plane stamp for the JSON line: the ``benchmarks nn``
    metadata-storm harness (concurrent wire clients against a started
    NameNode — the load shape that populates the per-method RPC
    decomposition and the instrumented namesystem lock's books,
    hdrf_tpu/benchmarks.py bench_nn) run in a CHILD process on the clean
    CPU env — the storm boots its own NN and must not share the parent's
    possibly-TPU-held backend.  Folded to the contention-observatory keys
    (rpc_p99_ms, lock_saturation, lock_wait_p99_us, top_method) that
    ROADMAP item 2's observer-read/sharded-lock PR will read as its
    before/after baseline; any failure degrades to ``{"ok": False}`` so
    a storm regression can never take down the bench line itself."""
    import subprocess

    from hdrf_tpu.utils.cleanenv import clean_cpu_env

    smoke = os.environ.get("HDRF_BENCH_SMOKE") == "1"
    argv = [sys.executable, "-m", "hdrf_tpu.benchmarks", "nn"]
    argv += (["--ops", "80", "--clients", "4", "--meta-per-op", "2"]
             if smoke else ["--ops", "1500", "--clients", "8"])
    # second child: the ISSUE 20 observer A/B legs (small paired rounds —
    # the stamp wants the observer-plane keys, not a full soak)
    ab_argv = [sys.executable, "-m", "hdrf_tpu.benchmarks", "nn",
               "--observer-ab", "--ops", "40", "--clients", "2",
               "--meta-per-op", "2", "--rounds", "1"]
    try:
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=600,
            env=clean_cpu_env(8),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        line = proc.stdout.strip().splitlines()[-1]
        out = json.loads(line)
    except Exception as e:          # noqa: BLE001 — stamp must never raise
        return {"ok": False, "error": repr(e)[:200], "rpc_p99_ms": 0.0,
                "lock_saturation": 0.0, "lock_wait_p99_us": 0.0,
                "top_method": None, "observer_reads": 0,
                "observer_share": 0.0, "msync_p99_ms": 0.0,
                "observer_lag_txids": 0}
    if proc.returncode != 0:
        return {"ok": False, "error": proc.stderr.strip()[-200:],
                "rpc_p99_ms": 0.0, "lock_saturation": 0.0,
                "lock_wait_p99_us": 0.0, "top_method": None,
                "observer_reads": 0, "observer_share": 0.0,
                "msync_p99_ms": 0.0, "observer_lag_txids": 0}
    try:
        ab_proc = subprocess.run(
            ab_argv, capture_output=True, text=True, timeout=600,
            env=clean_cpu_env(8),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        ab = json.loads(ab_proc.stdout.strip().splitlines()[-1])
        ab_ok = ab_proc.returncode == 0 and ab.get("errors", 1) == 0
    except Exception:               # noqa: BLE001 — stamp must never raise
        ab, ab_ok = {}, False
    return {
        # the observatory's own health bar: every profiled RPC's service
        # time >= 95% attributed to named phases, and a clean storm
        "ok": bool(out.get("attributed_frac", 0.0) >= 0.95
                   and out.get("errors", 1) == 0 and ab_ok),
        "clients": out.get("clients", 0),
        "ops_per_s": out.get("ops_per_s", 0),
        "rpc_p99_ms": out.get("rpc_p99_ms", 0.0),
        "lock_saturation": out.get("lock_saturation", 0.0),
        "lock_wait_p99_us": out.get("lock_wait_p99_us", 0.0),
        "top_method": out.get("top_method"),
        "lock_share": out.get("lock_share", {}),
        "attributed_frac": out.get("attributed_frac", 0.0),
        # ISSUE 20 observer plane (from the paired A/B child)
        "observer_reads": ab.get("observer_reads", 0),
        "observer_share": ab.get("observer_share", 0.0),
        "msync_p99_ms": ab.get("msync_p99_ms", 0.0),
        "observer_lag_txids": ab.get("observer_lag_txids", 0),
        "observer_read_p99_ratio": ab.get("read_p99_ratio", 0.0),
        "active_read_lock_share_b": ab.get(
            "b", {}).get("active_read_lock_share", 0.0),
    }


def _phase_profile(t0: float, t1: float) -> dict:
    """Cross-thread overlap profile of [t0, t1] for the JSON line: wall
    partitioned into the profiler's exclusive classes (host/device busy,
    transport wait, idle — sums exactly to wall_s), per-phase exclusive
    seconds, the overlap-efficiency ratio (wait hidden under host work /
    total hideable wait — the 1-vCPU host's only lever, PERF_NOTES round
    4), and attributed_frac (share of wall inside any named phase)."""
    from hdrf_tpu.utils import profiler

    prof = profiler.window_profile(t0, t1)
    return {
        "wall_s": round(prof["wall_s"], 3),
        "classes": {k: round(v, 3) for k, v in prof["classes"].items()},
        "phases": {k: round(v, 3) for k, v in sorted(prof["phases"].items())},
        "overlap_efficiency": round(prof["overlap_efficiency"], 3),
        "attributed_frac": round(prof["attributed_frac"], 3),
    }


def _pipeline_summary(phase_profile: dict) -> dict:
    """Pipeline-depth stamp for the output line: the configured write
    pipeline depth, WAL group-commit batches this run (chunk_index
    registry), and the profile window's overlap efficiency."""
    from hdrf_tpu.config import ReductionConfig
    from hdrf_tpu.utils import metrics

    counters = metrics.registry("chunk_index").snapshot()["counters"]
    return {
        "depth": ReductionConfig().pipeline_depth,
        "group_commit_batches": int(counters.get("group_commit_batches", 0)),
        "overlap_efficiency": phase_profile["overlap_efficiency"],
    }


def main() -> None:
    from hdrf_tpu.config import CdcConfig
    from hdrf_tpu.ops.dispatch import resolve_backend
    from hdrf_tpu.utils import device_ledger, profiler

    led0 = device_ledger.stamp()   # dispatch-ledger baseline for the run
    cdc = CdcConfig()
    base = _make_block(BLOCK_MB, seed=42)
    cpu_blocks = [_salt(base[: CPU_MB << 20], 100 + i) for i in range(2)]
    _cpu_run([cpu_blocks[0]], cdc)  # page-in warmup
    # best of three: the single-thread baseline must reflect an uncontended
    # core, not whatever else the host was doing during one pass
    cpu_value = max(_cpu_run(cpu_blocks, cdc) for _ in range(3))

    # Full-path corpus: DISTINCT blocks (separate seeds).  Salted copies of
    # one block would cross-block-dedup ~8x and let the entropy stage see
    # almost nothing; distinct blocks with intra-block duplicate spans are
    # the honest, harder case.  The same corpus feeds both the CPU and TPU
    # full-path passes.
    e2e_hosts = [_make_block(BLOCK_MB, seed=500 + i) for i in range(E2E_BLOCKS)]

    tmp = tempfile.mkdtemp(prefix="hdrf_bench_")
    try:
        backend = resolve_backend("auto")
        if backend != "tpu":
            cpu_e2e, cpu_ratio, cpu_dr = 0.0, 1.0, 1.0
            p0 = profiler.mark()   # phase-profile window: the e2e passes
            for i in range(2):
                os.sync()  # settle writeback between ~0.5 GB passes
                v, rr, dr = _cpu_full(e2e_hosts, cdc, tmp, f"cpu{i}")
                if v > cpu_e2e:
                    cpu_e2e, cpu_ratio, cpu_dr = v, rr, dr
            phase_profile = _phase_profile(p0, profiler.mark())
            led = device_ledger.delta(led0)
            print(json.dumps({
                "metric": "block reduction pipeline throughput (CDC+SHA-256), "
                          "native CPU backend (no TPU attached)",
                "value": round(cpu_value, 2), "unit": "MB/s",
                "vs_baseline": 1.0,
                "e2e_value": round(cpu_e2e, 2), "e2e_vs_baseline": 1.0,
                "e2e_ratio_cpu": round(cpu_ratio, 3),
                "dedup_ratio": round(cpu_dr, 4),
                "slow_peer_count": _slow_peer_count(),
                "ledger": led,
                "cdc_fused": _cdc_fused_summary(),
                "cdc_adaptive": _cdc_adaptive_summary(),
                "stalls": led.get("stall_total", 0),
                "resilience": _resilience_summary(),
                "ec": _ec_summary(),
                "mirror": _mirror_summary(),
                "coded_exchange": _coded_exchange_summary(),
                "read": _read_summary(tmp),
                "scrub": _scrub_summary(tmp),
                "qos": _qos_summary(),
                "phase_profile": phase_profile,
                "pipeline": _pipeline_summary(phase_profile),
                "multichip": _multichip_summary(),
                "longhorizon": _longhorizon_summary(),
                "nn": _nn_summary(),
            }))
            return

        import jax

        from hdrf_tpu.ops.lz4_tpu import _S as LZ4_TILE
        from hdrf_tpu.ops.lz4_tpu import TpuLz4
        from hdrf_tpu.ops.resident import ResidentReducer

        r = ResidentReducer(cdc)
        stacked = np.stack([_salt(base, i) for i in range(N_BLOCKS)])
        dev = jax.device_put(stacked)
        np.asarray(dev[0, :16])                 # force upload complete
        step = N_BLOCKS // SUB_BATCHES
        parts = [dev[i * step: (i + 1) * step] for i in range(SUB_BATCHES)]

        def one_pass() -> list:
            # Software-pipelined sub-batches: while sub-batch A's candidate
            # (then digest) readback is awaited, the other sub-batches'
            # dispatches execute on device — awaited transfers are the only
            # non-overlapped cost.
            bjs = [r.submit_many(h) for h in parts]
            for bj in bjs:
                r.start_sha_many(bj)
            out = []
            for bj in bjs:
                out.extend(r.finish_many(bj))
            return out

        one_pass()                              # compile all batched shapes

        # best of five passes: the tunneled transport's dispatch latency
        # varies run to run (a whole RUN has measured 770-1200 MB/s for
        # identical device work); the best pass is closest to the
        # device-bound rate
        value = 0.0
        for _ in range(5):
            t0 = time.perf_counter()
            results = one_pass()
            dt = time.perf_counter() - t0
            assert all(int(cuts[-1]) == BLOCK_MB << 20
                       and digs.shape[0] == cuts.size
                       for cuts, digs in results)
            value = max(value, N_BLOCKS * (BLOCK_MB << 20) / dt / (1 << 20))

        # ------------------------------------------------ full path (e2e)
        lz4 = TpuLz4()

        SEAL_GROUP = 1  # containers per scan dispatch: every rollover
        # dispatches immediately.  Monotone win measured across 4 -> 2 ->
        # 1 (TPU e2e 66 -> 71 -> 79 MB/s, TeraGen 139 -> 156 -> 163):
        # the earlier the device starts, the more compute hides under the
        # commit phase, and the per-dispatch RTTs hide under commit work
        DEBUG = os.environ.get("HDRF_BENCH_DEBUG") == "1"

        def _dbg(tag, label, t0):
            if DEBUG:
                print(f"[{tag}] {label:20s} {time.perf_counter() - t0:7.3f}s",
                      file=sys.stderr)

        # Chunk-index summary of the most recent full pass (captured just
        # before the pass closes its index): the exact-dedup-ratio source
        # for the JSON line.
        idx_summary: dict = {}

        def full_pass(tag: str, images: dict | None, hosts: list,
                      dev_parts: list):
            """One timed full-path pass, software-pipelined across the
            DN's three resources: the DEVICE runs CDC+SHA then the sealed
            containers' LZ4 match scans (grouped: one dispatch + one
            packed readback per SEAL_GROUP containers — separate readbacks
            each cost a fixed transport round trip); the COMMIT worker
            (one thread — the deterministic-layout equivalent of the
            reference's storer thread, DataDeduplicator.java:652-845) runs
            dedup lookup + container append + index WAL commit per block;
            the MAIN thread drains digest readbacks and runs native LZ4
            emits.  ``images`` maps container id -> HBM-staged payload
            image padded to the common 32 MiB grid (built by the untimed
            pre-pass); None runs the pre-pass itself.  Scan groups
            dispatch when their containers ROLL (the on_roll hook) — the
            product schedule: a container's bytes exist in the worker's
            HBM the moment it rolls, not before, so dispatching earlier
            (e.g. all groups at pass start against the staged images)
            would measure a replay-only overlap the real write path
            cannot achieve on first-seen data."""
            payloads: list = []   # (cid, payload) in seal order
            pend: list = []       # containers awaiting a grouped dispatch
            groups: list = []     # (cids, payloads, submit_many result)

            def flush_pend():
                if not pend:
                    return
                arrs = [np.frombuffer(p, np.uint8) for _, p in pend]
                sub = lz4.submit_many(
                    arrs, device_images=[images[c] for c, _ in pend])
                groups.append(([c for c, _ in pend],
                               [p for _, p in pend], sub))
                pend.clear()

            def on_roll(cid, payload):
                # fires in the commit worker at rollover: the scan group
                # dispatches mid-pass and overlaps the later commits.
                # The image-staging pre-pass (images None) only collects
                # payloads — scans wait for the staged common-size images,
                # so exactly the grouped shapes compile, once.
                payloads.append((cid, payload))
                if images is not None:
                    pend.append((cid, payload))
                    if len(pend) >= SEAL_GROUP:
                        flush_pend()

            from hdrf_tpu.reduction.dedup import CommitPipeline

            index, containers = _fresh_stores(tmp, tag, on_roll=on_roll)
            on_seal = _chain_seal(index, containers)
            t0 = time.perf_counter()
            bjs = [r.submit_many(h) for h in dev_parts]
            for bj in bjs:
                r.start_sha_many(bj)
            _dbg(tag, "cdc_sha_dispatch", t0)
            pipe = CommitPipeline(index, containers, batch=4,
                                  on_seal=on_seal)
            t0 = time.perf_counter()
            futs = []
            bid = 0
            for bj in bjs:
                for cuts, digs in r.finish_many(bj):
                    futs.append(pipe.submit(bid, hosts[bid], cuts, digs))
                    bid += 1
            _dbg(tag, "digest_readbacks", t0)
            t0 = time.perf_counter()

            # Drain commits and scan groups INTERLEAVED: group finishes are
            # mostly transport waits (readbacks were started at dispatch),
            # so taking them while commit futures are still pending lets
            # the commit worker fill the core under them instead of the
            # two phases running back-to-back.  Readbacks stay sequential
            # on this one thread (concurrent D2H degrades the tunneled
            # transport, PERF_NOTES.md).
            state = {"stored": 0, "ndone": 0}

            def _finish_group(grp):
                t1 = time.perf_counter()
                cids, pls, sub = grp
                comps = lz4.finish_many(sub)
                for cid, payload, comp in zip(cids, pls, comps):
                    out = comp if len(comp) < len(payload) else payload
                    with open(os.path.join(tmp, tag, f"sealed.{cid}"),
                              "wb") as f:
                        f.write(out)
                    state["stored"] += len(out)
                _dbg(tag, "  group_finish", t1)

            for f in futs:
                while not f.done() and state["ndone"] < len(groups):
                    _finish_group(groups[state["ndone"]])
                    state["ndone"] += 1
                f.result()
            pipe.close()
            containers.flush_open(on_seal=on_seal)
            flush_pend()
            _dbg(tag, "commit_drain", t0)
            t0 = time.perf_counter()
            while state["ndone"] < len(groups):
                _finish_group(groups[state["ndone"]])
                state["ndone"] += 1
            _dbg(tag, "seal_drain", t0)
            idx_summary.clear()
            idx_summary.update(index.stats())
            index.close()
            return payloads, state["stored"]

        def make_tpu(hosts: list, label: str):
            """Warm the TPU full path (stage images + compile grouped
            shapes + settle jit hints + settle the adaptive flood/bypass
            state); returns (tpu_pass, cleanup)."""
            # Fresh adaptive state per corpus, settled by the warm passes
            # and then CARRIED across the timed passes — the DataNode's
            # steady state on a homogeneous ingest stream (resetting per
            # pass forced a full re-probe of every container each pass,
            # ~1 s/pass of pure re-learning on the TeraGen corpus).
            with lz4._lock:
                lz4._flood_streak = 0
                lz4._bypass_left = 0
            dev = jax.device_put(np.stack(hosts))
            np.asarray(dev[0, :16])
            # 4 sub-batches measured best (2 -> 4 -> 8 parts: TPU e2e
            # 79 -> 84 -> 68 MB/s, TeraGen 163 -> 231 -> 201): finer
            # parts start the commit worker earlier (first digests after
            # 2 blocks), but per-block dispatches tip into RTT domination
            step = max(len(hosts) // 4, 1)
            dev_parts = [dev[i:i + step]
                         for i in range(0, len(hosts), step)]

            # Pre-pass: compile, learn record-slice shapes, and stage
            # container payload images in HBM (identical across passes —
            # fresh stores + deterministic append order — asserted below).
            payloads0, _ = full_pass(f"{label}_warm", None, hosts, dev_parts)

            # Stage every image at the COMMON 32 MiB grid so groups batch
            # regardless of exact payload size (pad-region records are
            # masked by the emit's MFLIMIT cut; zeros sort in equal time).
            common = max(1 << 25,
                         max(-(-len(p) // LZ4_TILE) * LZ4_TILE
                             for _, p in payloads0))

            def _pad_img(b: bytes) -> np.ndarray:
                a = np.frombuffer(b, np.uint8)
                return np.concatenate([a,
                                       np.zeros(common - a.size, np.uint8)])

            images = {cid: jax.device_put(_pad_img(payload))
                      for cid, payload in payloads0}
            sig0 = [(cid, hashlib.sha256(p).digest())
                    for cid, p in payloads0]
            # compile grouped-scan shapes, then recompile at the LEARNED
            # hints — they only settle during the first warm's finish phase
            full_pass(f"{label}_warm2", images, hosts, dev_parts)
            full_pass(f"{label}_warm3", images, hosts, dev_parts)
            logical = len(hosts) * (BLOCK_MB << 20)

            def tpu_pass(i: int):
                t0 = time.perf_counter()
                payloads, stored = full_pass(f"{label}{i}", images, hosts,
                                             dev_parts)
                dt = time.perf_counter() - t0
                sig = [(cid, hashlib.sha256(p).digest())
                       for cid, p in payloads]
                assert sig == sig0, "timed pass diverged from staged images"
                return logical / dt / (1 << 20), logical / max(stored, 1)

            def cleanup():
                for img in images.values():
                    img.delete()

            return tpu_pass, cleanup

        def paired(hosts: list, label: str, rounds: int):
            """Disk-weather-proof measurement: each round runs ONE CPU pass
            and ONE TPU pass back-to-back on the same disk state (sync
            fence before each leg), alternating leg order between rounds so
            neither path systematically inherits the other's writeback
            debt.  The reported speedup is the MEDIAN of the per-round
            paired ratios — a single pass hitting dirty-page throttling
            skews one round, not the verdict (the r03 capture measured the
            same build anywhere from 0.9x to 1.6x depending on which pass
            drew the bad disk weather)."""
            import statistics

            tpu_pass, cleanup = make_tpu(hosts, label)
            _cpu_full(hosts[:1], cdc, tmp, f"{label}_cpuwarm")  # page-in
            cpu_rates, tpu_rates, ratios = [], [], []
            tpu_ratio = cpu_red = 1.0
            for i in range(rounds):
                legs = ["cpu", "tpu"] if i % 2 == 0 else ["tpu", "cpu"]
                for leg in legs:
                    os.sync()  # settle writeback debt before each leg
                    if leg == "cpu":
                        v, cpu_red, _dr = _cpu_full(hosts, cdc, tmp,
                                                    f"{label}_cpu{i}")
                        cpu_rates.append(v)
                    else:
                        from hdrf_tpu.utils import device_ledger
                        leg0 = device_ledger.stamp()
                        v, tpu_ratio = tpu_pass(i)
                        leg_led = device_ledger.delta(leg0)
                        tpu_rates.append(v)
                ratios.append(tpu_rates[-1] / cpu_rates[-1])
                if DEBUG:
                    print(f"[{label}] round{i} cpu={cpu_rates[-1]:.1f} "
                          f"tpu={tpu_rates[-1]:.1f} ratio={ratios[-1]:.3f} "
                          f"ledger={leg_led}",
                          file=sys.stderr)
            cleanup()
            return {"tpu": statistics.median(tpu_rates),
                    "cpu": statistics.median(cpu_rates),
                    "paired": statistics.median(ratios),
                    "red_tpu": tpu_ratio, "red_cpu": cpu_red}

        # 5 rounds: a single catastrophic leg (the VM's write-burst
        # throttling stalls whichever pass draws it by ~35 s, observed on
        # the first post-warm TPU pass twice) must stay below the median's
        # breakdown point.
        p0 = profiler.mark()   # phase-profile window: the paired e2e rounds
        e2e = paired(e2e_hosts, "tpu", rounds=5)
        phase_profile = _phase_profile(p0, profiler.mark())

        # TeraGen-row corpus: the north-star benchmark's own data
        # (BASELINE.json "TeraGen 100 GB, equal ratio").
        tg_hosts = _teragen_blocks(TG_BLOCKS, BLOCK_MB)
        tg = paired(tg_hosts, "tg", rounds=5)

        led = device_ledger.delta(led0)
        print(json.dumps({
            "metric": "block reduction service rate (CDC+SHA-256), "
                      f"HBM-resident {BLOCK_MB} MiB blocks, overlapped "
                      f"x{N_BLOCKS}; e2e_* = full dedup_lz4 write path "
                      "(+dedup lookup, index WAL commit, container store, "
                      "TPU LZ4 container seal), PAIRED A/B vs the CPU "
                      "scheme (median of per-round interleaved ratios, "
                      "sync-fenced); tg_* = same on TeraGen rows",
            "value": round(value, 2),
            "unit": "MB/s",
            "vs_baseline": round(value / cpu_value, 3),
            "e2e_value": round(e2e["tpu"], 2),
            "e2e_cpu_value": round(e2e["cpu"], 2),
            "e2e_vs_baseline": round(e2e["paired"], 3),
            "e2e_ratio_tpu": round(e2e["red_tpu"], 3),
            "e2e_ratio_cpu": round(e2e["red_cpu"], 3),
            "tg_value": round(tg["tpu"], 2),
            "tg_cpu_value": round(tg["cpu"], 2),
            "tg_vs_baseline": round(tg["paired"], 3),
            "tg_ratio_tpu": round(tg["red_tpu"], 3),
            "tg_ratio_cpu": round(tg["red_cpu"], 3),
            "dedup_ratio": round(
                idx_summary["logical_bytes"]
                / max(idx_summary["unique_chunk_bytes"], 1), 4)
                if idx_summary else 1.0,
            "slow_peer_count": _slow_peer_count(),
            "ledger": led,
            "cdc_fused": _cdc_fused_summary(),
            "cdc_adaptive": _cdc_adaptive_summary(),
            "stalls": led.get("stall_total", 0),
            "resilience": _resilience_summary(),
            "ec": _ec_summary(),
            "mirror": _mirror_summary(),
            "coded_exchange": _coded_exchange_summary(),
            "read": _read_summary(tmp),
            "scrub": _scrub_summary(tmp),
            "qos": _qos_summary(),
            "phase_profile": phase_profile,
            "pipeline": _pipeline_summary(phase_profile),
            "multichip": _multichip_summary(),
            "longhorizon": _longhorizon_summary(),
            "nn": _nn_summary(),
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
