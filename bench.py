#!/usr/bin/env python
"""Headline benchmark: DataNode write-path reduction throughput.

Measures the device-resident block-reduction pipeline (ops/resident.py —
Gear CDC chunking + on-device chunk gather + lane-parallel SHA-256
fingerprinting, the hot path of DedupScheme.reduce, re-expressing the
reference's DataDeduplicator.java:264-307 chunk scan + utilities.java:98-137
JNI hashing) against the single-thread native C++ CPU baseline (the
reference's execution model).

Metric: sustained service rate over HBM-resident 64 MiB blocks with the
overlapped submit/finish pattern — the TPU worker's steady-state ingest rate
in the co-located deployment (BASELINE.json north star), where block bytes
arrive in HBM via the DataNode's streaming path.  The dev-environment tunnel
tops out at ~25 MB/s H2D (PERF_NOTES.md), which would measure the WAN link,
not the framework; results still include every dispatch, readback, and host
control-plane cost.

Prints ONE JSON line:
  {"metric": ..., "value": <TPU MB/s>, "unit": "MB/s", "vs_baseline": <ratio>}

vs_baseline = TPU rate / native-CPU rate on identical inputs and chunking
parameters (north star: >= 4x).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BLOCK_MB = 64
N_BLOCKS = 16
SUB_BATCHES = 4
CPU_MB = 32


def _make_block(mb: int, seed: int) -> np.ndarray:
    """Realistic-entropy block: compressible text-like spans + binary spans +
    planted duplicate regions (so CDC/dedup has real work, not pure noise)."""
    rng = np.random.default_rng(seed)
    n = mb << 20
    a = rng.integers(0, 256, size=n, dtype=np.uint8)
    a[: n // 4] = rng.integers(97, 123, size=n // 4, dtype=np.uint8)
    span = min(8 << 20, n // 4)
    a[n // 2 : n // 2 + span] = a[:span]
    return a


def _salt(block: np.ndarray, i: int) -> np.ndarray:
    b = block.copy()
    b[:4096] ^= np.uint8((i * 37 + 1) % 251)
    return b


def _cpu_run(blocks: list[np.ndarray], cdc) -> float:
    from hdrf_tpu import native
    from hdrf_tpu.ops.dispatch import gear_mask

    mask = gear_mask(cdc)
    t0 = time.perf_counter()
    total = 0
    for buf in blocks:
        cuts = native.cdc_chunk(buf, mask, cdc.min_chunk, cdc.max_chunk)
        starts = np.concatenate([[0], cuts[:-1]]).astype(np.uint64)
        native.sha256_batch(buf, starts, (cuts - starts).astype(np.uint64))
        total += buf.size
    return total / (time.perf_counter() - t0) / (1 << 20)


def main() -> None:
    from hdrf_tpu.config import CdcConfig
    from hdrf_tpu.ops.dispatch import resolve_backend

    cdc = CdcConfig()
    base = _make_block(BLOCK_MB, seed=42)
    cpu_blocks = [_salt(base[: CPU_MB << 20], 100 + i) for i in range(2)]
    _cpu_run([cpu_blocks[0]], cdc)  # page-in warmup
    # best of three: the single-thread baseline must reflect an uncontended
    # core, not whatever else the host was doing during one pass
    cpu_value = max(_cpu_run(cpu_blocks, cdc) for _ in range(3))

    backend = resolve_backend("auto")
    if backend != "tpu":
        print(json.dumps({
            "metric": "block reduction pipeline throughput (CDC+SHA-256), "
                      "native CPU backend (no TPU attached)",
            "value": round(cpu_value, 2), "unit": "MB/s", "vs_baseline": 1.0,
        }))
        return

    import jax

    from hdrf_tpu.ops.resident import ResidentReducer

    r = ResidentReducer(cdc)
    stacked = np.stack([_salt(base, i) for i in range(N_BLOCKS)])
    dev = jax.device_put(stacked)
    np.asarray(dev[0, :16])                 # force upload complete
    step = N_BLOCKS // SUB_BATCHES
    parts = [dev[i * step: (i + 1) * step] for i in range(SUB_BATCHES)]

    def one_pass() -> list:
        # Software-pipelined sub-batches: while sub-batch A's candidate
        # (then digest) readback is awaited, the other sub-batches'
        # dispatches execute on device — awaited transfers are the only
        # non-overlapped cost.
        bjs = [r.submit_many(h) for h in parts]
        for bj in bjs:
            r.start_sha_many(bj)
        out = []
        for bj in bjs:
            out.extend(r.finish_many(bj))
        return out

    one_pass()                              # compile all batched shapes

    # best of three passes: the tunneled transport's dispatch latency varies
    # run to run; the better pass is closer to the device-bound rate
    value = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        results = one_pass()
        dt = time.perf_counter() - t0
        assert all(int(cuts[-1]) == BLOCK_MB << 20
                   and digs.shape[0] == cuts.size
                   for cuts, digs in results)
        value = max(value, N_BLOCKS * (BLOCK_MB << 20) / dt / (1 << 20))

    print(json.dumps({
        "metric": "block reduction service rate (CDC+SHA-256), HBM-resident "
                  f"{BLOCK_MB} MiB blocks, overlapped x{N_BLOCKS}",
        "value": round(value, 2),
        "unit": "MB/s",
        "vs_baseline": round(value / cpu_value, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
