"""Per-tenant SLO / read-plane report over flight-recorder time series.

The read-side sibling of tools/gap_report.py (gap_report.py:1-24): where
that tool decomposes ONE run's write wall-clock, this one reads the
over-time story — the bounded gauge ring each daemon's flight recorder
keeps (utils/flight_recorder.py:1-40, served as ``/timeseries`` by
server/status_http.py:84-87 and the gateway) — and answers the operator
questions the reference leaves to external TSDBs: is read p95 regressing,
is the decoded-container cache decaying, is one tenant's load moving the
cluster (DataNodeMetrics.java:553-560 keeps windowed means; nothing in the
reference keeps the curve or flags the drift).

For every numeric gauge in the series it compares a BASELINE window (the
first ``baseline_frac`` of samples) against the CURRENT window (the last
``baseline_frac``) and flags regressions direction-aware: latency/backlog
gauges regress UP, ratio/hit-rate gauges regress DOWN, unflagged gauges
are reported but never flagged.

Sources, in order of preference:

- ``--input FILE``: a ``/timeseries`` capture (``{"samples": [...]}``),
  bench.py's single JSON output line (its ``read`` block becomes a
  one-sample series), or a bare JSON list of samples;
- ``--input DIR``: a flight-archive directory of JSONL segments
  (utils/flight_archive.py:1-40), replayed oldest-first with torn tails
  dropped — the restart-surviving long-horizon source;
- default: an in-process read-mostly MiniCluster smoke — write a tiny
  corpus once, read it repeatedly under two tenant identities, sampling
  the DN flight recorder between rounds
  (``python -m hdrf_tpu.tools.slo_report``).

``--trend`` switches from window comparison to the long-horizon fit:
per-metric least-squares slope + single-changepoint detection over the
whole series, same direction tables and jitter floor.  ``guard()`` is
the programmatic hook the DataNode's adaptive-chunking tick calls after
each retune window (server/datanode.py _cdc_tick) to decide whether the
retune regressed its blast-radius gauges and must roll back.
"""

from __future__ import annotations

import argparse
import json
import sys

SMOKE_BLOCKS = 3
SMOKE_BLOCK_KB = 256
SMOKE_ROUNDS = 4

# Direction a drift must move to count as a regression.  Everything else
# is informational: flagging unknown gauges both ways would page on any
# load change.
REGRESS_UP = ("read_p95_ms", "write_p95_ms", "stalls", "breakers_open",
              "breakers_half_open", "storage_ratio", "under_replicated",
              "pending_replication", "pending_recovery", "safemode",
              "read_amplification",
              # integrity drift (ISSUE 12): garbage growth and scrub/fsck
              # corruption counts only ever regress upward
              "garbage_bytes", "scrub_corrupt_total", "fsck_violations",
              # overload plane (ISSUE 14): a shed-rate climb is the QoS
              # plane absorbing pressure — flag it before clients notice
              "sheds_total",
              # metadata plane (ISSUE 17): rolling NN RPC tail latency
              "nn_rpc_p99_ms",
              # contention observatory (ISSUE 18): namesystem-lock
              # saturation and rolling acquire-wait tail — the leading
              # indicators of a lock convoy, both one-directional
              "nn_lock_saturation", "nn_lock_wait_p99_us",
              "observer_lag_s")
REGRESS_DOWN = ("container_cache_hit_ratio", "cache_hit_ratio",
                "dedup_ratio", "datanodes_live")
# Relative drift below this never flags (jitter floor), and a baseline of
# exactly 0 only flags on a nonzero current value.
DRIFT_FRAC = 0.25


def run_smoke(rounds: int = SMOKE_ROUNDS) -> list[dict]:
    """Read-mostly MiniCluster smoke: one write pass, ``rounds`` read
    passes under two tenant identities, one deterministic flight-recorder
    sample per round (sample_once, not the wall-clock sampler thread)."""
    import random

    from hdrf_tpu.testing.minicluster import MiniCluster
    from hdrf_tpu.utils import profiler, tenants

    profiler.reset()
    tenants.TRACKER.reset()
    rng = random.Random(0x510)
    payloads = [bytes(rng.getrandbits(8) for _ in range(SMOKE_BLOCK_KB << 10))
                for _ in range(SMOKE_BLOCKS)]
    samples: list[dict] = []
    with MiniCluster(n_datanodes=1, replication=1) as mc:
        with mc.client("slo-writer") as c:
            for i, p in enumerate(payloads):
                c.write(f"/slo/blk{i}", p, scheme="dedup")
        dn = mc.datanodes[0]
        for r in range(rounds):
            # tenant-a reads everything each round; tenant-b only half —
            # the per-tenant counters must keep them apart
            with mc.client("tenant-a") as a, mc.client("tenant-b") as b:
                for i in range(SMOKE_BLOCKS):
                    assert a.read(f"/slo/blk{i}") == payloads[i]
                    if i % 2 == 0:
                        b.read(f"/slo/blk{i}")
            dn.flight.sample_once()
            samples.append(dn.flight.snapshot()["samples"][-1])
    return samples


def _windows(vals: list[float],
             baseline_frac: float) -> tuple[list[float], list[float]]:
    n = len(vals)
    w = max(1, int(n * baseline_frac))
    return vals[:w], vals[-w:]


def aggregate(samples: list[dict],
              baseline_frac: float = 0.25) -> dict:
    """Fold a gauge series into per-gauge baseline/current rows with
    direction-aware regression flags.  Deterministic: rows sort by gauge
    name, windows are positional."""
    series: dict[str, list[float]] = {}
    for s in samples:
        for k, v in s.items():
            if k in ("t", "mono") or not isinstance(v, (int, float)):
                continue
            series.setdefault(k, []).append(float(v))
    rows = []
    regressions = []
    for name in sorted(series):
        vals = series[name]
        base_w, cur_w = _windows(vals, baseline_frac)
        base = sum(base_w) / len(base_w)
        cur = sum(cur_w) / len(cur_w)
        delta = cur - base
        rel = (delta / abs(base)) if base else (1.0 if delta else 0.0)
        direction = ("up" if name in REGRESS_UP
                     else "down" if name in REGRESS_DOWN else "none")
        regressed = bool(
            (direction == "up" and delta > 0 and rel > DRIFT_FRAC)
            or (direction == "down" and delta < 0 and -rel > DRIFT_FRAC))
        row = {"gauge": name, "baseline": base, "current": cur,
               "min": min(vals), "max": max(vals), "last": vals[-1],
               "rel_change": rel, "direction": direction,
               "regressed": regressed}
        rows.append(row)
        if regressed:
            regressions.append(name)
    return {"samples": len(samples), "baseline_frac": baseline_frac,
            "gauges": rows, "regressions": regressions,
            "verdict": "REGRESSED" if regressions else "OK"}


def format_table(agg: dict) -> str:
    """Deterministic text rendering (golden-tested)."""
    out = [f"slo report: {agg['samples']} samples, baseline window = "
           f"first/last {agg['baseline_frac'] * 100.0:.0f}%",
           f"verdict: {agg['verdict']}"
           + (f" ({', '.join(agg['regressions'])})"
              if agg["regressions"] else ""),
           "",
           f"{'gauge':<28} {'baseline':>10} {'current':>10} "
           f"{'drift':>8} {'flag':>5}"]
    for r in agg["gauges"]:
        flag = "REGR" if r["regressed"] else "-"
        out.append(f"{r['gauge']:<28} {r['baseline']:>10.3f} "
                   f"{r['current']:>10.3f} {r['rel_change'] * 100.0:>7.1f}% "
                   f"{flag:>5}")
    return "\n".join(out)


def slope(vals: list[float]) -> float:
    """Least-squares slope of a series over its sample index (per-sample
    units) — the long-horizon fit bench_churn and trend mode report."""
    n = len(vals)
    if n < 2:
        return 0.0
    xm = (n - 1) / 2.0
    ym = sum(vals) / n
    num = sum((i - xm) * (v - ym) for i, v in enumerate(vals))
    den = sum((i - xm) ** 2 for i in range(n))
    return num / den if den else 0.0


def _sse(vals: list[float]) -> float:
    if not vals:
        return 0.0
    m = sum(vals) / len(vals)
    return sum((v - m) ** 2 for v in vals)


def changepoint(vals: list[float]) -> dict | None:
    """Single-changepoint detection: the split index minimizing the summed
    squared error of a two-segment piecewise-constant fit (the simplest
    offline CUSUM-family estimator — deterministic, O(n^2), fine for
    flight-ring-sized series).  Returns ``{"index", "before", "after",
    "gain"}`` or None when the series is too short (< 4 samples)."""
    n = len(vals)
    if n < 4:
        return None
    total = _sse(vals)
    best_k, best_sse = None, total
    for k in range(1, n):
        s = _sse(vals[:k]) + _sse(vals[k:])
        if s < best_sse:
            best_k, best_sse = k, s
    if best_k is None:
        return None
    before = sum(vals[:best_k]) / best_k
    after = sum(vals[best_k:]) / (n - best_k)
    return {"index": best_k, "before": before, "after": after,
            "gain": total - best_sse}


def trend(samples: list[dict], jitter_frac: float = DRIFT_FRAC) -> dict:
    """Long-horizon trend report over an archived series: per-metric
    least-squares slope plus changepoint detection, regressions flagged
    direction-aware (the REGRESS_UP/REGRESS_DOWN tables) once the fitted
    total drift — or the changepoint's mean shift — clears the same 25%
    jitter floor ``aggregate`` uses.  A flat series never flags; an
    injected step or ramp deterministically does."""
    series: dict[str, list[float]] = {}
    for s in samples:
        for k, v in s.items():
            if k in ("t", "mono") or not isinstance(v, (int, float)):
                continue
            series.setdefault(k, []).append(float(v))
    rows = []
    regressions = []
    for name in sorted(series):
        vals = series[name]
        sl = slope(vals)
        total_drift = sl * (len(vals) - 1)
        base_w, _ = _windows(vals, DRIFT_FRAC)
        base = sum(base_w) / len(base_w)
        rel = ((total_drift / abs(base)) if base
               else (1.0 if total_drift else 0.0))
        cp = changepoint(vals)
        cp_rel = 0.0
        if cp is not None:
            shift = cp["after"] - cp["before"]
            cp_rel = ((shift / abs(cp["before"])) if cp["before"]
                      else (1.0 if shift else 0.0))
        direction = ("up" if name in REGRESS_UP
                     else "down" if name in REGRESS_DOWN else "none")
        regressed = bool(
            (direction == "up"
             and max(rel, cp_rel) > jitter_frac)
            or (direction == "down"
                and min(rel, cp_rel) < -jitter_frac))
        row = {"metric": name, "first": vals[0], "last": vals[-1],
               "slope": sl, "total_drift": total_drift,
               "rel_drift": rel, "changepoint": cp,
               "changepoint_rel": cp_rel, "direction": direction,
               "regressed": regressed}
        rows.append(row)
        if regressed:
            regressions.append(name)
    return {"samples": len(samples), "jitter_frac": jitter_frac,
            "metrics": rows, "regressions": regressions,
            "verdict": "REGRESSED" if regressions else "OK"}


def format_trend_table(tr: dict) -> str:
    """Deterministic text rendering of a trend report (golden-tested)."""
    out = [f"slo trend: {tr['samples']} samples, jitter floor = "
           f"{tr['jitter_frac'] * 100.0:.0f}%",
           f"verdict: {tr['verdict']}"
           + (f" ({', '.join(tr['regressions'])})"
              if tr["regressions"] else ""),
           "",
           f"{'metric':<28} {'first':>10} {'last':>10} "
           f"{'slope':>10} {'cp':>4} {'flag':>5}"]
    for r in tr["metrics"]:
        flag = "REGR" if r["regressed"] else "-"
        cp = str(r["changepoint"]["index"]) if r["changepoint"] else "-"
        out.append(f"{r['metric']:<28} {r['first']:>10.3f} "
                   f"{r['last']:>10.3f} {r['slope']:>10.4f} {cp:>4} "
                   f"{flag:>5}")
    return "\n".join(out)


def guard(baseline_samples: list[dict], current_samples: list[dict],
          gauges: tuple | None = None,
          jitter_frac: float = DRIFT_FRAC) -> dict:
    """Retune regression guard (ROADMAP item 5; called from the DN's
    _cdc_tick after each retune window): compare the pre-change window's
    gauge means against the post-change window's, direction-aware with
    the same jitter floor — ``regressed`` means the change made a flagged
    gauge measurably worse and should be rolled back.  ``gauges`` narrows
    the comparison to the metrics the change can plausibly move (the
    caller's blast radius), so unrelated cluster noise cannot veto it."""
    def _means(samples):
        acc: dict[str, list[float]] = {}
        for s in samples:
            for k, v in s.items():
                if k in ("t", "mono") or not isinstance(v, (int, float)):
                    continue
                if gauges is not None and k not in gauges:
                    continue
                acc.setdefault(k, []).append(float(v))
        return {k: sum(v) / len(v) for k, v in acc.items()}

    base = _means(baseline_samples)
    cur = _means(current_samples)
    rows = []
    regressed_any = False
    for name in sorted(set(base) & set(cur)):
        b, c = base[name], cur[name]
        delta = c - b
        rel = (delta / abs(b)) if b else (1.0 if delta else 0.0)
        direction = ("up" if name in REGRESS_UP
                     else "down" if name in REGRESS_DOWN else "none")
        regressed = bool(
            (direction == "up" and delta > 0 and rel > jitter_frac)
            or (direction == "down" and delta < 0 and -rel > jitter_frac))
        rows.append({"metric": name, "baseline": b, "current": c,
                     "rel_change": rel, "direction": direction,
                     "regressed": regressed})
        regressed_any = regressed_any or regressed
    return {"regressed": regressed_any, "rows": rows}


def _load_samples(doc) -> list[dict]:
    """Accept the three documented input shapes (mirrors gap_report.py's
    --input leniency, gap_report.py:138-147): a /timeseries capture, the
    bench.py JSON line (its ``read`` block as a one-sample series), or a
    bare sample list."""
    if isinstance(doc, list):
        return doc
    if isinstance(doc, dict):
        if isinstance(doc.get("samples"), list):
            return doc["samples"]
        if isinstance(doc.get("read"), dict):
            return [doc["read"]]
        return [doc]
    raise ValueError("unrecognized slo_report input shape")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="hdrf_tpu.tools.slo_report",
        description="Read-plane / per-tenant SLO drift report over "
                    "flight-recorder time series")
    p.add_argument("--input", help="JSON file (a /timeseries capture, "
                   "bench JSON line, or bare sample list) OR a flight-"
                   "archive directory of JSONL segments, replayed torn-"
                   "tail-tolerantly (default: run a read-mostly "
                   "MiniCluster smoke)")
    p.add_argument("--rounds", type=int, default=SMOKE_ROUNDS,
                   help="smoke-mode read rounds")
    p.add_argument("--baseline-frac", type=float, default=0.25,
                   help="fraction of samples in each comparison window")
    p.add_argument("--trend", action="store_true",
                   help="long-horizon mode: per-metric slope fit + "
                        "changepoint detection instead of the window "
                        "comparison")
    p.add_argument("--json", action="store_true",
                   help="emit the aggregate as JSON instead of the table")
    args = p.parse_args(argv)
    if args.input:
        import os

        if os.path.isdir(args.input):
            from hdrf_tpu.utils import flight_archive

            samples = flight_archive.replay_dir(args.input)
        else:
            with open(args.input) as f:
                samples = _load_samples(json.load(f))
    else:
        samples = run_smoke(rounds=args.rounds)
    if args.trend:
        tr = trend(samples)
        print(json.dumps(tr) if args.json else format_trend_table(tr))
        return 0
    agg = aggregate(samples, baseline_frac=args.baseline_frac)
    if args.json:
        print(json.dumps(agg))
    else:
        print(format_table(agg))
    return 0


if __name__ == "__main__":
    sys.exit(main())
