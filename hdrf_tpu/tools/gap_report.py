"""Gap-attribution report over write-path profiler timelines.

The observability artifact ISSUE 6 / ROADMAP item 1 are judged against: the
reference ships nothing comparable (its closest relative is the rate summary
``dfsadmin -report`` prints from DatanodeInfo.java:519-560 — throughput with
no decomposition), so this tool is where the profiler's per-block phase
spans (utils/profiler.py) become an engineering answer: "the write path
does X MB/s; serialized WAL commit costs Y MB/s, awaited device dispatch Z,
…".  For each phase it computes the rate the run would reach if that phase's
EXCLUSIVE (non-overlapped) seconds vanished — the classic critical-path
what-if — and for the run as a whole the overlap-efficiency ratio (hidden /
hideable wait; the 1-vCPU DN host's only lever, PERF_NOTES.md round 4).

Sources, in order of preference:

- ``--input FILE``: a JSON list of BlockTimeline snapshots (a
  /traces-style capture), OR bench.py's single JSON output line itself
  (the ``phase_profile`` object is lifted out), OR a bare window/phase
  profile object — so ``python bench.py > out.json`` pipes straight in;
- default: run an in-process MiniCluster smoke write (the tiny-corpus
  analog of ``HDRF_BENCH_SMOKE``) and report over its timelines — the
  zero-setup mode the acceptance gate drives
  (``python -m hdrf_tpu.tools.gap_report``).
"""

from __future__ import annotations

import argparse
import json
import sys

from hdrf_tpu.utils import profiler

SMOKE_BLOCKS = 4
SMOKE_BLOCK_MB = 1


def run_smoke(n_blocks: int = SMOKE_BLOCKS,
              block_mb: int = SMOKE_BLOCK_MB) -> list[dict]:
    """Write a tiny dedup corpus through a MiniCluster and return the
    finished BlockTimeline snapshots (deterministic data: half fresh
    pseudo-random bytes, half a repeat of the first block so dedup_lookup
    and container_io both see realistic hit/miss mixes)."""
    import random

    from hdrf_tpu.testing.minicluster import MiniCluster

    profiler.reset()
    rng = random.Random(0x6A9)
    fresh = bytes(rng.getrandbits(8) for _ in range(block_mb << 20))
    with MiniCluster(n_datanodes=1, replication=1,
                     block_size=block_mb << 20) as mc:
        with mc.client("gap-report") as c:
            for i in range(n_blocks):
                payload = fresh if i % 2 else fresh[::-1]
                c.write(f"/gap/blk{i}", payload, scheme="dedup")
    return profiler.timelines_snapshot()


def aggregate(timelines: list[dict]) -> dict:
    """Fold per-block profiles into one run-level attribution table."""
    wall = nbytes = 0.0
    hidden = hideable = 0.0
    classes = dict.fromkeys(profiler.CLASSES, 0.0)
    phases: dict[str, float] = {}
    for tl in timelines:
        prof = tl.get("profile") or {}
        wall += prof.get("wall_s", 0.0)
        nbytes += tl.get("nbytes", 0) or 0
        hidden += prof.get("hidden_wait_s", 0.0)
        hideable += prof.get("hideable_wait_s", 0.0)
        for k, v in (prof.get("classes") or {}).items():
            classes[k] = classes.get(k, 0.0) + v
        for k, v in (prof.get("phases") or {}).items():
            phases[k] = phases.get(k, 0.0) + v
    rate = nbytes / wall / (1 << 20) if wall > 0 else 0.0
    rows = []
    for name, excl in phases.items():
        # rate if this phase's exclusive time vanished (critical-path
        # what-if); "lost" is the MB/s that phase costs the run
        without = nbytes / (wall - excl) / (1 << 20) if wall > excl else 0.0
        rows.append({"phase": name, "exclusive_s": excl,
                     "share": excl / wall if wall > 0 else 0.0,
                     "lost_mb_per_s": max(without - rate, 0.0)})
    rows.sort(key=lambda r: (-r["exclusive_s"], r["phase"]))
    attributed = (classes["host_busy"] + classes["device_busy"]
                  + classes["transport_wait"])
    return {
        "blocks": len(timelines),
        "bytes": int(nbytes),
        "wall_s": wall,
        "mb_per_s": rate,
        "classes": classes,
        "attributed_frac": attributed / wall if wall > 0 else 1.0,
        "overlap_efficiency": hidden / hideable if hideable > 0 else 1.0,
        "hidden_wait_s": hidden,
        "hideable_wait_s": hideable,
        "phases": rows,
    }


def format_table(agg: dict) -> str:
    """Deterministic text rendering (golden-tested)."""
    out = []
    out.append(f"write path: {agg['blocks']} blocks, "
               f"{agg['bytes'] / (1 << 20):.2f} MiB in {agg['wall_s']:.3f} s "
               f"= {agg['mb_per_s']:.1f} MB/s")
    out.append(f"attributed: {agg['attributed_frac'] * 100.0:.1f}% of wall "
               f"clock in named phase/overlap classes")
    out.append(f"overlap efficiency: {agg['overlap_efficiency'] * 100.0:.1f}%"
               f" ({agg['hidden_wait_s']:.3f} s of "
               f"{agg['hideable_wait_s']:.3f} s wait hidden under host work)")
    out.append("")
    out.append(f"{'class':<16} {'seconds':>9} {'share':>7}")
    wall = agg["wall_s"] or 1.0
    for cls in profiler.CLASSES:
        v = agg["classes"].get(cls, 0.0)
        out.append(f"{cls:<16} {v:>9.3f} {v / wall * 100.0:>6.1f}%")
    out.append("")
    out.append(f"{'phase':<16} {'excl s':>9} {'share':>7} {'lost MB/s':>10}")
    for r in agg["phases"]:
        out.append(f"{r['phase']:<16} {r['exclusive_s']:>9.3f} "
                   f"{r['share'] * 100.0:>6.1f}% {r['lost_mb_per_s']:>10.1f}")
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="hdrf_tpu.tools.gap_report",
        description="Gap-attribution table over write-path timelines")
    p.add_argument("--input", help="JSON file of BlockTimeline snapshots "
                   "(default: run a MiniCluster smoke write)")
    p.add_argument("--blocks", type=int, default=SMOKE_BLOCKS,
                   help="smoke-mode block count")
    p.add_argument("--json", action="store_true",
                   help="emit the aggregate as JSON instead of the table")
    args = p.parse_args(argv)
    if args.input:
        with open(args.input) as f:
            doc = json.load(f)
        if isinstance(doc, dict):
            # bench.py's JSON line (lift its phase_profile) or a bare
            # window-profile object: view it as one pseudo-timeline so
            # the same aggregation serves both shapes
            prof = doc.get("phase_profile", doc)
            doc = [{"nbytes": prof.get("bytes", 0), "profile": prof}]
        timelines = doc
    else:
        timelines = run_smoke(n_blocks=args.blocks)
    agg = aggregate(timelines)
    if args.json:
        print(json.dumps(agg))
    else:
        print(format_table(agg))
    return 0


if __name__ == "__main__":
    sys.exit(main())
