"""Command-line tools: the reference's shell surface re-expressed.

One entry point (``python -m hdrf_tpu.tools.cli``) with subcommands mirroring
the reference's launcher + admin tools (``src/main/bin/hdfs`` subcommand
dispatch; DFSAdmin.java:441, OfflineImageViewer / OfflineEditsViewer under
``hdfs/tools/``; Balancer under ``server/balancer/``):

  namenode / datanode      daemon launchers
  httpfs                   WebHDFS-style HTTP gateway
  dfs                      -ls -mkdir -put -get -cat -rm -mv -stat -du -count
                           -createSnapshot -deleteSnapshot -lsSnapshots
                           -snapshotDiff -checksum
                           -chmod -chown -getfacl -setfacl -setfattr -getfattr
  mover                    migrate replicas to satisfy storage policies
  dfsadmin                 -report -savenamespace -metrics -slowPeers
                           -contention -ecStatus -fsck
                           -movblock -setBalancerBandwidth -provide
                           -allowSnapshot -setQuota -setSpaceQuota -clrQuota
                           -safemode -decommission -decommissionStatus
                           -haState -haStatus -transitionToActive
  oiv / oev                offline fsimage / edit-log viewers
  balancer                 spread replicas toward the mean DN utilization
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _addr(s: str) -> tuple[str, int]:
    host, port = s.rsplit(":", 1)
    return host, int(port)


def _client(args):
    from hdrf_tpu.client.filesystem import HdrfClient
    from hdrf_tpu.config import ClientConfig

    # --secure (or HDRF_SECURE=1): fetch a delegation token and encrypt the
    # data wire — required against require_token_auth/encrypted clusters.
    secure = bool(getattr(args, "secure", False) or
                  os.environ.get("HDRF_SECURE"))
    cfg = ClientConfig(use_delegation_tokens=secure,
                       encrypt_data_transfer=secure)
    return HdrfClient(_addr(args.namenode), config=cfg)


# ------------------------------------------------------------------- daemons

def cmd_namenode(args) -> int:
    from hdrf_tpu.config import HdrfConfig
    from hdrf_tpu.server.namenode import NameNode

    cfg = HdrfConfig.load(args.config)
    if args.port is not None:
        cfg.namenode.port = args.port
    nn = NameNode(cfg.namenode).start()
    # daemon banners go to STDOUT via the structured logger (tooling greps
    # the "listening on host:port" substring, kept in both log formats)
    from hdrf_tpu.utils import log

    log.get_logger("namenode", stream=sys.stdout).info(
        f"namenode listening on {nn.addr[0]}:{nn.addr[1]}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        nn.stop()
    return 0


def cmd_datanode(args) -> int:
    from hdrf_tpu.config import HdrfConfig
    from hdrf_tpu.server.datanode import DataNode

    cfg = HdrfConfig.load(args.config)
    if args.data_dir:
        cfg.datanode.data_dir = args.data_dir
    dn = DataNode(cfg.datanode, _addr(args.namenode)).start()
    from hdrf_tpu.utils import log

    log.get_logger("datanode", stream=sys.stdout).info(
        f"datanode {dn.dn_id} listening on {dn.addr[0]}:{dn.addr[1]}",
        dn_id=dn.dn_id)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        dn.stop()
    return 0


def cmd_httpfs(args) -> int:
    from hdrf_tpu.server.http_gateway import HttpGateway

    gw = HttpGateway(_addr(args.namenode), port=args.port).start()
    from hdrf_tpu.utils import log

    log.get_logger("http_gateway", stream=sys.stdout).info(
        f"http gateway on http://{gw.addr[0]}:{gw.addr[1]}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        gw.stop()
    return 0


# ----------------------------------------------------------------------- dfs

def cmd_dfs(args) -> int:
    with _client(args) as c:
        if args.op == "-ls":
            for e in c.ls(args.args[0] if args.args else "/"):
                kind = "d" if e["type"] == "dir" else "-"
                size = e.get("length", e.get("children", 0))
                print(f"{kind} {size:>12} {e['name']}")
        elif args.op == "-mkdir":
            c.mkdir(args.args[0])
        elif args.op == "-put":
            local, remote = args.args
            with open(local, "rb") as f:
                c.write(remote, f.read(), scheme=args.scheme, ec=args.ec)
        elif args.op == "-get":
            remote, local = args.args
            data = c.read(remote)
            with open(local, "wb") as f:
                f.write(data)
        elif args.op == "-cat":
            sys.stdout.buffer.write(c.read(args.args[0]))
        elif args.op == "-rm":
            paths = [a for a in args.args if a != "-skipTrash"]
            if not paths:
                print("usage: -rm [-skipTrash] <path>", file=sys.stderr)
                return 1
            ok = c.delete(paths[0], skip_trash="-skipTrash" in args.args)
            if not ok:
                print(f"no such path: {paths[0]}", file=sys.stderr)
                return 1
        elif args.op == "-expunge":
            print(f"removed {c.expunge()} trash entries")
        elif args.op == "-mv":
            c.rename(args.args[0], args.args[1])
        elif args.op == "-stat":
            print(json.dumps(c.stat(args.args[0]), indent=2))
        elif args.op == "-du":
            total = sum(e.get("length", 0) for e in c.ls(args.args[0])
                        if e["type"] == "file")
            print(total)
        elif args.op == "-count":
            s = c.content_summary(args.args[0])
            print(f"{s['dirs']} {s['files']} {s['length']} {args.args[0]}")
        elif args.op == "-createSnapshot":
            c.create_snapshot(args.args[0], args.args[1])
        elif args.op == "-deleteSnapshot":
            c.delete_snapshot(args.args[0], args.args[1])
        elif args.op == "-lsSnapshots":
            for name in c.list_snapshots(args.args[0]):
                print(name)
        elif args.op == "-checksum":
            fc = c.get_file_checksum(args.args[0])
            print(f"{args.args[0]}\t{fc['algorithm']}\t{fc['bytes']}")
        elif args.op == "-snapshotDiff":
            # <root> <from> <to>; "." for <to> = the current tree
            root, frm, to = args.args[0], args.args[1], args.args[2]
            rep = c.snapshot_diff(root, frm, "" if to == "." else to)
            marks = {"CREATE": "+", "DELETE": "-", "MODIFY": "M",
                     "RENAME": "R"}
            for e in rep["entries"]:
                line = f"{marks[e['type']]}\t{e['path']}"
                if e["type"] == "RENAME":
                    line += f" -> {e['target']}"
                print(line)
        elif args.op == "-chmod":
            c.chmod(args.args[1], int(args.args[0], 8))
        elif args.op == "-chown":
            spec, path = args.args
            owner, _, group = spec.partition(":")
            c.chown(path, owner=owner, group=group)
        elif args.op == "-getfacl":
            for line in c.getfacl(args.args[0])["entries"]:
                print(line)
        elif args.op == "-setfacl":
            # -setfacl [-b | -k] <path> | -setfacl -m <spec> <path>
            if args.args[0] == "-b":
                c.setfacl(args.args[1], remove_all=True)
            elif args.args[0] == "-k":
                c.setfacl(args.args[1], remove_default=True)
            else:
                spec = args.args[1] if args.args[0] == "-m" else args.args[0]
                path = args.args[-1]
                acc = ",".join(e for e in spec.split(",")
                               if not e.startswith("default:"))
                dfl = ",".join(e[len("default:"):]
                               for e in spec.split(",")
                               if e.startswith("default:"))
                c.setfacl(path, spec=acc, default_spec=dfl)
        elif args.op == "-setfattr":
            # -setfattr -n name [-v value] <path> | -setfattr -x name <path>
            if args.args[0] == "-x":
                c.removefattr(args.args[2], args.args[1])
            else:
                name = args.args[1]
                if "-v" in args.args:
                    v = args.args[args.args.index("-v") + 1].encode()
                else:
                    v = b""
                c.setfattr(args.args[-1], name, v)
        elif args.op == "-getfattr":
            for k, v in sorted(c.getfattr(args.args[0]).items()):
                print(f"{k}={v.decode(errors='replace')}")
        else:
            print(f"unknown dfs op {args.op}", file=sys.stderr)
            return 1
    return 0


# ------------------------------------------------------------------ dfsadmin

def _dn_call(addr: str, op: str, timeout: float = 30.0, **fields) -> dict:
    """One data-plane op against a DataNode ('host:port') — the direct
    path dfsadmin -reconfig and diskbalancer share."""
    import socket as _socket

    from hdrf_tpu.proto import datatransfer as dt
    from hdrf_tpu.proto.rpc import recv_frame

    host, port = addr.rsplit(":", 1)
    with _socket.create_connection((host, int(port)), timeout=timeout) as s:
        dt.send_op(s, op, **fields)
        return recv_frame(s)


def cmd_dfsadmin(args) -> int:
    if args.op == "-reconfig":
        # DataNode-direct (ReconfigurationProtocol analog): no NN
        # round trip — reconfiguring a DN must work while the NN is down
        if args.args[1] == "list":
            print(json.dumps(_dn_call(args.args[0], "get_reconfigurable")))
        else:
            print(json.dumps(_dn_call(args.args[0], "reconfigure",
                                      key=args.args[1],
                                      value=args.args[2])))
        return 0
    with _client(args) as c:
        if args.op == "-report":
            # cluster summary first (dfsadmin -report's header block).
            # dedup_ratio prints with repr fidelity: operators (and the
            # acceptance test) compare it exactly against the ratio
            # recomputed from the chunk index.
            cs = c._call("cluster_status")
            print(f"Cluster: up={cs['live']} down={cs['dead']} "
                  f"blocks={cs['blocks']} "
                  f"under_replicated={cs['under_replicated']} "
                  f"safemode={cs['safemode']}")
            print(f"Reduction: dedup_ratio={cs['dedup_ratio']!r} "
                  f"dedup_logical={cs['dedup_logical_bytes']} "
                  f"dedup_unique={cs['dedup_unique_bytes']}")
            print(f"Health: slow_peers={cs['slow_peers']} "
                  f"slow_volumes={cs['slow_volumes']} "
                  f"reduction_degraded={cs.get('reduction_degraded', 0)}")
            for d in c.datanode_report():
                state = "live" if d["alive"] else "dead"
                stats = d.get("stats", {})
                stalls = stats.get("stalls", 0)
                vols = stats.get("volumes") or {}
                failed = sum(1 for v in vols.values() if v.get("failed"))
                # passthrough marker: the DN's worker breaker is open —
                # writes land unreduced until the half-open probe re-closes
                degraded = (" REDUCTION_DEGRADED"
                            if stats.get("reduction_degraded") else "")
                print(f"{d['dn_id']:>12} {state:>5} blocks={d['blocks']} "
                      f"logical={stats.get('logical_bytes', 0)} "
                      f"physical={stats.get('physical_bytes', 0)} "
                      f"volumes={len(vols)} failed_volumes={failed} "
                      f"stalls={stalls}{degraded}")
        elif args.op == "-savenamespace":
            c._call("save_namespace")
            print("namespace saved")
        elif args.op == "-metrics":
            print(json.dumps(c._call("metrics"), indent=2, sort_keys=True))
        elif args.op == "-slowPeers":
            # the outlier detector's verdict (slow_nodes_report) — peers
            # AND volumes, with the medians they were judged against
            print(json.dumps(c._call("slow_nodes_report"), indent=2))
        elif args.op == "-contention":
            # control-plane contention observatory (ISSUE 18): per-method
            # RPC service table + the namesystem lock's wait/hold books
            print(json.dumps(c._call("contention"), indent=2,
                             sort_keys=True))
        elif args.op == "-ecStatus":
            # cold-tier census: striped vs replicated containers and the
            # stripe tier's physical/logical ratio vs replication
            es = c._call("ec_status")
            print(f"EC policy: {es['policy']} "
                  f"(demote_after_s={es['demote_after_s']})")
            print(f"Demoted blocks: {es['demoted_blocks']} "
                  f"(pending_demotions={es['pending_demotions']} "
                  f"pending_stripe_repairs={es['pending_stripe_repairs']})")
            print(f"Containers: striped={es['striped_containers']} "
                  f"replicated={es['replicated_containers']} "
                  f"stripe_groups={es['stripe_groups']}")
            print(f"Stripe tier: logical={es['stripe_logical_bytes']} "
                  f"physical={es['stripe_physical_bytes']} "
                  f"ratio={es['storage_ratio_striped']:.2f}x "
                  f"(replicated tier: "
                  f"{es['storage_ratio_replicated']:.1f}x)")
        elif args.op == "-fsck":
            # invariant census (NamenodeFsck analog): block map vs live
            # DN membership, reported lengths, stripe decodability —
            # JSON verdict with per-class violation ids
            print(json.dumps(c._call("fsck"), indent=2, sort_keys=True))
        elif args.op == "-finalizeUpgrade":
            r = c._call("finalize_upgrade")
            print(f"finalized: namenode={r['namenode_finalized']} "
                  f"datanodes_queued={r['datanodes_queued']}")
        elif args.op == "-allowSnapshot":
            c.allow_snapshot(args.args[0])
            print(f"snapshots enabled on {args.args[0]}")
        elif args.op == "-setQuota":
            c.set_quota(args.args[1], namespace_quota=int(args.args[0]))
        elif args.op == "-setSpaceQuota":
            c.set_quota(args.args[1], space_quota=int(args.args[0]))
        elif args.op == "-clrQuota":
            c.set_quota(args.args[0])
        elif args.op == "-provide":
            # mount an external file as a PROVIDED-storage HDFS file:
            # NN registers the namespace half, then every live DN gets
            # the FileRegions (aliasmap/InMemoryAliasMapProtocol's
            # write half over the DN op)
            local, hpath = args.args
            local = os.path.abspath(local)
            length = os.path.getsize(local)
            out = c._call("provide_file", path=hpath,
                          uri=f"file://{local}", length=length)
            pushed = 0
            for d in c.datanode_report():
                if not d["alive"]:
                    continue
                addr = f"{d['addr'][0]}:{d['addr'][1]}"
                try:
                    r = _dn_call(addr, "alias_add",
                                 regions=out["regions"],
                                 tokens=out.get("tokens"))
                    pushed += 1 if r.get("ok") else 0
                except (OSError, ConnectionError) as e:
                    # a DN that died since its last heartbeat must not
                    # abort the mount mid-push; the rest keep serving
                    print(f"  warning: {d['dn_id']} unreachable ({e})",
                          file=sys.stderr)
            print(f"provided {hpath} ({length} bytes, "
                  f"{len(out['regions'])} regions) on {pushed} datanodes")
        elif args.op == "-setBalancerBandwidth":
            n = c._call("set_balancer_bandwidth",
                        bytes_per_s=int(args.args[0]))
            print(f"bandwidth {args.args[0]} B/s queued to {n} datanodes")
        elif args.op == "-recoverLease":
            ok = c._call("recover_lease", path=args.args[0])
            print("recovered" if ok else "not recovered")
        elif args.op == "-safemode":
            mode = args.args[0] if args.args else "get"
            on = c._call("safemode", action=mode)
            print(f"Safe mode is {'ON' if on else 'OFF'}")
        elif args.op == "-decommission":
            ok = c._call("decommission", dn_id=args.args[0])
            print("decommissioning" if ok else "unknown datanode")
            return 0 if ok else 1
        elif args.op == "-recommission":
            ok = c._call("recommission", dn_id=args.args[0])
            print("recommissioned" if ok else "was not decommissioning")
        elif args.op == "-decommissionStatus":
            st = c._call("decommission_status", dn_id=args.args[0])
            print(f"{args.args[0]}: {st['state']} remaining={st['remaining']}")
        elif args.op == "-haState":
            from hdrf_tpu.proto.rpc import RpcClient
            for a in args.args or [args.namenode]:
                host, port = a.rsplit(":", 1)
                try:
                    with RpcClient((host, int(port)), timeout=3.0) as rc:
                        st = rc.call("ha_state")
                    print(f"{a}: {st['role']} seq={st['seq']} epoch={st['epoch']}")
                except (OSError, ConnectionError):
                    print(f"{a}: unreachable")
        elif args.op == "-haStatus":
            # observer-aware -haState (ISSUE 20; haadmin -getAllServiceState
            # analog): role + applied txid + tail lag per endpoint
            from hdrf_tpu.proto.rpc import RpcClient
            for a in args.args or [args.namenode]:
                host, port = a.rsplit(":", 1)
                try:
                    with RpcClient((host, int(port)), timeout=3.0) as rc:
                        st = rc.call("ha_state")
                    print(f"{a}: {st['role']} "
                          f"applied_txid={st.get('applied_txid', st['seq'])} "
                          f"lag_s={st.get('lag_s', 0.0)} "
                          f"epoch={st['epoch']}")
                except (OSError, ConnectionError):
                    print(f"{a}: unreachable")
        elif args.op == "-transitionToActive":
            from hdrf_tpu.proto.rpc import RpcClient
            host, port = args.args[0].rsplit(":", 1)
            with RpcClient((host, int(port))) as rc:
                rc.call("transition_to_active")
            print("transitioned")
        elif args.op == "-movblock":
            bid, src, dst = args.args
            ok = c._call("move_block", block_id=int(bid), from_dn=src,
                            to_dn=dst)
            print("scheduled" if ok else "rejected")
            return 0 if ok else 1
        else:
            print(f"unknown dfsadmin op {args.op}", file=sys.stderr)
            return 1
    return 0


# ------------------------------------------------------------------- oiv/oev

def cmd_storage(args) -> int:
    """Offline storage-dir maintenance (Storage.java state machine): show
    the VERSION file, roll a store back to its pre-upgrade snapshot
    (namenode -rollback analog), or finalize (drop the snapshot).  The
    daemon owning the dir must be stopped."""
    from hdrf_tpu.storage import version as storage_version

    if args.action == "version":
        v = storage_version.read_version(args.directory)
        print(json.dumps(v if v is not None
                         else {"layoutVersion": 0, "unversioned": True}))
    elif args.action == "rollback":
        storage_version.rollback(args.directory)
        print(f"rolled back {args.directory}")
    elif args.action == "finalize":
        had = storage_version.finalize_upgrade(args.directory)
        print("finalized" if had else "nothing to finalize")
    return 0


def cmd_oiv(args) -> int:
    """Offline image viewer: dump the fsimage namespace as JSON lines
    (OfflineImageViewerPB analog)."""
    from hdrf_tpu.server.editlog import EditLog

    log = EditLog(args.meta_dir)
    snap = log.load_image()
    if snap is None:
        print("no fsimage", file=sys.stderr)
        return 1

    def walk(tree: dict, prefix: str):
        for name, v in sorted(tree.items()):
            path = f"{prefix}/{name}"
            if v[0] == "f":
                print(json.dumps({
                    "path": path, "type": "file", "replication": v[1],
                    "scheme": v[2], "blocks": v[3], "complete": v[4],
                    "ec": v[6] if len(v) > 6 else None}))
            else:
                print(json.dumps({"path": path, "type": "dir"}))
                walk(v[1], path)

    print(json.dumps({"image_seq": log.seq,
                      "next_block_id": snap["next_block_id"],
                      "gen_stamp": snap["gen_stamp"]}))
    walk(snap["tree"], "")
    return 0


def cmd_oev(args) -> int:
    """Offline edits viewer: dump WAL records as JSON lines
    (OfflineEditsViewer analog)."""
    import msgpack

    from hdrf_tpu.utils import wal as walmod

    path = os.path.join(args.meta_dir, "edits.wal")
    for payload in walmod.recover(path, truncate=False):
        seq, *rec = msgpack.unpackb(payload, raw=False, use_list=True,
                                    strict_map_key=False)
        print(json.dumps({"seq": seq, "op": rec[0], "args": rec[1:]}))
    return 0


# ------------------------------------------------------------------ balancer

def cmd_balancer(args) -> int:
    """Move replicas from over- to under-utilized DNs until every node is
    within ``threshold`` of the mean (Balancer.java policy, simplified to
    block counts; the Dispatcher's move legs ride rpc_move_block)."""
    with _client(args) as c:
        for _ in range(args.iterations):
            report = [d for d in c.datanode_report() if d["alive"]]
            if len(report) < 2:
                print("not enough live datanodes")
                return 0
            mean = sum(d["blocks"] for d in report) / len(report)
            over = [d for d in report if d["blocks"] > mean + args.threshold]
            under = sorted((d for d in report
                            if d["blocks"] < mean - args.threshold),
                           key=lambda d: d["blocks"])
            if not over or not under:
                print(f"balanced: mean={mean:.1f} "
                      f"spread={[d['blocks'] for d in report]}")
                return 0
            moved = 0
            for src in over:
                blocks = c._call("datanode_blocks", dn_id=src["dn_id"],
                                    limit=args.batch)
                for bid in blocks:
                    dst = under[moved % len(under)]
                    if c._call("move_block", block_id=bid,
                                  from_dn=src["dn_id"], to_dn=dst["dn_id"]):
                        moved += 1
                    if moved >= args.batch:
                        break
                if moved >= args.batch:
                    break
            print(f"scheduled {moved} moves; waiting for settle")
            time.sleep(args.wait_s)
    return 0


def cmd_diskbalancer(args) -> int:
    """DiskBalancer-lite (server/diskbalancer analog): ask a DataNode to
    even its own volumes — plan + execute in one round trip."""
    r = _dn_call(args.datanode, "disk_balance", timeout=60.0,
                 threshold=args.threshold)
    print(json.dumps(r, indent=2))
    return 0


def cmd_mover(args) -> int:
    """Mover (server/mover/Mover.java:70 analog): migrate replicas until
    every block's storage types satisfy its path's effective policy.  The
    NN proposes (from, to) legs; each rides the same rpc_move_block the
    balancer uses (copy to target, invalidate source once reported)."""
    with _client(args) as c:
        total = 0
        for _ in range(args.iterations):
            moves = c._call("policy_violations", limit=args.batch)
            if not moves:
                print(f"storage policies satisfied ({total} moves)")
                return 0
            for mv in moves:
                if c._call("move_block", block_id=mv["block_id"],
                           from_dn=mv["from_dn"], to_dn=mv["to_dn"]):
                    total += 1
            print(f"scheduled {len(moves)} moves; waiting for settle")
            time.sleep(args.wait_s)
        print(f"iteration budget exhausted after {total} moves")
        return 1


# ---------------------------------------------------------------------- main

def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="hdrf")
    sub = p.add_subparsers(dest="cmd", required=True)

    d = sub.add_parser("namenode")
    d.add_argument("--config", default=None)
    d.add_argument("--port", type=int, default=None)
    d.set_defaults(fn=cmd_namenode)

    d = sub.add_parser("datanode")
    d.add_argument("--config", default=None)
    d.add_argument("--namenode", required=True)
    d.add_argument("--secure", action="store_true")
    d.add_argument("--data-dir", default=None)
    d.set_defaults(fn=cmd_datanode)

    d = sub.add_parser("httpfs")
    d.add_argument("--namenode", required=True)
    d.add_argument("--secure", action="store_true")
    d.add_argument("--port", type=int, default=9870)
    d.set_defaults(fn=cmd_httpfs)

    d = sub.add_parser("dfs")
    d.add_argument("--namenode", required=True)
    d.add_argument("--secure", action="store_true")
    d.add_argument("--scheme", default=None)
    d.add_argument("--ec", default=None)
    d.set_defaults(fn=cmd_dfs, takes_ops=True)

    d = sub.add_parser("dfsadmin")
    d.add_argument("--namenode", required=True)
    d.add_argument("--secure", action="store_true")
    d.set_defaults(fn=cmd_dfsadmin, takes_ops=True)

    d = sub.add_parser("diskbalancer")
    d.add_argument("--datanode", required=True, help="host:port")
    d.add_argument("--threshold", type=float, default=0.10)
    d.set_defaults(fn=cmd_diskbalancer)

    d = sub.add_parser("storage")
    d.add_argument("action", choices=["version", "rollback", "finalize"])
    d.add_argument("directory")
    d.set_defaults(fn=cmd_storage)

    d = sub.add_parser("oiv")
    d.add_argument("meta_dir")
    d.set_defaults(fn=cmd_oiv)

    d = sub.add_parser("oev")
    d.add_argument("meta_dir")
    d.set_defaults(fn=cmd_oev)

    d = sub.add_parser("mover")
    d.add_argument("--namenode", required=True)
    d.add_argument("--secure", action="store_true")
    d.add_argument("--iterations", type=int, default=10)
    d.add_argument("--batch", type=int, default=16)
    d.add_argument("--wait-s", type=float, default=1.0)
    d.set_defaults(fn=cmd_mover)

    d = sub.add_parser("balancer")
    d.add_argument("--namenode", required=True)
    d.add_argument("--secure", action="store_true")
    d.add_argument("--threshold", type=float, default=2.0)
    d.add_argument("--iterations", type=int, default=10)
    d.add_argument("--batch", type=int, default=8)
    d.add_argument("--wait-s", type=float, default=2.0)
    d.set_defaults(fn=cmd_balancer)

    # dfs/dfsadmin ops are dash-prefixed like the reference shell (-ls,
    # -put, ...), which argparse (and its subparsers) won't accept — split
    # the command line at the first single-dash token and parse only the
    # prefix; everything from the op onward passes through verbatim.
    argv = list(sys.argv[1:] if argv is None else argv)
    op_args: list[str] = []
    if argv and argv[0] in ("dfs", "dfsadmin"):
        for i, tok in enumerate(argv[1:], start=1):
            if tok.startswith("-") and not tok.startswith("--"):
                argv, op_args = argv[:i], argv[i:]
                break
    args = p.parse_args(argv)
    if getattr(args, "takes_ops", False):
        if not op_args:
            p.error("missing operation")
        args.op, args.args = op_args[0], op_args[1:]
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
