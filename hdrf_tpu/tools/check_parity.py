"""Parity-citation lint: every module must cite its reference sources.

The repo convention (CLAUDE.md; e.g. the headers of server/datanode.py,
reduction/dedup.py) is that each module's docstring names the reference
files it re-expresses with ``file:line`` citations — DataNode.java:438,
SlowPeerTracker.java:56, index/chunk_index.py:309 — so the component map
(PARITY.md) stays verifiable against the code.  This tool enforces it:
every ``hdrf_tpu/**/*.py`` module (``__init__.py`` exempt — package
markers carry no component of their own) must have a docstring containing
at least one such citation.

Run as ``python -m hdrf_tpu.tools.check_parity`` (exit 1 on violations);
wired as a tier-1 test in tests/test_tools.py.
"""

from __future__ import annotations

import ast
import os
import re
import sys

# file.ext:NNN with an optional -NNN range, e.g. "OutlierDetector.java:61-103"
CITATION = re.compile(
    r"[A-Za-z0-9_][A-Za-z0-9_.\-/]*"
    r"\.(?:java|py|c|cc|cpp|h|hpp|proto|md|html|sh|json)"
    r":\d+(?:-\d+)?")


def check(root: str) -> list[str]:
    """Return one message per violating module (empty = clean)."""
    problems: list[str] = []
    for dirpath, _dirs, files in sorted(os.walk(root)):
        for fn in sorted(files):
            if not fn.endswith(".py") or fn == "__init__.py":
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, os.path.dirname(root))
            try:
                tree = ast.parse(open(path, encoding="utf-8").read(), path)
            except SyntaxError as e:
                problems.append(f"{rel}: unparseable ({e.msg})")
                continue
            doc = ast.get_docstring(tree)
            if not doc:
                problems.append(f"{rel}: no module docstring")
            elif not CITATION.search(doc):
                problems.append(f"{rel}: docstring has no file:line "
                                f"reference citation")
    return problems


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    problems = check(root)
    for p in problems:
        print(p)
    print(f"{len(problems)} violation(s)" if problems
          else "parity citations: all modules cite references")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
