"""Parity-citation and fault-point lints.

The repo convention (CLAUDE.md; e.g. the headers of server/datanode.py,
reduction/dedup.py) is that each module's docstring names the reference
files it re-expresses with ``file:line`` citations — DataNode.java:438,
SlowPeerTracker.java:56, index/chunk_index.py:309 — so the component map
(PARITY.md) stays verifiable against the code.  This tool enforces it:
every ``hdrf_tpu/**/*.py`` module (``__init__.py`` exempt — package
markers carry no component of their own) must have a docstring containing
at least one such citation.

It also lints the fault-injection matrix (the DataNodeFaultInjector.java:33
mechanism re-expressed by utils/fault_injection.py): every
``fault_injection.point("name", ...)`` declared in main code must be
referenced by at least one test under ``tests/`` — an unexercised crash
window is a crash window nobody has proven survivable.

Third lint: the /prom metric contract.  Every metric name declared with a
plain string literal through a registry's incr / observe / gauge / time
call must appear backticked in ARCHITECTURE.md's metrics table — an
undocumented gauge is a dashboard nobody can interpret.
Dynamic (f-string) names are exempt by construction — their FAMILIES must
be documented under the base name instead (e.g. ``phase_us``,
``wait_us``), which the tests pin.

Run as ``python -m hdrf_tpu.tools.check_parity`` (exit 1 on violations);
wired as tier-1 tests in tests/test_tools.py.
"""

from __future__ import annotations

import ast
import os
import re
import sys

# file.ext:NNN with an optional -NNN range, e.g. "OutlierDetector.java:61-103"
CITATION = re.compile(
    r"[A-Za-z0-9_][A-Za-z0-9_.\-/]*"
    r"\.(?:java|py|c|cc|cpp|h|hpp|proto|md|html|sh|json)"
    r":\d+(?:-\d+)?")

# fault_injection.point("name", ...) declarations in main code
FAULT_POINT = re.compile(
    r"fault_injection\.point\(\s*['\"]([A-Za-z0-9_.]+)['\"]")

# Plain-string metric declarations.  f-string names (per-phase/per-op
# families like f"wait_us|op={op}") never match: the ``f`` prefix sits
# between the open paren and the quote, which ``\s*`` rejects.
METRIC_CALL = re.compile(
    r"\.(?:incr|observe|gauge|time)\(\s*['\"]([A-Za-z0-9_.|=]+)['\"]")


def check(root: str) -> list[str]:
    """Return one message per violating module (empty = clean)."""
    problems: list[str] = []
    for dirpath, _dirs, files in sorted(os.walk(root)):
        for fn in sorted(files):
            if not fn.endswith(".py") or fn == "__init__.py":
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, os.path.dirname(root))
            try:
                tree = ast.parse(open(path, encoding="utf-8").read(), path)
            except SyntaxError as e:
                problems.append(f"{rel}: unparseable ({e.msg})")
                continue
            doc = ast.get_docstring(tree)
            if not doc:
                problems.append(f"{rel}: no module docstring")
            elif not CITATION.search(doc):
                problems.append(f"{rel}: docstring has no file:line "
                                f"reference citation")
    return problems


def declared_fault_points(root: str) -> dict[str, str]:
    """Every fault point declared under ``root`` -> declaring file."""
    points: dict[str, str] = {}
    for dirpath, _dirs, files in sorted(os.walk(root)):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            src = open(path, encoding="utf-8").read()
            for name in FAULT_POINT.findall(src):
                points.setdefault(name,
                                  os.path.relpath(path,
                                                  os.path.dirname(root)))
    return points


def check_fault_points(root: str, tests_dir: str | None = None) -> list[str]:
    """Return one message per declared-but-untested fault point."""
    if tests_dir is None:
        tests_dir = os.path.join(os.path.dirname(root), "tests")
    corpus = []
    if os.path.isdir(tests_dir):
        for dirpath, _dirs, files in sorted(os.walk(tests_dir)):
            for fn in sorted(files):
                if fn.endswith(".py"):
                    corpus.append(
                        open(os.path.join(dirpath, fn),
                             encoding="utf-8").read())
    corpus = "\n".join(corpus)
    problems = []
    for name, where in sorted(declared_fault_points(root).items()):
        if f'"{name}"' not in corpus and f"'{name}'" not in corpus:
            problems.append(f"fault point '{name}' ({where}) is referenced "
                            f"by no test under {tests_dir}")
    return problems


def declared_metrics(root: str) -> dict[str, str]:
    """Every plain-literal metric name declared under ``root`` -> first
    declaring file.  Keys keep any ``|label=value`` suffix; the documented
    unit is the base name (``key.split("|")[0]``)."""
    names: dict[str, str] = {}
    for dirpath, _dirs, files in sorted(os.walk(root)):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            src = open(path, encoding="utf-8").read()
            for name in METRIC_CALL.findall(src):
                names.setdefault(name,
                                 os.path.relpath(path,
                                                 os.path.dirname(root)))
    return names


def check_prom_metrics(root: str, arch_md: str | None = None) -> list[str]:
    """Return one message per metric name absent from ARCHITECTURE.md's
    metrics table (matched as a backticked base name)."""
    if arch_md is None:
        arch_md = os.path.join(os.path.dirname(root), "ARCHITECTURE.md")
    text = ""
    if os.path.isfile(arch_md):
        text = open(arch_md, encoding="utf-8").read()
    problems = []
    for name, where in sorted(declared_metrics(root).items()):
        base = name.split("|")[0]
        if f"`{base}`" not in text:
            problems.append(f"metric '{base}' ({where}) is not documented "
                            f"in {os.path.basename(arch_md)}")
    return problems


def _value_carries_key(value: ast.expr, sub: str,
                       funcs: dict[str, ast.FunctionDef]) -> bool:
    """Does the expression bound to a top-level bench key provably carry
    ``sub`` as a literal dict key?  Two shapes are recognized: an inline
    ``{...}`` literal, and a call to a module-level helper (the
    ``_read_summary(tmp)`` pattern) whose ``return {...}`` literal is
    scanned one level deep."""
    dicts: list[ast.Dict] = []
    if isinstance(value, ast.Dict):
        dicts.append(value)
    elif (isinstance(value, ast.Call) and isinstance(value.func, ast.Name)
            and value.func.id in funcs):
        for node in ast.walk(funcs[value.func.id]):
            if isinstance(node, ast.Return) and isinstance(node.value,
                                                           ast.Dict):
                dicts.append(node.value)
    return any(isinstance(k, ast.Constant) and k.value == sub
               for d in dicts for k in d.keys)


def check_bench_contract(root: str, bench_py: str | None = None,
                         key: str = "multichip") -> list[str]:
    """Fourth lint: bench.py's output contract.  The bench emits its one
    JSON line from two branches (native CPU fallback and the TPU path);
    a summary block added to only one silently vanishes from whichever
    backend the driver happens to land on.  Assert the ``key`` appears as
    a literal dict key in at least two ``json.dumps({...})`` calls.

    A dotted key (``read.chunk_cache_hit_ratio``) additionally pins a
    SUB-key of a summary block: each branch's value for the top key must
    carry the sub-key, either as an inline dict literal or inside the
    ``return {...}`` of the module-level helper the branch calls — so a
    metric dropped from a summary helper fails the lint even though both
    branches still name the block."""
    if bench_py is None:
        bench_py = os.path.join(os.path.dirname(root), "bench.py")
    if not os.path.isfile(bench_py):
        return [f"bench contract: {bench_py} missing"]
    tree = ast.parse(open(bench_py, encoding="utf-8").read(), bench_py)
    top, _, sub = key.partition(".")
    funcs = {n.name: n for n in ast.walk(tree)
             if isinstance(n, ast.FunctionDef)}
    hits = 0
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "dumps" and node.args
                and isinstance(node.args[0], ast.Dict)):
            d = node.args[0]
            if not sub:
                hits += any(isinstance(k, ast.Constant) and k.value == top
                            for k in d.keys)
                continue
            hits += any(isinstance(k, ast.Constant) and k.value == top
                        and _value_carries_key(v, sub, funcs)
                        for k, v in zip(d.keys, d.values))
    if hits < 2:
        return [f"bench contract: '{key}' key present in {hits} of the "
                f"expected 2+ json.dumps branches of bench.py"]
    return []


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    problems = (check(root) + check_fault_points(root)
                + check_prom_metrics(root) + check_bench_contract(root)
                + check_bench_contract(root, key="mirror")
                + check_bench_contract(root, key="read")
                + check_bench_contract(root, key="read.chunk_cache_hit_ratio")
                + check_bench_contract(root, key="read.read_batches")
                + check_bench_contract(
                    root, key="read.containers_decoded_per_read")
                + check_bench_contract(root, key="scrub")
                + check_bench_contract(root, key="qos")
                + check_bench_contract(root, key="qos.sheds")
                + check_bench_contract(root, key="qos.tenant_fairness_ratio")
                + check_bench_contract(root, key="qos.ec_hedge_wins")
                + check_bench_contract(root, key="cdc_adaptive")
                + check_bench_contract(root, key="cdc_adaptive.skip_ahead")
                + check_bench_contract(
                    root, key="cdc_adaptive.scan_slab_survivors")
                + check_bench_contract(
                    root, key="cdc_adaptive.mask_bits_effective")
                + check_bench_contract(root, key="cdc_adaptive.retunes")
                + check_bench_contract(root, key="coded_exchange")
                + check_bench_contract(
                    root, key="coded_exchange.repair_wire_ratio")
                + check_bench_contract(
                    root, key="coded_exchange.coded_repairs")
                + check_bench_contract(
                    root, key="coded_exchange.pack_saved_frac")
                + check_bench_contract(root, key="longhorizon")
                + check_bench_contract(
                    root, key="longhorizon.storage_ratio_slope")
                + check_bench_contract(root, key="nn")
                + check_bench_contract(root, key="nn.rpc_p99_ms")
                + check_bench_contract(root, key="nn.lock_saturation")
                + check_bench_contract(root, key="nn.observer_share"))
    for p in problems:
        print(p)
    print(f"{len(problems)} violation(s)" if problems
          else "parity citations + fault-point coverage + metric docs + "
               "bench contract: clean")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
