"""Durable chunk/fingerprint index — the owned replacement for Redis.

The reference keeps all reduction metadata in an external Redis at
localhost:6379 with no auth, no durability guarantees, and no recovery path
(SURVEY.md §5: "Redis or chunk-store loss = silent data loss"):

- Table 1: 4-byte HDFS block ID -> [4-byte filesize | N x hash]
  (DataDeduplicator.java:372-392, read back DataConstructor.java:91-100)
- Table 2: hash -> 11-byte packed chunkMeta {nCopy, containerID, start, stop}
  (chunkMeta.java:35-77, written DataDeduplicator.java:803)
- per-block writer-thread container cursors (utilities.java:66-75)

Here the same two tables are an in-process store with an append-only WAL,
periodic checkpoints, and crash recovery = checkpoint + WAL replay.  Chunks are
refcounted and deletable — the reference's "Table #3 for later"
(DataDeduplicator.java:61-62) — so containers can be compacted.

Durability discipline:

- WAL record framing: [u32 payload_len][u32 crc32c(payload)][msgpack payload];
  a torn final record (crash mid-append) is detected by CRC and dropped.
- **Log before apply**: a failed WAL append leaves memory untouched, so later
  records can never reference state the log doesn't contain.
- **Sequence numbers make replay idempotent**: every record carries a
  monotonically increasing seqno and the checkpoint stores the last seqno it
  folded in; recovery skips WAL records <= that seqno, so a crash between
  checkpoint publish and WAL truncation cannot double-apply refcounts.
- **Bounded group-commit window** (the FSEditLog.java:1648 ``logSync``
  batching discipline): when armed (``group_window_s`` > 0), concurrent
  ``commit_block`` callers elect a leader that waits up to the window (or
  until ``group_max`` entries queue) and flushes the whole batch through
  one WAL append + ONE fsync.  Each caller still returns only after its
  record is durable AND applied — log-before-apply holds per block, and a
  crash mid-window loses only blocks whose callers were never acked.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from dataclasses import dataclass

import msgpack

from hdrf_tpu.utils import fault_injection, metrics, profiler, wal as walmod

_M = metrics.registry("chunk_index")

WAL_NAME = "index.wal"
CKPT_NAME = "index.ckpt"
CKPT_TMP = "index.ckpt.tmp"


def _norm_manifest(m: dict) -> dict:
    """Normalize a striping manifest read back through msgpack raw=True:
    dict keys and string values arrive as bytes — decode them so live-path
    and recovered manifests compare equal (the replay idempotence bar the
    WAL discipline sets)."""
    out = {}
    for key, v in m.items():
        key = key.decode() if isinstance(key, bytes) else key
        if isinstance(v, bytes):
            v = v.decode()
        elif isinstance(v, (list, tuple)):
            v = [[x.decode() if isinstance(x, bytes) else x for x in e]
                 if isinstance(e, (list, tuple))
                 else (e.decode() if isinstance(e, bytes) else e)
                 for e in v]
        out[key] = v
    return out


@dataclass
class ChunkLocation:
    """Where a chunk's bytes live.  Fixed-width equivalent of the reference's
    11-byte chunkMeta record (chunkMeta.java:35-60): container id, byte range
    within the *uncompressed* container, and the refcount (nCopy)."""

    container_id: int
    offset: int
    length: int
    refcount: int = 1


@dataclass
class BlockEntry:
    """Table-1 row: logical length + ordered chunk fingerprints."""

    logical_len: int
    hashes: list[bytes]


class _GCEntry:
    """One caller's block parked in the group-commit window."""

    __slots__ = ("block", "done", "losers", "exc")

    def __init__(self, block: tuple) -> None:
        self.block = block
        self.done = False
        self.losers: list[bytes] = []
        self.exc: BaseException | None = None


class ChunkIndex:
    """Thread-safe durable index with WAL + checkpoint recovery."""

    def __init__(self, directory: str, checkpoint_every: int = 10000,
                 group_window_s: float = 0.0, group_max: int = 8):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._blocks: dict[int, BlockEntry] = {}
        self._chunks: dict[bytes, ChunkLocation] = {}
        self._sealed: set[int] = set()  # container ids sealed (compressed)
        self._stripes: dict[int, dict] = {}  # cid -> EC striping manifest
        self._seq = 0  # last seqno applied
        self._pending_recs: list[list] = []  # advisory recs awaiting a flush
        self._ops_since_ckpt = 0
        self._checkpoint_every = checkpoint_every
        # group-commit window: 0 = every commit_block fsyncs on its own
        # (the serial pipeline_depth=1 behavior)
        self._group_window_s = group_window_s
        self._group_max = max(group_max, 1)
        self._gc_cv = threading.Condition()
        self._gc_entries: list[_GCEntry] = []
        self._gc_leader = False
        # commit listeners fire inside _apply's b"blk" branch with the
        # record's first-seen fingerprints (the sharded bucket table's
        # incremental refresh feed) — registered before _recover() so
        # replay-applied records also notify.
        self._listeners: list = []
        # dedup-race loser bytes per container: both writers appended the
        # chunk, one commit won, the loser's container bytes are orphans.
        # In-memory advisory accounting (not WAL'd — a restart folds prior
        # orphans into the generic dead-bytes delta); the scrubber's
        # garbage census splits `garbage_bytes|class=orphan_append` out of
        # the payload-minus-live delta with it.
        self._orphans: dict[int, int] = {}
        self._recover()
        self._wal = open(os.path.join(directory, WAL_NAME), "ab")

    # ------------------------------------------------------------- recovery

    def _recover(self) -> None:
        ckpt = os.path.join(self._dir, CKPT_NAME)
        if os.path.exists(ckpt):
            with open(ckpt, "rb") as f:
                snap = msgpack.unpackb(f.read(), raw=True, strict_map_key=False)
            self._blocks = {
                bid: BlockEntry(e[0], list(e[1])) for bid, e in snap[b"blocks"].items()
            }
            self._chunks = {
                h: ChunkLocation(*loc) for h, loc in snap[b"chunks"].items()
            }
            self._sealed = set(snap[b"sealed"])
            self._stripes = {cid: _norm_manifest(m)
                             for cid, m in snap.get(b"stripes", {}).items()}
            self._seq = snap.get(b"seq", 0)
        # recover() truncates any torn tail so the append handle continues at
        # the good prefix (otherwise post-crash records land behind garbage).
        for payload in walmod.recover(os.path.join(self._dir, WAL_NAME)):
            seq, *rec = msgpack.unpackb(payload, raw=True, use_list=True)
            if seq > self._seq:  # skip records the checkpoint already folded in
                self._apply(rec)
                self._seq = seq

    def _apply(self, rec: list) -> None:
        op = rec[0]
        if op == b"blk":  # [op, block_id, logical_len, [hashes], {hash: [cid,off,len]}]
            _, bid, llen, hashes, new_chunks = rec
            for h, loc in new_chunks.items():
                self._chunks[h] = ChunkLocation(loc[0], loc[1], loc[2], 0)
            for h in hashes:
                self._chunks[h].refcount += 1
            self._blocks[bid] = BlockEntry(llen, list(hashes))
            if self._listeners and new_chunks:
                fps = list(new_chunks)
                for fn in self._listeners:
                    try:
                        fn(fps)
                    except Exception:  # noqa: BLE001 — advisory feed; a bad
                        pass  # listener must never fail the durable commit
        elif op == b"del":  # [op, block_id]
            entry = self._blocks.pop(rec[1], None)
            if entry:
                for h in entry.hashes:
                    loc = self._chunks.get(h)
                    if loc:
                        loc.refcount -= 1
                        if loc.refcount <= 0:
                            del self._chunks[h]
        elif op == b"seal":  # [op, container_id]
            self._sealed.add(rec[1])
        elif op == b"moved":  # [op, {hash: [cid, off, len]}] — compaction result
            for h, loc in rec[1].items():
                c = self._chunks.get(h)
                if c is not None:
                    c.container_id, c.offset, c.length = loc[0], loc[1], loc[2]
        elif op == b"unseal":  # [op, container_id] — container deleted by GC
            self._sealed.discard(rec[1])
        elif op == b"stripe":  # [op, container_id, manifest] — EC demotion
            self._stripes[rec[1]] = _norm_manifest(rec[2])
        elif op == b"unstripe":  # [op, container_id] — promoted back / deleted
            self._stripes.pop(rec[1], None)

    # ------------------------------------------------------------------ WAL

    def _commit(self, rec: list) -> None:
        self._commit_many([rec])

    def _commit_many(self, recs: list[list]) -> None:
        """Log all, fsync ONCE, then apply, then maybe checkpoint (group
        commit — the FSEditLog.logSync batching idea applied to the chunk
        index).  Caller holds the lock.  A failed append raises *before*
        any in-memory mutation.  Buffered advisory records (seal markers)
        ride along, already applied."""
        if self._pending_recs:
            pending, self._pending_recs = self._pending_recs, []
            for rec in pending:
                payload = msgpack.packb([self._seq + 1, *rec])
                self._wal.write(walmod.frame(payload))
                self._seq += 1
            # note: pending records were applied at buffer time; only the
            # WAL bytes were deferred
        with profiler.phase("wal_commit"):
            buf = bytearray()
            for i, rec in enumerate(recs):
                buf += walmod.frame(msgpack.packb([self._seq + 1 + i, *rec]))
            fault_injection.point("index.wal_append")
            self._wal.write(bytes(buf))
            self._wal.flush()
            os.fsync(self._wal.fileno())
            for rec in recs:
                self._seq += 1
                self._apply(rec)
        self._ops_since_ckpt += len(recs)
        if self._ops_since_ckpt >= self._checkpoint_every:
            self._checkpoint_locked()

    # ------------------------------------------------------------- mutation

    def add_commit_listener(self, fn) -> None:
        """Register ``fn(fingerprints: list[bytes])`` to run on every block
        commit with that record's FIRST-SEEN chunk fingerprints (after the
        record is durable + applied).  Advisory: exceptions are swallowed,
        delivery is at-least-once across recovery replay.  Feeds the mesh
        plane's device-resident bucket table (parallel/sharded.py)."""
        with self._lock:
            self._listeners.append(fn)

    def lookup_chunks(self, hashes: list[bytes]) -> dict[bytes, ChunkLocation | None]:
        """Batch fingerprint probe — the reference's per-thread Redis MULTI GET
        (DataDeduplicator.java:588-610).  Returns copies: callers may hold the
        results across a concurrent compaction commit."""
        with self._lock:
            return {h: dataclasses.replace(loc) if (loc := self._chunks.get(h))
                    else None for h in hashes}

    def commit_blocks(self, blocks: list[tuple]) -> list[bytes]:
        """Group commit of several reduced blocks: one WAL write + ONE
        fsync covers every record (the latency/throughput lever the
        per-block fsync lacks).  ``blocks`` is a list of
        (block_id, logical_len, hashes, new_chunks) tuples with the same
        semantics as commit_block; returns the union of race-loser
        fingerprints."""
        losers: list[bytes] = []
        with profiler.phase("wal_commit"), self._lock:
            recs = []
            seen_new: set[bytes] = set()
            for block_id, logical_len, hashes, new_chunks in blocks:
                fresh = {}
                for h, loc in new_chunks.items():
                    if h in self._chunks or h in seen_new:
                        losers.append(h)
                        self._note_orphan_locked(loc)
                    else:
                        fresh[h] = loc
                        seen_new.add(h)
                for h in hashes:
                    if h not in self._chunks and h not in fresh \
                            and h not in seen_new:
                        raise ValueError(
                            f"hash {h.hex()} neither known nor new")
                recs.append([b"blk", block_id, logical_len, hashes,
                             {h: [c, o, ln]
                              for h, (c, o, ln) in fresh.items()}])
            self._commit_many(recs)
            _M.incr("group_commit_batches")
            _M.observe("group_commit_blocks", len(recs))
            return losers

    def commit_block(self, block_id: int, logical_len: int, hashes: list[bytes],
                     new_chunks: dict[bytes, tuple[int, int, int]]) -> list[bytes]:
        """Atomically commit a reduced block: register first-seen chunks at
        their container locations, bump refcounts for every reference, and
        write the Table-1 row.  One WAL record; replaces the reference's
        unordered Redis SET pipeline (DataDeduplicator.java:372-392,803).

        Two writers may race dedup'ing the same never-seen chunk: both will
        have appended its bytes and both declare it in ``new_chunks``.  The
        first commit wins; later commits keep the existing location and the
        loser's container bytes become orphans (reclaimed by compaction).
        Returns the fingerprints that lost such races.

        With the group-commit window armed, concurrent callers park in the
        window and share one fsync (leader/follower election); validation
        failures stay PER CALLER — one bad block raises to its own writer
        and the rest of the window commits."""
        if self._group_window_s > 0:
            return self._commit_block_grouped(
                (block_id, logical_len, hashes, new_chunks))
        with profiler.phase("wal_commit"), self._lock:
            losers = [h for h in new_chunks if h in self._chunks]
            for h in losers:
                self._note_orphan_locked(new_chunks[h])
            fresh = {h: loc for h, loc in new_chunks.items() if h not in self._chunks}
            for h in hashes:
                if h not in self._chunks and h not in fresh:
                    raise ValueError(f"hash {h.hex()} neither known nor new")
            self._commit([b"blk", block_id, logical_len, hashes,
                          {h: [c, o, ln] for h, (c, o, ln) in fresh.items()}])
            return losers

    # --------------------------------------------------- group-commit window

    def _commit_block_grouped(self, block: tuple) -> list[bytes]:
        """Park ``block`` in the group-commit window; return once its record
        is fsync'd + applied (or raise its per-caller validation error).
        First arrival with no leader becomes the leader, waits out the
        window (early-out at ``group_max``), and commits the whole batch
        with one fsync; followers just wait on their entry."""
        entry = _GCEntry(block)
        with profiler.phase("wal_commit"):
            with self._gc_cv:
                self._gc_entries.append(entry)
                profiler.counter_set("wal_queue_depth",
                                     len(self._gc_entries))
                self._gc_cv.notify_all()  # window-waiting leader may early-out
                while not entry.done:
                    if not self._gc_leader:
                        self._gc_leader = True
                        self._lead_group_locked()
                    else:
                        self._gc_cv.wait()
        if entry.exc is not None:
            raise entry.exc
        return entry.losers

    def _lead_group_locked(self) -> None:
        """Leader body.  Called with ``_gc_cv`` held and ``_gc_leader`` set;
        returns with both restored and every batch entry done-flagged."""
        deadline = time.monotonic() + self._group_window_s
        while len(self._gc_entries) < self._group_max:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            self._gc_cv.wait(timeout=remaining)
        batch, self._gc_entries = self._gc_entries, []
        profiler.counter_set("wal_queue_depth", 0)
        # drop the cv while fsyncing so late arrivals queue the NEXT window
        self._gc_cv.release()
        try:
            self._commit_group(batch)
        finally:
            self._gc_cv.acquire()
            self._gc_leader = False
            for e in batch:
                e.done = True
            self._gc_cv.notify_all()

    def _commit_group(self, batch: list[_GCEntry]) -> None:
        """Validate each entry (per-caller isolation: a bad block gets its
        exception set and is EXCLUDED), then push the valid records through
        one ``_commit_many`` — one WAL append, one fsync, apply after.  A
        failed append leaves memory untouched and raises to every valid
        caller (log-before-apply, now per window)."""
        with self._lock:
            recs: list[list] = []
            committing: list[_GCEntry] = []
            seen_new: set[bytes] = set()
            for e in batch:
                block_id, logical_len, hashes, new_chunks = e.block
                fresh = {}
                losers = []
                try:
                    for h, loc in new_chunks.items():
                        if h in self._chunks or h in seen_new:
                            losers.append(h)
                            self._note_orphan_locked(loc)
                        else:
                            fresh[h] = loc
                    for h in hashes:
                        if h not in self._chunks and h not in fresh \
                                and h not in seen_new:
                            raise ValueError(
                                f"hash {h.hex()} neither known nor new")
                except ValueError as exc:
                    e.exc = exc
                    continue
                seen_new.update(fresh)
                e.losers = losers
                recs.append([b"blk", block_id, logical_len, hashes,
                             {h: [c, o, ln]
                              for h, (c, o, ln) in fresh.items()}])
                committing.append(e)
            if not recs:
                return
            try:
                self._commit_many(recs)
            except BaseException as exc:  # each caller re-raises its own
                for e in committing:
                    e.exc = exc
                return
            _M.incr("group_commit_batches")
            _M.observe("group_commit_blocks", len(recs))

    def delete_block(self, block_id: int) -> list[bytes]:
        """Drop a block's Table-1 row and decref its chunks.  Returns the
        fingerprints whose refcount reached zero (now dead; eligible for
        container compaction)."""
        with self._lock:
            entry = self._blocks.get(block_id)
            if entry is None:
                return []
            dead: list[bytes] = []
            counts: dict[bytes, int] = {}
            for h in entry.hashes:
                counts[h] = counts.get(h, 0) + 1
            for h, n in counts.items():
                loc = self._chunks.get(h)
                if loc and loc.refcount - n <= 0:
                    dead.append(h)
            self._commit([b"del", block_id])
            return dead

    def seal_container(self, container_id: int) -> None:
        """Record that a container rolled over and was compressed
        (DataDeduplicator.java:770-781's LZ4-on-rollover).  The record is
        BUFFERED and rides the next group commit's fsync: sealed-ness is
        self-describing on disk (.sealed vs .raw), so the index copy is
        advisory (compaction planning) and needs no immediate barrier —
        while an inline fsync here, called from inside a hot container
        rollover, measured ~10% of the whole commit path."""
        with self._lock:
            self._pending_recs.append([b"seal", container_id])
            self._apply([b"seal", container_id])

    def record_stripe(self, container_id: int, manifest: dict) -> None:
        """Durably record an EC striping manifest for a sealed container
        (the cold-tier demotion commit point: after this fsync the sealed
        file may be deleted — the manifest + any k stripes reproduce it).
        One WAL record, immediate fsync: unlike seal markers this is NOT
        advisory — losing it orphans remote stripes."""
        with self._lock:
            self._commit([b"stripe", container_id, dict(manifest)])

    def drop_stripe(self, container_id: int) -> None:
        """Forget a container's striping manifest (container deleted, or
        re-replicated back to the hot tier)."""
        with self._lock:
            if container_id in self._stripes:
                self._commit([b"unstripe", container_id])

    def stripe_manifest(self, container_id: int) -> dict | None:
        with self._lock:
            m = self._stripes.get(container_id)
            return dict(m) if m is not None else None

    def stripe_manifests(self) -> dict[int, dict]:
        with self._lock:
            return {cid: dict(m) for cid, m in self._stripes.items()}

    def record_moves(self, moves: dict[bytes, tuple[int, int, int]],
                     dropped_container: int | None = None) -> None:
        """Commit a compaction: chunks relocated to new container positions.
        MUST be called after the new bytes are durably appended and *before*
        the old container file is deleted (see ContainerStore.copy_live)."""
        with self._lock:
            self._commit([b"moved",
                          {h: [c, o, ln] for h, (c, o, ln) in moves.items()}])
            if dropped_container is not None:
                self._commit([b"unseal", dropped_container])

    # --------------------------------------------------------------- lookup

    def get_block(self, block_id: int) -> BlockEntry | None:
        with self._lock:
            e = self._blocks.get(block_id)
            return BlockEntry(e.logical_len, list(e.hashes)) if e else None

    def has_block(self, block_id: int) -> bool:
        with self._lock:
            return block_id in self._blocks

    def block_ids(self) -> list[int]:
        with self._lock:
            return sorted(self._blocks)

    def chunk_location(self, h: bytes) -> ChunkLocation | None:
        with self._lock:
            loc = self._chunks.get(h)
            return dataclasses.replace(loc) if loc else None

    def is_sealed(self, container_id: int) -> bool:
        with self._lock:
            return container_id in self._sealed

    def container_live_bytes(self) -> dict[int, int]:
        """Live (referenced) bytes per container — compaction planning input."""
        with self._lock:
            out: dict[int, int] = {}
            for loc in self._chunks.values():
                out[loc.container_id] = out.get(loc.container_id, 0) + loc.length
            return out

    def live_chunks_in(self, container_id: int) -> dict[bytes, tuple[int, int]]:
        """fingerprint -> (offset, length) for live chunks of one container."""
        with self._lock:
            return {h: (c.offset, c.length) for h, c in self._chunks.items()
                    if c.container_id == container_id}

    def _note_orphan_locked(self, loc) -> None:
        """Attribute one dedup-race loser's appended bytes to its container
        (caller holds ``_lock``); ``loc`` is the loser's declared
        (container_id, offset, length)."""
        cid, _off, ln = loc
        self._orphans[cid] = self._orphans.get(cid, 0) + int(ln)

    def orphan_bytes(self) -> dict[int, int]:
        """container_id -> cumulative dedup-race loser bytes appended since
        startup (advisory, in-memory: restarts fold prior orphans back
        into the generic dead-bytes delta).  The scrubber census subtracts
        this class out of payload-minus-live garbage."""
        with self._lock:
            return dict(self._orphans)

    def stats(self) -> dict:
        with self._lock:
            return {
                "blocks": len(self._blocks),
                "chunks": len(self._chunks),
                "sealed_containers": len(self._sealed),
                "striped_containers": len(self._stripes),
                "logical_bytes": sum(b.logical_len for b in self._blocks.values()),
                "unique_chunk_bytes": sum(c.length for c in self._chunks.values()),
            }

    def accounting(self) -> dict:
        """Reduction-effectiveness snapshot over the live tables
        (reduction/accounting.py's state half): the refcount distribution
        as a power-of-2 histogram {bucket_upper_bound: chunks} — the
        sharing profile the reference's missing "Table #3"
        (DataDeduplicator.java:61-62) would have exposed — plus the exact
        aggregate the cluster dedup ratio is defined by."""
        with self._lock:
            ref_hist: dict[int, int] = {}
            for c in self._chunks.values():
                b = 1 << max(c.refcount - 1, 0).bit_length()
                ref_hist[b] = ref_hist.get(b, 0) + 1
            return {
                "refcount_hist": ref_hist,
                "logical_bytes": sum(b.logical_len
                                     for b in self._blocks.values()),
                "unique_chunk_bytes": sum(c.length
                                          for c in self._chunks.values()),
            }

    # ----------------------------------------------------------- checkpoint

    def checkpoint(self) -> None:
        with self._lock:
            self._checkpoint_locked()

    def _checkpoint_locked(self) -> None:
        snap = {
            "blocks": {bid: [e.logical_len, e.hashes] for bid, e in self._blocks.items()},
            "chunks": {h: [c.container_id, c.offset, c.length, c.refcount]
                       for h, c in self._chunks.items()},
            "sealed": sorted(self._sealed),
            "stripes": {cid: m for cid, m in self._stripes.items()},
            "seq": self._seq,
        }
        tmp = os.path.join(self._dir, CKPT_TMP)
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(snap))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, CKPT_NAME))
        # WAL records <= seq are folded into the checkpoint.  If we crash
        # before the truncate, replay skips them by seqno (idempotent).
        fault_injection.point("index.post_checkpoint")
        wal = getattr(self, "_wal", None)
        if wal is not None:
            wal.truncate(0)
            wal.seek(0)
        else:  # during recovery (no WAL handle yet)
            open(os.path.join(self._dir, WAL_NAME), "wb").close()
        self._ops_since_ckpt = 0

    def close(self) -> None:
        with self._lock:
            if self._pending_recs:
                self._commit_many([])  # flush buffered advisory records
            self._wal.close()
