"""NameNode: the metadata plane.

Re-expression of the reference's NameNode stack — FSNamesystem (namespace +
lease manager, FSNamesystem.java, 8 kLoC), FSDirectory (INode tree),
BlockManager (block->location map, replication scheduling,
BlockManager.java:158), DatanodeManager + HeartbeatManager
(HeartbeatManager.java:44 dead-node detection), NameNodeRpcServer — collapsed
into one clean daemon with the same responsibilities:

- namespace ops (mkdir/create/addBlock/complete/delete/rename/listing)
- per-file **reduction scheme** attribute, chosen at create time: the explicit
  policy that replaces the reference's hardcoded ``compressor`` static
  (DataNode.java:438) and MapReduce-header sniffing (BlockReceiver.java:800-820)
- lease management with expiry recovery (LeaseManager analog)
- block map rebuilt from block reports; never persisted (HDFS invariant)
- heartbeat-driven command delivery: replicate / invalidate
  (DNA_TRANSFER / DNA_INVALIDATE, §3.5 of SURVEY.md)
- durability via EditLog + fsimage (server/editlog.py)

Locking: one namesystem lock (the reference's FSNamesystem global lock) —
correct first, sharded later if metadata ops ever become the bottleneck.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from hdrf_tpu.config import NameNodeConfig
from hdrf_tpu.proto.rpc import RpcServer
from hdrf_tpu.server.editlog import EditLog
from hdrf_tpu.utils import fault_injection, metrics

_M = metrics.registry("namenode")


@dataclass
class FileNode:
    replication: int
    scheme: str
    blocks: list[int] = field(default_factory=list)
    complete: bool = False
    mtime: float = 0.0


@dataclass
class BlockInfo:
    block_id: int
    gen_stamp: int
    length: int  # logical; -1 until the client reports it at complete()
    path: str
    locations: set[str] = field(default_factory=set)  # dn_ids


@dataclass
class DatanodeInfo:
    dn_id: str
    addr: tuple[str, int]  # data-transfer endpoint
    last_heartbeat: float = 0.0
    blocks: set[int] = field(default_factory=set)
    commands: list[dict] = field(default_factory=list)  # queued for next heartbeat
    stats: dict = field(default_factory=dict)


class LeaseManager:
    """File-write leases (LeaseManager analog): one writer per file, renewed
    by client heartbeat, expired leases recovered by the monitor."""

    def __init__(self, expiry_s: float = 60.0):
        self.expiry_s = expiry_s
        self._leases: dict[str, tuple[str, float]] = {}  # path -> (client, deadline)

    def acquire(self, path: str, client: str) -> None:
        holder = self._leases.get(path)
        now = time.monotonic()
        if holder and holder[0] != client and holder[1] > now:
            raise PermissionError(f"{path} leased by {holder[0]}")
        self._leases[path] = (client, now + self.expiry_s)

    def check(self, path: str, client: str) -> None:
        holder = self._leases.get(path)
        if holder is None or holder[0] != client:
            raise PermissionError(f"{client} does not hold the lease on {path}")

    def release(self, path: str, client: str) -> None:
        self.check(path, client)
        del self._leases[path]

    def renew_all(self, client: str) -> None:
        now = time.monotonic()
        for path, (holder, _) in list(self._leases.items()):
            if holder == client:
                self._leases[path] = (client, now + self.expiry_s)

    def expired(self) -> list[str]:
        now = time.monotonic()
        return [p for p, (_, dl) in self._leases.items() if dl <= now]

    def drop(self, path: str) -> None:
        self._leases.pop(path, None)

    def drop_subtree(self, prefix: str) -> None:
        """Release leases on ``prefix`` and everything under it (directory
        delete must not leave stale leases blocking re-creation)."""
        p = prefix.rstrip("/")
        for path in list(self._leases):
            if path == p or path.startswith(p + "/"):
                del self._leases[path]


class NameNode:
    def __init__(self, config: NameNodeConfig | None = None):
        self.config = config or NameNodeConfig()
        self._lock = threading.RLock()  # the FSNamesystem lock analog
        # namespace: nested dict tree; leaves are FileNode
        self._root: dict[str, Any] = {}
        self._blocks: dict[int, BlockInfo] = {}
        self._datanodes: dict[str, DatanodeInfo] = {}
        self._leases = LeaseManager()
        self._pending_repl: dict[int, float] = {}  # block_id -> retry deadline
        self._next_block_id = 1
        self._gen_stamp = 1
        self._editlog = EditLog(self.config.meta_dir,
                                self.config.editlog_checkpoint_every)
        self._load()
        self._rpc = RpcServer(self.config.host, self.config.port, self, "namenode")
        self._monitor_stop = threading.Event()
        self._monitor: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "NameNode":
        self._rpc.start()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="nn-monitor", daemon=True)
        self._monitor.start()
        return self

    def stop(self) -> None:
        self._monitor_stop.set()
        if self._monitor:
            self._monitor.join(timeout=5)
        self._rpc.stop()
        self._editlog.close()

    @property
    def addr(self) -> tuple[str, int]:
        return self._rpc.addr

    # ---------------------------------------------------------- persistence

    def _load(self) -> None:
        snap = self._editlog.load_image()
        if snap is not None:
            self._restore(snap)
        self._editlog.replay(self._apply_tolerant)
        self._editlog.open_for_append(self._snapshot)

    def _apply_tolerant(self, rec: list) -> None:
        """Replay-path apply: a record that no longer applies (e.g. the WAL
        tail diverged because an append failed mid-crash) is skipped with a
        count rather than crash-looping the NameNode on startup."""
        try:
            self._apply(rec)
        except Exception:  # noqa: BLE001 — startup must make progress
            _M.incr("replay_records_skipped")

    def _snapshot(self) -> dict:
        def walk(node: dict) -> dict:
            out = {}
            for name, child in node.items():
                if isinstance(child, FileNode):
                    out[name] = ["f", child.replication, child.scheme,
                                 child.blocks, child.complete, child.mtime]
                else:
                    out[name] = ["d", walk(child)]
            return out

        return {
            "tree": walk(self._root),
            "blocks": {b.block_id: [b.gen_stamp, b.length, b.path]
                       for b in self._blocks.values()},
            "next_block_id": self._next_block_id,
            "gen_stamp": self._gen_stamp,
        }

    def _restore(self, snap: dict) -> None:
        def walk(m: dict) -> dict:
            out: dict[str, Any] = {}
            for name, v in m.items():
                if v[0] == "f":
                    out[name] = FileNode(v[1], v[2], list(v[3]), v[4], v[5])
                else:
                    out[name] = walk(v[1])
            return out

        self._root = walk(snap["tree"])
        self._blocks = {bid: BlockInfo(bid, gs, ln, path)
                        for bid, (gs, ln, path) in snap["blocks"].items()}
        self._next_block_id = snap["next_block_id"]
        self._gen_stamp = snap["gen_stamp"]

    def _apply(self, rec: list) -> None:
        """Apply one edit record (replay path and live path share this)."""
        op = rec[0]
        if op == "mkdir":
            self._mkdir_apply(rec[1])
        elif op == "create":
            _, path, replication, scheme, mtime = rec
            parent, name = self._parent_of(path, create=True)
            parent[name] = FileNode(replication, scheme, mtime=mtime)
        elif op == "add_block":
            _, path, bid, gs = rec
            node = self._file(path)
            node.blocks.append(bid)
            self._blocks[bid] = BlockInfo(bid, gs, -1, path)
            self._next_block_id = max(self._next_block_id, bid + 1)
            self._gen_stamp = max(self._gen_stamp, gs + 1)
        elif op == "abandon_block":
            _, path, bid = rec
            node = self._file(path)
            if bid in node.blocks:
                node.blocks.remove(bid)
            self._blocks.pop(bid, None)
        elif op == "complete":
            _, path, lengths, mtime = rec
            node = self._file(path)
            node.complete = True
            node.mtime = mtime
            for bid, ln in lengths.items():
                if bid in self._blocks:
                    self._blocks[bid].length = ln
        elif op == "delete":
            self._delete_apply(rec[1])
        elif op == "rename":
            self._rename_apply(rec[1], rec[2])

    def _log(self, rec: list) -> None:
        """Apply-then-append: the mutation is validated against live state
        *before* it reaches the WAL, so a rejected op (mkdir over a file,
        rename onto an existing dst, ...) raises to the client without
        leaving a record that would poison every future replay.  Appending
        after a successful apply is safe for single-writer edits: the lock is
        held, and a crash between apply and append merely loses the op (the
        client never got an ack — same contract as FSEditLog.logSync)."""
        self._apply(rec)
        self._editlog.append(rec)

    # ------------------------------------------------------- tree utilities

    @staticmethod
    def _parts(path: str) -> list[str]:
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise ValueError("root path not allowed here")
        return parts

    def _parent_of(self, path: str, create: bool = False) -> tuple[dict, str]:
        parts = self._parts(path)
        node = self._root
        for p in parts[:-1]:
            child = node.get(p)
            if child is None:
                if not create:
                    raise FileNotFoundError(f"parent of {path} does not exist")
                child = node[p] = {}
            if isinstance(child, FileNode):
                raise NotADirectoryError(f"{p} in {path} is a file")
            node = child
        return node, parts[-1]

    def _resolve(self, path: str) -> Any:
        parts = [p for p in path.split("/") if p]
        node: Any = self._root
        for p in parts:
            if isinstance(node, FileNode):
                raise NotADirectoryError(path)
            if p not in node:
                raise FileNotFoundError(path)
            node = node[p]
        return node

    def _file(self, path: str) -> FileNode:
        node = self._resolve(path)
        if not isinstance(node, FileNode):
            raise IsADirectoryError(path)
        return node

    def _mkdir_apply(self, path: str) -> None:
        node = self._root
        for p in self._parts(path):
            child = node.get(p)
            if child is None:
                child = node[p] = {}
            if isinstance(child, FileNode):
                raise FileExistsError(f"{path}: {p} is a file")
            node = child

    def _delete_apply(self, path: str) -> None:
        parent, name = self._parent_of(path)
        node = parent.pop(name, None)
        for fn in self._iter_files(node):
            for bid in fn.blocks:
                info = self._blocks.pop(bid, None)
                if info:
                    for dn_id in info.locations:
                        dn = self._datanodes.get(dn_id)
                        if dn:
                            dn.commands.append({"cmd": "invalidate",
                                                "block_ids": [bid]})
        # in-flight writes anywhere under the deleted path lose their leases
        self._leases.drop_subtree(path)

    def _rename_apply(self, src: str, dst: str) -> None:
        sparent, sname = self._parent_of(src)
        node = sparent[sname]
        dparent, dname = self._parent_of(dst, create=True)
        if dname in dparent:
            raise FileExistsError(dst)
        del sparent[sname]
        dparent[dname] = node
        # fix block back-pointers
        prefix_old, prefix_new = src.rstrip("/"), dst.rstrip("/")
        for info in self._blocks.values():
            if info.path == prefix_old or info.path.startswith(prefix_old + "/"):
                info.path = prefix_new + info.path[len(prefix_old):]

    @staticmethod
    def _iter_files(node: Any):
        if isinstance(node, FileNode):
            yield node
        elif isinstance(node, dict):
            for child in node.values():
                yield from NameNode._iter_files(child)

    # ------------------------------------------------------ client RPC: fs ops

    def rpc_mkdir(self, path: str) -> bool:
        with self._lock:
            self._log(["mkdir", path])
            _M.incr("mkdir")
            return True

    def rpc_create(self, path: str, client: str, replication: int | None = None,
                   scheme: str | None = None) -> dict:
        with self._lock:
            replication = replication or self.config.replication
            scheme = scheme or "direct"
            parent, name = self._parent_of(path, create=True)
            existing = parent.get(name)
            if existing is not None:
                if isinstance(existing, dict):
                    raise IsADirectoryError(path)
                if existing.complete:
                    raise FileExistsError(path)
            self._leases.acquire(path, client)
            if existing is not None:
                # Overwriting an abandoned incomplete file: drop it first so
                # its allocated blocks are invalidated on DNs rather than
                # leaking in the block map forever.
                self._log(["delete", path])
            self._log(["create", path, replication, scheme, time.time()])
            _M.incr("create")
            return {"block_size": self.config.block_size, "scheme": scheme,
                    "replication": replication}

    def rpc_add_block(self, path: str, client: str) -> dict:
        """Allocate the next block + choose target DNs (addBlock RPC ->
        BlockManager placement, DataStreamer.java:1655's nextBlockOutputStream)."""
        with self._lock:
            self._leases.check(path, client)
            node = self._file(path)
            bid, gs = self._next_block_id, self._gen_stamp
            targets = self._choose_targets(node.replication, exclude=set())
            if not targets:
                raise IOError("no datanodes available")
            self._log(["add_block", path, bid, gs])
            _M.incr("add_block")
            return {"block_id": bid, "gen_stamp": gs, "scheme": node.scheme,
                    "targets": [{"dn_id": d.dn_id, "addr": list(d.addr)}
                                for d in targets]}

    def rpc_abandon_block(self, path: str, client: str, block_id: int) -> bool:
        with self._lock:
            self._leases.check(path, client)
            self._log(["abandon_block", path, block_id])
            return True

    def rpc_complete(self, path: str, client: str,
                     block_lengths: dict[int, int]) -> bool:
        with self._lock:
            self._leases.check(path, client)
            self._log(["complete", path, dict(block_lengths), time.time()])
            self._leases.release(path, client)
            _M.incr("complete")
            return True

    def rpc_renew_lease(self, client: str) -> bool:
        with self._lock:
            self._leases.renew_all(client)
            return True

    def rpc_get_block_locations(self, path: str) -> dict:
        with self._lock:
            node = self._file(path)
            blocks = []
            for bid in node.blocks:
                info = self._blocks[bid]
                locs = [{"dn_id": d, "addr": list(self._datanodes[d].addr)}
                        for d in info.locations if d in self._datanodes]
                blocks.append({"block_id": bid, "gen_stamp": info.gen_stamp,
                               "length": info.length, "locations": locs})
            _M.incr("get_block_locations")
            return {"blocks": blocks, "scheme": node.scheme,
                    "length": sum(max(b["length"], 0) for b in blocks),
                    "complete": node.complete}

    def rpc_delete(self, path: str) -> bool:
        with self._lock:
            try:
                self._resolve(path)
            except FileNotFoundError:
                return False
            self._log(["delete", path])
            _M.incr("delete")
            return True

    def rpc_rename(self, src: str, dst: str) -> bool:
        with self._lock:
            self._resolve(src)
            s = "/" + "/".join(self._parts(src))
            d = "/" + "/".join(p for p in dst.split("/") if p)
            if d == s or d.startswith(s + "/"):
                raise ValueError(f"cannot rename {src} into its own subtree {dst}")
            self._log(["rename", src, dst])
            return True

    def rpc_listing(self, path: str) -> list[dict]:
        with self._lock:
            node = self._resolve(path)
            if isinstance(node, FileNode):
                return [self._stat_entry(path.rstrip("/").rsplit("/", 1)[-1], node)]
            return [self._stat_entry(name, child)
                    for name, child in sorted(node.items())]

    def rpc_stat(self, path: str) -> dict:
        with self._lock:
            node = self._resolve(path)
            name = path.rstrip("/").rsplit("/", 1)[-1] or "/"
            return self._stat_entry(name, node)

    def _stat_entry(self, name: str, node: Any) -> dict:
        if isinstance(node, FileNode):
            length = sum(max(self._blocks[b].length, 0) for b in node.blocks
                         if b in self._blocks)
            return {"name": name, "type": "file", "length": length,
                    "replication": node.replication, "scheme": node.scheme,
                    "complete": node.complete, "blocks": len(node.blocks),
                    "mtime": node.mtime}
        return {"name": name, "type": "dir", "children": len(node)}

    # --------------------------------------------------- datanode RPC: control

    def rpc_register_datanode(self, dn_id: str, addr: list) -> dict:
        with self._lock:
            self._datanodes[dn_id] = DatanodeInfo(
                dn_id, (addr[0], addr[1]), last_heartbeat=time.monotonic())
            _M.incr("dn_registered")
            return {"heartbeat_interval_s": self.config.heartbeat_interval_s}

    def rpc_heartbeat(self, dn_id: str, stats: dict | None = None) -> dict:
        with self._lock:
            dn = self._datanodes.get(dn_id)
            if dn is None:
                return {"reregister": True, "commands": []}
            dn.last_heartbeat = time.monotonic()
            dn.stats = stats or {}
            cmds, dn.commands = dn.commands, []
            return {"reregister": False, "commands": cmds}

    def rpc_block_report(self, dn_id: str, blocks: list) -> bool:
        """Full report: authoritative sync of this DN's replica set
        (BlockManager.processReport analog)."""
        with self._lock:
            dn = self._datanodes.get(dn_id)
            if dn is None:
                raise KeyError(f"unregistered datanode {dn_id}")
            reported = set()
            for bid, gs, length in blocks:
                reported.add(bid)
                info = self._blocks.get(bid)
                if info is None:
                    # replica for a deleted file: tell DN to drop it
                    dn.commands.append({"cmd": "invalidate", "block_ids": [bid]})
                    continue
                info.locations.add(dn_id)
                if info.length < 0:
                    info.length = length
            for bid in dn.blocks - reported:
                info = self._blocks.get(bid)
                if info:
                    info.locations.discard(dn_id)
            dn.blocks = reported
            _M.incr("block_reports")
            return True

    def rpc_block_received(self, dn_id: str, block_id: int, length: int) -> bool:
        """Incremental block report on pipeline finalize (IBR analog)."""
        with self._lock:
            dn = self._datanodes.get(dn_id)
            info = self._blocks.get(block_id)
            if dn is None or info is None:
                return False
            dn.blocks.add(block_id)
            info.locations.add(dn_id)
            if info.length < 0:
                info.length = length
            return True

    # ------------------------------------------------------------- admin RPC

    def rpc_datanode_report(self) -> list[dict]:
        with self._lock:
            now = time.monotonic()
            return [{"dn_id": d.dn_id, "addr": list(d.addr),
                     "alive": now - d.last_heartbeat < self.config.dead_node_interval_s,
                     "blocks": len(d.blocks), "stats": d.stats}
                    for d in self._datanodes.values()]

    def rpc_save_namespace(self) -> bool:
        with self._lock:
            self._editlog.checkpoint()
            return True

    def rpc_metrics(self) -> dict:
        return metrics.all_snapshots()

    # ---------------------------------------------------------- block mgmt

    def _choose_targets(self, n: int, exclude: set[str]) -> list[DatanodeInfo]:
        """Placement: random spread over live DNs (BlockPlacementPolicyDefault's
        rack-awareness collapses to uniform random without topology info)."""
        now = time.monotonic()
        live = [d for d in self._datanodes.values()
                if now - d.last_heartbeat < self.config.dead_node_interval_s
                and d.dn_id not in exclude]
        random.shuffle(live)
        return live[:n]

    def _monitor_loop(self) -> None:
        """HeartbeatManager.Monitor + RedundancyMonitor (§3.5): declare dead
        DNs, schedule re-replication, recover expired leases."""
        interval = self.config.heartbeat_interval_s
        while not self._monitor_stop.wait(interval):
            try:
                fault_injection.point("namenode.monitor_tick")
                self._check_dead_nodes()
                self._check_replication()
                self._recover_leases()
            except Exception:  # noqa: BLE001 — monitor must survive
                _M.incr("monitor_errors")

    def _check_dead_nodes(self) -> None:
        with self._lock:
            now = time.monotonic()
            for dn in list(self._datanodes.values()):
                if now - dn.last_heartbeat > self.config.dead_node_interval_s:
                    _M.incr("dn_declared_dead")
                    for bid in dn.blocks:
                        info = self._blocks.get(bid)
                        if info:
                            info.locations.discard(dn.dn_id)
                    del self._datanodes[dn.dn_id]

    def _check_replication(self) -> None:
        with self._lock:
            now = time.monotonic()
            for info in self._blocks.values():
                node = self._try_file(info.path)
                if node is None or not node.complete:
                    continue
                live = {d for d in info.locations if d in self._datanodes}
                deficit = node.replication - len(live)
                if deficit <= 0 or not live:
                    self._pending_repl.pop(info.block_id, None)
                    continue
                # PendingReconstructionBlocks analog: don't re-queue the same
                # block every monitor tick while a transfer is in flight.
                deadline = self._pending_repl.get(info.block_id, 0.0)
                if deadline > now:
                    continue
                targets = self._choose_targets(deficit, exclude=live)
                if targets:
                    src = self._datanodes[next(iter(live))]
                    src.commands.append({
                        "cmd": "replicate", "block_id": info.block_id,
                        "gen_stamp": info.gen_stamp,
                        "targets": [{"dn_id": t.dn_id, "addr": list(t.addr)}
                                    for t in targets]})
                    self._pending_repl[info.block_id] = (
                        now + self.config.pending_replication_timeout_s)
                    _M.incr("replications_scheduled")

    def _recover_leases(self) -> None:
        with self._lock:
            for path in self._leases.expired():
                self._leases.drop(path)
                node = self._try_file(path)
                if node is not None and not node.complete:
                    # finalize with whatever lengths block reports gave us
                    lengths = {b: max(self._blocks[b].length, 0)
                               for b in node.blocks if b in self._blocks}
                    self._log(["complete", path, lengths, time.time()])
                    _M.incr("leases_recovered")

    def _try_file(self, path: str) -> FileNode | None:
        try:
            node = self._resolve(path)
            return node if isinstance(node, FileNode) else None
        except (FileNotFoundError, NotADirectoryError):
            return None
